"""Iteration-level (continuous) decoding over a paged KV pool.

Request-mode serving (serving/scheduler.py default) packs whole requests
into device batches: a sentence admitted mid-decode waits for the
current batch to drain, and the dense per-batch cache makes every row
pay the longest member's decode length. This module turns decode rows
into SLOTS over one shared paged KV pool (ops/pallas/kv_pool.py):

- a sentence JOINS a running decode at any step boundary, claiming a
  slot and enough pages for its own decode cap, and starts at its own
  position 0 while its neighbors are at position 40;
- a finished sentence LEAVES at the step it emits EOS, releasing its
  pages immediately — capacity returns to the admission plane per
  sentence, not per batch;
- each step runs one jitted decode over the occupied slot prefix,
  rounded UP to a ROW BUCKET (ops/pallas/kv_pool.ROW_BUCKETS) so every
  step lands on one of a small closed set of compiled shapes — the TPU
  static-shape compilation model is preserved by bucketing, never by
  dynamic shapes.

This engine is GREEDY (beam 1) — the production high-throughput serving
config (cf. bench_decode's MARIAN_DECBENCH_BEAM=1 "student serving"
note). Beam>1 iteration decoding rides the SAME slot machinery via
copy-on-write page sharing across hypotheses — refcounted full pages,
per-beam partial pages (translator/beam_iteration.py; the server picks
the engine by --beam-size). Cross-request prefix sharing (ISSUE 12,
--prefix-cache) composes with both: an exact source repeat forks
copy-on-write from a live row or replays a completed decode
(translator/prefix_cache.py).

Threading contract: every device-touching method (``admit_and_step``)
runs on the serving scheduler's single device worker thread. The
metrics scrape thread reads only the counters guarded by
``PagedDecodeEngine._lock`` and the pool's own lock.

Determinism: joins are applied in caller order onto the LOWEST free
slot, page claims pop a deterministic free list, idle slots write only
zeros into the reserved trash page — replaying an identical join/evict
schedule yields bitwise-identical outputs (tests/test_iteration.py).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..common import faultpoints as fp
from ..common import jitwit
from ..common import lockdep
from ..common import logging as log
from ..data.vocab import EOS_ID
from ..ops.pallas.kv_pool import (DEFAULT_PAGE_LEN, KVPool, PoolCorruption,
                                  PoolExhausted, ROW_BUCKETS, bucket_rows,
                                  pages_for_tokens)
from .decode_features import RowFeatures
from .prefix_cache import PrefixCache

# continuous pool auditing: with MARIAN_POOL_AUDIT=1 every admit+step
# round ends with a full invariant audit (tests/conftest.py arms it for
# the whole tier-1 run); without it the audit runs only at quiesce
# boundaries and the cheap row-exit leak check stays always-on
ENV_POOL_AUDIT = "MARIAN_POOL_AUDIT"

# fatal join-rejection reasons: the sentence can NEVER be admitted (the
# scheduler fails its request explicitly instead of re-queueing — this
# is what keeps a drained pool from deadlocking the step loop behind an
# unadmittable head-of-line sentence)
FATAL_REASONS = ("src_too_long", "too_large")


@dataclass
class StepResult:
    """One admit+step round on the device worker thread."""
    accepted: List[object] = field(default_factory=list)
    # key -> reason; reasons in FATAL_REASONS are permanent
    rejected: List[Tuple[object, str]] = field(default_factory=list)
    # key -> operator-actionable detail for FATAL rejections (the
    # computed page requirement vs the pool's capacity — ISSUE 11: the
    # error a client sees must tell the operator which knob to turn)
    reject_detail: Dict[object, str] = field(default_factory=dict)
    finished: List[Tuple[object, str]] = field(default_factory=list)
    # per-key decode detail for finished sentences (beam engine: raw /
    # length-normalized scores, hypothesis length — the parity tests
    # and n-best-curious callers read it; greedy leaves it empty)
    finished_info: Dict[object, dict] = field(default_factory=dict)
    # rows evicted MID-DECODE because a lazy COW page claim found the
    # pool dry (beam divergence): retriable by contract — the serving
    # scheduler fails them with RowEvicted (!!SERVER-RETRY)
    pool_evicted: List[object] = field(default_factory=list)
    rows: int = 0                 # active rows this round (before finishes)
    bucket: int = 0               # compiled row bucket the round ran at
    tokens: int = 0               # target tokens consumed this round
    steps: int = 0                # decode steps the round advanced
    # the engine's last install width (halving encode bucket; 0 before
    # any install) — with `bucket` and `steps` it forms the round's
    # steady-state compile key the scheduler reports to obs.PERF
    enc_bucket: int = 0
    device_s: float = 0.0         # admit+step wall on the worker thread
    mid_decode_joins: int = 0     # joins that landed beside running rows
    # per-row lifecycle instants this round (ISSUE 14): (key, name,
    # attrs) tuples — prefix-cache hits/forks, COW events — that the
    # serving scheduler turns into timeline events tagged with the
    # row's trace id (the engine never learns trace ids) and into the
    # #trace reply-metadata row breakdown. Always populated (the reply
    # metadata is tracing-independent); tiny and rare, never per-token.
    row_events: List[Tuple[object, str, dict]] = field(default_factory=list)
    # streaming (ISSUE 16): per-round partial target text for rows whose
    # join meta asked for it — (key, text_so_far, tokens_so_far) for
    # STILL-DECODING rows; a finishing row's last text arrives via
    # ``finished`` as always. The serving scheduler fans these out to
    # #stream: clients between rounds.
    partials: List[Tuple[object, str, int]] = field(default_factory=list)
    # pool page traffic THIS round (deltas of KVPool.stats + the
    # engine's fork-copy count) — the serve.round span attrs and the
    # marian_serving_kv_pool_pages_*_total series read these
    pages_claimed: int = 0
    pages_freed: int = 0
    pages_aliased: int = 0
    pages_copied: int = 0


class _Slot:
    __slots__ = ("key", "tokens", "pos", "cap", "prev", "src_tokens",
                 "expected_refs", "src_key", "feat")

    def __init__(self, key, cap: int, src_tokens: int,
                 expected_refs: int = 0, src_key=None, feat=None):
        self.key = key
        self.tokens: List[int] = []
        self.pos = 0                # next write position
        self.cap = cap              # decode cap (max positions)
        self.prev = 0               # previous token id (0 at pos 0)
        self.src_tokens = src_tokens
        # page REFERENCES this row's exit must give back (cap pages for
        # a cold join; aliased fulls + owned tail for a prefix fork) —
        # the row-exit leak check compares against it
        self.expected_refs = expected_refs
        self.src_key = src_key      # source id tuple (prefix-cache key)
        self.feat = feat            # RowFeatures (decode_features.py)


class PagedDecodeEngine:
    """Slot-based continuous greedy decoder over a paged KV pool."""

    # encode-at-join batch buckets (one compiled encoder shape per entry)
    JOIN_BUCKETS = (1, 2, 4, 8)
    # n-best needs per-hypothesis score bookkeeping (PagedBeamEngine)
    _SUPPORTS_NBEST = False

    def __init__(self, model, params, src_vocab, trg_vocab,
                 max_rows: int = 32,
                 page_len: int = DEFAULT_PAGE_LEN,
                 pool_bytes: int = 0,
                 src_len_cap: int = 64,
                 max_length_cap: int = 256,
                 max_length_factor: float = 3.0,
                 row_buckets: Sequence[int] = ROW_BUCKETS,
                 steps_per_round: int = 1,
                 registry=None,
                 prefix_cache: Optional[PrefixCache] = None,
                 features=None):
        # the annotation is load-bearing beyond documentation: the
        # static callgraph types self.prefix from it, which is what
        # links the engine's claim sites to the cache's adopt/release
        # sites in the ownership graph (ISSUE 15)
        cfg = getattr(model, "cfg", None)
        if cfg is None or getattr(cfg, "decoder_autoreg", "") \
                != "self-attention":
            raise ValueError("iteration-level decoding requires a "
                             "transformer with the self-attention "
                             "autoreg decoder")
        if getattr(cfg, "n_encoders", 1) != 1:
            raise ValueError("iteration-level decoding supports a single "
                             "source stream")
        self.model = model
        self.params = params
        self.src_vocab = src_vocab
        self.trg_vocab = trg_vocab
        self.max_rows = int(max_rows)
        self.page_len = int(page_len)
        self.src_cap = int(src_len_cap)
        self.max_length_cap = int(max_length_cap)
        self.max_length_factor = float(max_length_factor)
        self.row_buckets = tuple(sorted(set(
            min(b, self.max_rows) for b in row_buckets)))
        if self.max_rows > max(row_buckets):
            # slots past the largest compiled bucket would never step
            # (and the beam merge would index past the device output)
            raise ValueError(
                f"max_rows {self.max_rows} exceeds the largest row "
                f"bucket {max(row_buckets)} (extend row_buckets or "
                f"lower --iteration-rows)")
        self.max_pages = pages_for_tokens(self.max_length_cap,
                                          self.page_len)
        # decode steps per round, run as ONE jitted lax.scan: joins are
        # still admitted every round, so admission granularity is
        # steps_per_round steps (default 1 = pure iteration-level).
        # >1 amortizes per-call dispatch/transfer on host-bound
        # backends; a row finishing mid-scan self-feeds until the host
        # cuts at its EOS — those few wasted row-steps are the price of
        # the amortization (docs/DEPLOYMENT.md)
        self.steps_per_round = max(1, int(steps_per_round))

        h, dh, depth = cfg.heads, cfg.dim_head, cfg.dec_depth
        self._dtype = cfg.compute_dtype
        dtype_bytes = jnp.dtype(self._dtype).itemsize
        # bytes one PAGE costs across the whole decoder: K+V, all layers
        self.page_bytes = 2 * depth * h * self.page_len * dh * dtype_bytes
        if pool_bytes and pool_bytes > 0:
            n_pages = 1 + max(1, int(pool_bytes) // self.page_bytes)
        else:
            n_pages = 1 + self._default_pool_pages()
        self.pool = KVPool(n_pages, self.page_len,
                           max_pages_per_row=self.max_pages)

        # device state: model paged state (pools + cross caches) plus
        # the per-slot source mask; owned by the worker thread
        d = cfg.dim_emb
        enc0 = jnp.zeros((self.max_rows, self.src_cap, d), self._dtype)
        mask0 = np.zeros((self.max_rows, self.src_cap), np.float32)
        mask0[:, 0] = 1.0       # idle rows keep one live source position
        self._src_mask = jnp.asarray(mask0)
        self._state = model.start_paged_state(
            params, enc0, self._src_mask, n_pages, self.page_len,
            self.max_pages)

        # host slot bookkeeping (worker thread); the COUNTERS cross to
        # the metrics scrape thread and ride the lock
        self._slots: List[Optional[_Slot]] = [None] * self.max_rows
        self._by_key: Dict[object, int] = {}
        self._lock = lockdep.make_lock("PagedDecodeEngine._lock")
        self._n_active = 0              # guarded-by: _lock
        self._used_tokens = 0           # guarded-by: _lock
        self._ever_stepped = False
        # brownout level 1 (serving/brownout.py): NEW joins claim a
        # scaled-down decode cap so each row costs fewer pages/steps
        # under sustained overload. Written by the brownout thread,
        # read on the worker thread — a single float, no invariant
        # couples it to other state, so it rides no lock.
        self._cap_scale = 1.0
        self._audit_always = os.environ.get(ENV_POOL_AUDIT, "") == "1"
        # engine round counters + last-audit verdict for the /poolz
        # inspector (ISSUE 14): plain ints written on the worker thread,
        # read by the metrics/poolz HTTP threads — hence the lock
        self._counters: Dict[str, int] = {
            "rounds": 0, "joins": 0, "mid_decode_joins": 0,
            "prefix_hits": 0, "forks": 0, "pool_evictions": 0,
            "pages_copied": 0, "audits": 0,
            "audit_failures": 0}            # guarded-by: _lock
        self._last_audit: Optional[dict] = None   # guarded-by: _lock
        # fork-copied pages in the CURRENT round (worker thread only;
        # reset at the top of admit_and_step, folded into res at its end)
        self._round_copied = 0
        self._metrics_declared = False
        # per-row decode-feature plane (ISSUE 16, decode_features.py):
        # None keeps the exact pre-feature compiled step signature
        self.features = features
        if features is not None and features.n_best \
                and not self._SUPPORTS_NBEST:
            raise ValueError("n-best needs beam bookkeeping — the server "
                             "routes it to PagedBeamEngine (any beam "
                             "size)")
        # sampling RNG lane allocator: each admitted row gets the next
        # ordinal, so a replayed join schedule replays its dice
        self._lane_ctr = 0
        # cross-request prefix sharing (--prefix-cache; ISSUE 12):
        # engine-scoped — a hot swap builds a fresh engine with a fresh
        # cache, so stale-version pages are unreachable by construction
        if features is not None and not features.cacheable \
                and prefix_cache is not None:
            log.info("iteration engine: --output-sampling disables the "
                     "prefix cache (sampled decodes must not be "
                     "replayed or forked)")
            prefix_cache = None
        self.prefix = prefix_cache

        self._step_jit: Dict[int, object] = {}
        self._install_jit: Dict[int, object] = {}
        self._fork_jit = None
        # retrace witness (common/jitwit.py, ISSUE 17): every jit
        # object this engine creates is noted under this token, so a
        # REBUILD of an already-noted compile key is caught as a
        # retrace at suite teardown. (jb, w) install shapes are noted
        # on first admission — the install jit's own cache compiles
        # one kernel per shape pair.
        self._jitwit_token = jitwit.new_token()
        self._install_shapes: set = set()    # (jb, w) pairs compiled
        self._enc_w = 0     # last install width: the round's encode
        #                     bucket for steady-state recompile keys
        self._jit_drill_nonce = 0   # jit.closure_vary drill counter

        if registry is not None:
            self._declare_metrics(registry)

    def _default_pool_pages(self) -> int:
        """Unsized-pool page budget (no --kv-pool-bytes): every slot can
        hold a full-cap row, so the pool is never the constraint —
        shrink --kv-pool-bytes to make admission page-bound. Subclasses
        add round-transient headroom on top (the fused beam merge
        preclaims a round's worst-case fresh pages before each scan)."""
        return self.max_rows * self.max_pages

    # -- metrics ------------------------------------------------------------
    def _declare_metrics(self, r) -> None:
        self.m_pool_pages = r.gauge(
            "marian_serving_kv_pool_pages",
            "Paged KV pool size in allocatable pages (page 0 reserved)")
        self.m_pool_pages.set(self.pool.usable_pages)
        self.m_pool_free = r.gauge(
            "marian_serving_kv_pool_pages_free",
            "Paged KV pool pages currently free")
        self.m_pool_free.set_function(self.pool.free_pages)
        self.m_pool_frag = r.gauge(
            "marian_serving_kv_pool_fragmentation_ratio",
            "Internal fragmentation of claimed pages: 1 - written "
            "tokens / (claimed pages x page_len)")
        self.m_pool_frag.set_function(self.fragmentation)
        self.m_active_rows = r.gauge(
            "marian_serving_active_rows",
            "Decode slots occupied by live sentences (iteration mode)")
        self.m_active_rows.set_function(self.active_rows)
        self.m_audits = r.counter(
            "marian_serving_pool_audits_total",
            "Pool invariant audits run (quiesce boundaries; every round "
            "under MARIAN_POOL_AUDIT=1)")
        self.m_audit_failures = r.counter(
            "marian_serving_pool_audit_failures_total",
            "Pool invariant audits that found violations (double-free, "
            "table/claim mismatch, refcount drift, leaked pages, "
            "row-exit leak)")
        # pool occupancy / COW telemetry (ISSUE 14): live gauges the
        # scrape thread samples, plus cumulative page-traffic counters
        # fed per round by admit_and_step. The gauges re-point to the
        # engine actually serving on every install_engine re-declare.
        self.m_pool_occupancy = r.gauge(
            "marian_serving_kv_pool_occupancy_ratio",
            "Claimed pages / allocatable pages of the paged KV pool")
        self.m_pool_occupancy.set_function(self.occupancy)
        self.m_pool_shared = r.gauge(
            "marian_serving_kv_pool_pages_shared",
            "Pages currently COW-aliased (refcount >= 2): held by more "
            "than one hypothesis/row/cache entry")
        self.m_pool_shared.set_function(
            lambda: self.pool.alias_stats()["shared"])
        self.m_pool_refmax = r.gauge(
            "marian_serving_kv_pool_refcount_max",
            "Highest live page refcount (refcount-distribution summary; "
            "1 = no sharing at all right now)")
        self.m_pool_refmax.set_function(
            lambda: self.pool.alias_stats()["max"])
        self.m_pool_alias_ratio = r.gauge(
            "marian_serving_kv_pool_cow_alias_ratio",
            "Fraction of live page-table references that are COW "
            "aliases rather than sole ownership: (refs - live pages) / "
            "refs. 0 = no sharing; rises with beam forks and prefix "
            "hits")
        self.m_pool_alias_ratio.set_function(self.cow_alias_ratio)
        self.m_rounds = r.counter(
            "marian_serving_engine_rounds_total",
            "Admit+step rounds the paged engine ran — each round is "
            "one device dispatch covering --iteration-steps decode "
            "steps (greedy AND fused-merge beam scan; only the "
            "host-merge beam baseline pins rounds to one step)")
        self.m_pages_claimed = r.counter(
            "marian_serving_kv_pool_pages_claimed_total",
            "Fresh pages claimed off the pool free list (cold joins, "
            "lazy COW growth, fork partials)")
        self.m_pages_freed = r.counter(
            "marian_serving_kv_pool_pages_freed_total",
            "Pages returned to the pool free list (row exits, beam "
            "reorders dropping dead lineages, cache evictions)")
        self.m_pages_aliased = r.counter(
            "marian_serving_kv_pool_pages_aliased_total",
            "Copy-on-write references added to already-live pages "
            "(beam forks, prefix hits, reorder shares) — pages served "
            "by aliasing instead of recompute or copy")
        self.m_pages_copied = r.counter(
            "marian_serving_kv_pool_pages_copied_total",
            "Partial pages content-copied by pool_fork_partial (the "
            "one copy a COW fork pays; cow=False replication copies "
            "full histories here too)")
        self.m_bytes_copied = r.counter(
            "marian_serving_kv_pool_bytes_copied_total",
            "Bytes moved by pool_fork_partial copies "
            "(pages_copied x the whole-decoder page cost)")
        self.m_bytes_aliased = r.counter(
            "marian_serving_kv_pool_bytes_aliased_total",
            "Bytes served by COW page aliasing instead of being copied "
            "(pages_aliased x the whole-decoder page cost) — the "
            "data-movement win the reorder/prefix sharing buys")
        self.m_forks = r.counter(
            "marian_serving_cow_forks_total",
            "Copy-on-write forks performed (prefix-cache live forks + "
            "beam-reorder child hypotheses that left their parent's "
            "row)")
        if self.prefix is not None:
            self.prefix._declare_metrics(r)
            m_held = r.gauge(
                "marian_prefix_held_pages",
                "KV pages currently held by prefix-cache entries "
                "(retained decodes an exact repeat replays for free)")
            m_held.set_function(self.prefix.held_pages)
            m_recl = r.gauge(
                "marian_prefix_reclaimable_pages",
                "Pages evicting the whole prefix cache would free "
                "RIGHT NOW (held references with page refcount 1) — "
                "the pressure-relief headroom admission already counts")
            m_recl.set_function(
                lambda: self.prefix.reclaimable_pages(self.pool))
        if self.features is not None \
                and self.features.shortlist_gen is not None:
            # lexical-shortlist series (ISSUE 16): how many rows decode
            # through a sliced output GEMM and how wide their slices are
            # — the operator's check that --shortlist actually shrinks
            # the [rows, vocab] projection (PAPER.md's serving trick)
            self.m_shortlist_rows = r.counter(
                "marian_shortlist_rows_total",
                "Decode rows admitted with a per-row lexical shortlist "
                "(iteration mode)")
            self.m_shortlist_width = r.histogram(
                "marian_shortlist_width_tokens",
                "Per-row shortlist width (the row's true padded index "
                "count — the output GEMM runs at the engine's static K)",
                buckets=(128, 256, 384, 512, 768, 1024, 2048, 4096))
        self._metrics_declared = True

    # -- capacity (any thread) ----------------------------------------------
    def active_rows(self) -> int:
        with self._lock:
            return self._n_active

    def occupancy(self) -> float:
        """Claimed / allocatable pages (any thread)."""
        return self.pool.used_pages() / float(self.pool.usable_pages)

    def cow_alias_ratio(self) -> float:
        """(references - live pages) / references — see the gauge help
        and KVPool.alias_stats (any thread)."""
        st = self.pool.alias_stats()
        return (st["refs"] - st["live"]) / st["refs"] if st["refs"] \
            else 0.0

    def _count(self, name: str, n: int = 1) -> None:
        """Bump one /poolz round counter (worker thread writes, the
        HTTP threads read the dict under the same lock)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def fragmentation(self) -> float:
        used_pages = self.pool.used_pages()
        if used_pages == 0:
            return 0.0
        with self._lock:
            used_tokens = self._used_tokens
        if self.prefix is not None:
            # cache-held pages hold real (reusable) tokens — retention
            # must not read as waste
            used_tokens += self.prefix.held_tokens()
        return max(0.0, 1.0 - used_tokens
                   / float(used_pages * self.page_len))

    def free_pages(self) -> int:
        """Free pages PLUS what evicting the prefix cache would free
        right now — page-priced admission sees relievable pressure, and
        the claim path relieves it before failing (_claim_pages)."""
        free = self.pool.free_pages()
        if self.prefix is not None:
            free += self.prefix.reclaimable_pages(self.pool)
        return free

    def free_slots(self) -> int:
        with self._lock:
            return self.max_rows - self._n_active

    def idle(self) -> bool:
        return self.active_rows() == 0

    def decode_cap(self, n_src_tokens: int) -> int:
        """Static decode cap for a sentence (mirrors BeamSearch's
        max-length-factor rule so both modes price work the same).
        Brownout level >= 1 scales it down for NEW joins — shorter rows
        claim fewer pages and leave sooner (serving/brownout.py)."""
        base = min(self.max_length_cap,
                   max(8, round(self.max_length_factor
                                * max(1, n_src_tokens))))
        return int(max(8, round(base * self._cap_scale)))

    def set_cap_scale(self, scale: float) -> None:
        """Brownout level 1: scale the decode cap of FUTURE joins (rows
        already decoding keep the cap they claimed pages for). Clamped
        so the cap never collapses below the 8-token floor's reach."""
        self._cap_scale = min(1.0, max(0.05, float(scale)))

    def row_progress(self, key) -> Optional[Tuple[int, int]]:
        """(pos, cap) of an active row, or None — the brownout eviction
        policy's 'longest remaining' tiebreak reads this (any thread)."""
        with self._lock:
            slot = self._by_key.get(key)
            if slot is None:
                return None
            s = self._slots[slot]
            return (s.pos, s.cap) if s is not None else None

    def pages_for_text(self, text: str) -> int:
        """Pages one sentence will claim (admission pricing: queue debt
        in PAGES, serving/admission.py). Token estimate only — the join
        re-measures with the real vocab encoding."""
        n_src = len(text.split()) + 1
        return pages_for_tokens(self.decode_cap(n_src), self.page_len)

    # -- the admit + step round (device worker thread only) -----------------
    def admit_and_step(self, joins: Sequence[Tuple[object, str]],
                       evicts: Sequence[object] = ()) -> StepResult:
        """Apply evictions (dead requests), admit what fits, run ONE
        decode step over the occupied slots. Never blocks on pool
        space: a join that does not fit is rejected back to the caller
        (reason ``no_slot``/``no_pages`` = retry later; FATAL_REASONS =
        fail the request)."""
        t0 = time.perf_counter()
        res = StepResult()
        # page-traffic accounting (ISSUE 14): diff the pool's cumulative
        # counters across the round — two dict copies under the pool
        # lock, nothing on the tracer (the zero-overhead guard covers
        # this path with tracing disabled)
        stats0 = self.pool.stats()
        self._round_copied = 0
        # corruption-detection drills (no-ops unless the pool.* catalog
        # points are armed): they corrupt real state so the audit below
        # is proven against the bug classes it claims to catch
        self.pool.chaos_double_free()
        self.pool.chaos_refcount_corrupt()
        self._chaos_table_corrupt()
        for key in evicts:
            self._evict(key)
        rows_before = self.active_rows()
        joiners: List[Tuple[object, List[int], int]] = []
        # joins arrive as (key, text) or (key, text, meta) — the meta
        # dict carries serving-side per-row flags (stream, sid) the
        # engine keys its feature plane off (ISSUE 16); 2-tuples keep
        # every pre-feature caller working unchanged
        for j in joins:
            key, text = j[0], j[1]
            meta = j[2] if len(j) > 2 else None
            why = self._try_claim(key, text, joiners, res.reject_detail,
                                  res=res, meta=meta)
            if why is None:
                res.accepted.append(key)
            else:
                res.rejected.append((key, why))
        if joiners:
            self._install(joiners)
            if rows_before > 0:
                # distinct keys, not joiner rows: a beam-k sentence
                # installs k hypothesis rows but is ONE mid-decode join
                res.mid_decode_joins = len({k for k, _, _ in joiners})
        if self.active_rows() > 0:
            self._step(res)
        if self._audit_always:
            bad = self.audit(context="round")
            if bad:
                # fail the round loudly: the scheduler evicts the
                # round's rows with a retriable error and rebuilds the
                # engine — corrupted page state must never serve
                # another token (docs/ROBUSTNESS.md)
                raise PoolCorruption(
                    "pool audit failed: " + "; ".join(bad[:4]))
        stats1 = self.pool.stats()
        res.pages_claimed = stats1["claimed"] - stats0["claimed"]
        res.pages_freed = stats1["freed"] - stats0["freed"]
        res.pages_aliased = stats1["aliased"] - stats0["aliased"]
        res.pages_copied = self._round_copied
        with self._lock:
            self._counters["rounds"] += 1
            self._counters["joins"] += len(res.accepted)
            self._counters["mid_decode_joins"] += res.mid_decode_joins
            self._counters["pool_evictions"] += len(res.pool_evicted)
            self._counters["pages_copied"] += res.pages_copied
        if self._metrics_declared:
            self.m_rounds.inc()
            if res.pages_claimed:
                self.m_pages_claimed.inc(res.pages_claimed)
            if res.pages_freed:
                self.m_pages_freed.inc(res.pages_freed)
            if res.pages_aliased:
                self.m_pages_aliased.inc(res.pages_aliased)
                self.m_bytes_aliased.inc(res.pages_aliased
                                         * self.page_bytes)
            if res.pages_copied:
                self.m_pages_copied.inc(res.pages_copied)
                self.m_bytes_copied.inc(res.pages_copied
                                        * self.page_bytes)
        res.device_s = time.perf_counter() - t0  # mtlint: ok -- the step's per-token fetch (np.asarray in _step) IS the result fence; this window closes host-side after it
        return res

    def _try_claim(self, key, text: str, joiners: List,
                   detail: Optional[Dict[object, str]] = None,
                   res: Optional[StepResult] = None,
                   meta: Optional[dict] = None) -> Optional[str]:
        plane = self.features
        forced: List[int] = []
        if plane is not None and plane.force_decode:
            # iteration force-decode line convention: source<TAB>prefix
            text, forced = plane.split_forced(text, self.trg_vocab)
        ids = self.src_vocab.encode(text, add_eos=True, inference=True)
        if len(ids) > self.src_cap:
            if detail is not None:
                detail[key] = (f"source encodes to {len(ids)} tokens but "
                               f"the engine's source cap is "
                               f"{self.src_cap} (raise --max-length)")
            return "src_too_long"
        src_key = tuple(int(i) for i in ids)
        if plane is not None:
            # a forced trunk salts the cache/fork key: a constrained
            # prefix is a shareable trunk, but only among requests
            # constrained the SAME way (decode_features.cache_key)
            src_key = plane.cache_key(src_key, forced)
        # cross-request prefix sharing (ISSUE 12): an exact repeat of a
        # COMPLETED decode resolves instantly (greedy decode is
        # deterministic, so the cached tokens are bitwise what a cold
        # decode would emit); a repeat of a sentence decoding RIGHT NOW
        # forks from it copy-on-write below
        if self.prefix is not None and res is not None:
            ent = self.prefix.get(src_key, self.prefix.version)
            if ent is not None:
                res.finished.append((key, ent.text))
                res.row_events.append((key, "prefix.hit",
                                       {"kind": "replay",
                                        "tokens": len(ent.tokens)}))
                self._count("prefix_hits")
                return None
        cap = self.decode_cap(len(ids))
        if forced:
            # the cap must cover the forced trunk plus continuation
            # headroom (dense twin: beam_search pads L to plen + 8)
            if len(forced) + 8 > self.max_length_cap:
                if detail is not None:
                    detail[key] = (
                        f"forced target prefix is {len(forced)} tokens "
                        f"but the engine's decode cap is "
                        f"{self.max_length_cap} (raise --max-length)")
                return "too_large"
            cap = min(self.max_length_cap, max(cap, len(forced) + 8))
        stream = bool(meta.get("stream")) if meta else False
        sid = int(meta.get("sid", 0)) if meta else 0
        feat = None
        if plane is not None:
            feat = plane.row_features(ids, forced=forced,
                                      lane=self._lane_ctr,
                                      stream=stream, sid=sid)
        elif stream or sid:
            feat = RowFeatures(stream=stream, sid=sid)
        n_pages = pages_for_tokens(cap, self.page_len)
        if n_pages > self.pool.max_pages_per_row:
            if detail is not None:
                detail[key] = (
                    f"decode cap {cap} tokens needs {n_pages} KV pages "
                    f"of {self.page_len} tokens but the page table "
                    f"holds {self.pool.max_pages_per_row}/row (raise "
                    f"--kv-page-len or --kv-pool-bytes)")
            return "too_large"
        with self._lock:
            if self._n_active >= self.max_rows:
                return "no_slot"
        if self.prefix is not None:
            forked = self._try_fork(key, src_key, cap, n_pages, len(ids),
                                    res=res, feat=feat)
            if forked is not None:
                if forked:
                    self._row_admitted(feat)
                    return None
                return "no_pages"
            self.prefix.note_miss()
        try:
            pages = self._claim_pages(key, n_pages)
        except PoolExhausted:
            # retriable only if the pool could EVER satisfy it
            if n_pages > self.pool.usable_pages:
                if detail is not None:
                    detail[key] = (
                        f"decode cap {cap} tokens needs {n_pages} KV "
                        f"pages but the whole pool holds only "
                        f"{self.pool.usable_pages} allocatable pages "
                        f"of {self.page_len} tokens (raise "
                        f"--kv-pool-bytes or lower --max-length)")
                return "too_large"
            return "no_pages"
        # lowest free slot (deterministic; keeps the occupied prefix —
        # and with it the compiled row bucket — tight)
        with self._lock:
            slot = next(i for i, s in enumerate(self._slots) if s is None)
            self._slots[slot] = _Slot(key, cap, len(ids),
                                      expected_refs=n_pages,
                                      src_key=src_key, feat=feat)
            self._by_key[key] = slot
            self._n_active += 1
        if self.prefix is not None:
            self.prefix.register_live(src_key, key)
        # page table row on the host mirror; device copy goes with the
        # next step's table upload
        self._table[slot, :] = 0
        self._table[slot, :len(pages)] = pages
        joiners.append((key, ids, slot))
        self._row_admitted(feat)
        return None

    def _row_admitted(self, feat) -> None:
        """Post-admission feature bookkeeping: advance the sampling lane
        allocator (so a replayed join schedule replays its lanes) and
        feed the shortlist series."""
        if self.features is None:
            return
        self._lane_ctr += 1
        if feat is not None and feat.shortlist is not None:
            if hasattr(self, "m_shortlist_rows"):
                self.m_shortlist_rows.inc()
                self.m_shortlist_width.observe(feat.sl_len)

    def _claim_pages(self, key, n: int):  # owns: caller -- the claim joins the engine's slot machinery; _evict gives it back
        """Fresh-page claim with prefix-cache pressure relief: when the
        free list is short, LRU cache entries are evicted (their held
        references dropped) and the claim retried once."""
        try:
            return self.pool.claim(key, n)
        except PoolExhausted:
            if self.prefix is None \
                    or not self.prefix.evict_for_pages(self.pool, n):
                raise
            return self.pool.claim(key, n)

    def _try_fork(self, key, src_key, cap: int, n_pages: int,
                  n_src: int, res: Optional[StepResult] = None,
                  feat=None) -> Optional[bool]:
        """Copy-on-write fork from a LIVE row with the same source:
        alias its full (append-only) pages with refcount++, content-copy
        only its current partial page, copy its cross-attention rows
        slot-to-slot (no encoder forward), and resume at its position.
        Returns True (joined), False (fork viable but pool dry —
        caller defers), or None (no fork source; caller takes the cold
        path)."""
        leader_key = self.prefix.leader(src_key)
        if leader_key is None or leader_key == key:
            return None
        with self._lock:
            slot_l = self._by_key.get(leader_key)
            s_l = self._slots[slot_l] if slot_l is not None else None
            # the leader must have stepped at least once (its encoder
            # rows are installed) and price work identically (a brownout
            # cap change between the two joins vetoes the fork)
            if s_l is None or s_l.pos <= 0 or s_l.cap != cap:
                return None
            pos_l, prev_l, toks_l = s_l.pos, s_l.prev, list(s_l.tokens)
        n_full = pos_l // self.page_len
        has_partial = pos_l % self.page_len != 0
        leader_pages = self.pool.pages_of(leader_key)
        fulls = leader_pages[:n_full]
        own_needed = n_pages - n_full

        def build():  # owns: caller -- a successful fork's references live in the forked row; _evict gives them back
            self.pool.share(key, fulls)
            try:
                return self.pool.claim_extra(key, own_needed)
            except PoolExhausted:
                self.pool.release(key)
                raise
        try:
            own = build()
        except PoolExhausted:
            if not self.prefix.evict_for_pages(self.pool, own_needed):
                return False
            try:
                own = build()
            except PoolExhausted:
                return False
        with self._lock:
            slot = next(i for i, s in enumerate(self._slots) if s is None)
            s = _Slot(key, cap, n_src,
                      expected_refs=n_full + own_needed, src_key=src_key,
                      feat=feat)
            s.tokens = toks_l
            s.pos = pos_l
            s.prev = prev_l
            self._slots[slot] = s
            self._by_key[key] = slot
            self._n_active += 1
            # invariant: _used_tokens == sum of active row positions
            self._used_tokens += pos_l
        self.prefix.register_live(src_key, key)
        row = fulls + own
        self._table[slot, :] = 0
        self._table[slot, :len(row)] = row
        # device half: cross-attn rows + source mask slot copy, plus the
        # partial page's content (pairs of (0,0) are deterministic
        # no-ops, used when the leader sat exactly on a page boundary)
        src_page = leader_pages[n_full] if has_partial else 0
        dst_page = own[0] if has_partial else 0
        if self._fork_jit is None:
            self._fork_jit = self._make_fork()
        self._state, self._src_mask = self._fork_jit(
            self._state, self._src_mask,
            jnp.asarray([slot_l], jnp.int32),
            jnp.asarray([slot], jnp.int32),
            jnp.asarray([src_page], jnp.int32),
            jnp.asarray([dst_page], jnp.int32))
        self.prefix.note_fork(tokens_saved=pos_l, pages_reused=n_full)
        if has_partial:
            self._round_copied += 1
        self._count("forks")
        self._count("prefix_hits")
        if self._metrics_declared:
            self.m_forks.inc()
        if res is not None:
            res.row_events.append((key, "prefix.fork",
                                   {"kind": "live", "pos": pos_l,
                                    "aliased": n_full,
                                    "copied": int(has_partial)}))
        return True

    def _make_fork(self):
        model = self.model
        _, pool_keys, _ = self._state_key_groups()
        k_keys = tuple(sorted(k for k in pool_keys
                              if k.endswith("_pool_k")))

        def fork(state, src_mask, src_slot, dst_slot,
                 src_page, dst_page):
            from ..ops.pallas.kv_pool import pool_fork_partial
            new_state, new_mask = model.fork_paged_rows(
                state, src_mask, src_slot, dst_slot)
            for kk in k_keys:
                vk = kk[:-1] + "v"
                nk, nv = pool_fork_partial(new_state[kk], new_state[vk],
                                           src_page, dst_page)
                new_state[kk] = nk
                new_state[vk] = nv
            return new_state, new_mask

        jitwit.note_compile_key(self._jitwit_token, ("fork",))
        return jax.jit(fork, donate_argnums=(0, 1))

    def _evict(self, key, adopt_text: Optional[str] = None) -> bool:  # owns: callee -- the row exit: releases (or adopts into the prefix cache) what _try_claim acquired
        with self._lock:
            slot = self._by_key.pop(key, None)
            if slot is None:
                return False
            s = self._slots[slot]
            self._slots[slot] = None
            self._n_active -= 1
            self._used_tokens -= s.pos
        if self.prefix is not None and s.src_key is not None:
            self.prefix.unregister_live(s.src_key, key)
        # normal finish with the prefix cache armed: the row's page
        # references TRANSFER to the cache (refcounts unchanged) along
        # with its decode, instead of a release — an exact repeat then
        # replays the decode as a page-table hit (ISSUE 12)
        released = 0
        if adopt_text is not None and self.prefix is not None \
                and s.src_key is not None:
            released = self.prefix.adopt(self.pool, s.src_key, key,
                                         s.tokens, adopt_text)
        if released == 0:
            released = self.pool.release(key)
        # row-exit leak detector (always on — one comparison): the row
        # must give back exactly the page references it held (cap pages
        # cold, aliased fulls + owned tail after a fork); any drift
        # means the claim table and the slot state diverged
        expected = s.expected_refs or pages_for_tokens(s.cap,
                                                       self.page_len)
        if released != expected:
            self._report_audit(
                [f"row exit released {released} page reference(s) for "
                 f"key {key!r}, expected {expected} (cap {s.cap})"],
                context="row-exit")
        self._table[slot, :] = 0
        return True

    # -- pool invariant auditor (ISSUE 11) ----------------------------------
    def audit(self, context: str = "quiesce") -> List[str]:
        """Cross-check free-list / page-table / per-row position
        consistency plus leaked claims; returns violations (empty =
        clean) and reports them (log + timeline event + flight dump +
        counter). Run at every quiesce boundary, and after every round
        under ``MARIAN_POOL_AUDIT=1`` (tier-1 arms it process-wide).

        Called only from threads that own the engine state between
        rounds (the device worker, or the event loop at a quiesce
        boundary with no round in flight) — the snapshots below are
        taken under the engine lock only for the metrics-thread
        counters' sake."""
        with self._lock:
            slots = list(self._slots)
            by_key = dict(self._by_key)
            n_active = self._n_active
            used_tokens = self._used_tokens
        v = self.pool.audit()
        refs = self.pool.refcounts()
        active = [(i, s) for i, s in enumerate(slots) if s is not None]
        if n_active != len(active):
            v.append(f"active-row counter {n_active} != {len(active)} "
                     f"occupied slots")
        pos_sum = sum(s.pos for _, s in active)
        if used_tokens != pos_sum:
            v.append(f"used-token counter {used_tokens} != sum of row "
                     f"positions {pos_sum}")
        table = getattr(self, "_table_np", None)
        for i, s in active:
            if by_key.get(s.key) != i:
                v.append(f"slot {i} key {s.key!r} missing from the "
                         f"key index (maps to {by_key.get(s.key)})")
            if s.pos > s.cap:
                v.append(f"slot {i} position {s.pos} past its decode "
                         f"cap {s.cap}")
            pages = self.pool.pages_of(s.key)
            want = s.expected_refs or pages_for_tokens(s.cap,
                                                       self.page_len)
            if len(pages) != want:
                v.append(f"slot {i} holds {len(pages)} page "
                         f"reference(s), expected {want} (cap {s.cap})")
            if pages:
                # COW write safety (shared with the beam audit): the
                # page this row WRITES — the one holding position pos —
                # must be refcount-1; prefix forks alias only FULL
                # pages, so a shared write target means the fork
                # mis-split full/partial and every aliasing row's KV is
                # being corrupted
                wt = pages[min(s.pos // self.page_len, len(pages) - 1)]
                if refs.get(wt, 0) != 1:
                    v.append(f"slot {i} write-target page {wt} has "
                             f"refcount {refs.get(wt, 0)} (COW safety: "
                             f"partial pages must be exclusive)")
            if table is not None:
                row = table[i]
                if list(row[:len(pages)]) != pages \
                        or any(int(p) != 0 for p in row[len(pages):]):
                    v.append(f"slot {i} page-table row "
                             f"{[int(p) for p in row]} does not match "
                             f"its claim {pages} (table corruption)")
        cache_owners = (set(map(repr, self.prefix.owner_keys()))
                        if self.prefix is not None else set())
        for owner in self.pool.owners():
            if owner in by_key:
                continue
            if self.prefix is not None and self.prefix.owns(owner):
                if repr(owner) not in cache_owners:
                    v.append(f"pool claim for prefix-cache owner "
                             f"{owner!r} matches no cache entry "
                             f"(stale cache claim)")
                continue
            v.append(f"pool claim for {owner!r} has no active row "
                     f"(pages leaked at row exit)")
        self._note_audit(v, context)
        return v

    def _note_audit(self, violations: List[str], context: str) -> None:
        """Record the audit pass into the /poolz counters and the
        last-audit verdict (ISSUE 14), then report failures the usual
        loud way. Shared by both engines' auditors."""
        with self._lock:
            self._counters["audits"] += 1
            self._last_audit = {
                "context": context,
                "clean": not violations,
                "violations": list(violations[:8]),
                "ts": time.time(),
            }
        if hasattr(self, "m_audits"):    # registry-less engines: no series
            self.m_audits.inc()
        if violations:
            self._report_audit(violations, context)

    # -- /poolz live inspector (ISSUE 14) ------------------------------------
    def _slot_owner(self, slot: int, s: "_Slot"):
        """The pool-claim owner of an occupied slot (the beam engine's
        owners are (key, slot) pairs — it overrides this)."""
        return s.key

    @staticmethod
    def _owner_label(owner) -> str:
        """Human/JSON-safe label for a claim owner: serving units carry
        their request's trace id, prefix-cache owners their tag; bare
        keys (library/test callers) fall back to repr. Tenanted owners
        (ISSUE 20) get a ``<tag>/`` prefix — the label-level tenant
        convention fleet/accounting.py re-derives per-tenant page sums
        from, so a dead process's /poolz flight dump stays attributable
        (the shared prefix cache stays untenanted on purpose)."""
        probe = owner
        if isinstance(owner, tuple) and len(owner) == 2:
            probe = owner[0]              # beam (key, slot) pair
        req = getattr(probe, "req", None)
        tenant = getattr(req, "tenant", "") if req is not None \
            else getattr(probe, "tenant", "") or ""
        prefix = f"{tenant}/" if tenant else ""
        tid = getattr(req, "trace_id", "") if req is not None else ""
        if tid:
            base = f"{prefix}trace:{tid}"
            return base if probe is owner else f"{base}#{owner[1]}"
        if isinstance(owner, tuple) and len(owner) == 3 \
                and owner[0] == "prefix":
            return "prefix-cache"
        return (prefix + repr(owner))[:96]

    def pool_state(self) -> dict:
        """JSON-ready snapshot of the whole paged-serving data plane:
        the per-page map (refcount + owning rows/cache entries), the
        per-slot table (trace id, pos, cap, pages held), the engine
        round counters and the last audit verdict — the ``/poolz``
        document and the flight recorder's ``pool`` member. Snapshot
        semantics: each map is taken under its own lock (never nested);
        a round committing mid-snapshot can skew adjacent maps by one
        row, which the auditor (not this inspector) is the consistency
        oracle for."""
        refs = self.pool.refcounts()
        claims = self.pool.claims()
        alias = self.pool.alias_stats()
        stats = self.pool.stats()
        with self._lock:
            slots_snap = list(self._slots)
            counters = dict(self._counters)
            last_audit = dict(self._last_audit) if self._last_audit \
                else None
            n_active = self._n_active
            used_tokens = self._used_tokens
        owners_by_page: Dict[int, List[str]] = {}
        for owner, pages in claims.items():
            label = self._owner_label(owner)
            for p in pages:
                owners_by_page.setdefault(int(p), []).append(label)
        page_map = {
            str(p): {"refs": int(rc),
                     "owners": sorted(owners_by_page.get(p, []))}
            for p, rc in sorted(refs.items())}
        slot_rows = []
        for i, s in enumerate(slots_snap):
            if s is None:
                continue
            owner = self._slot_owner(i, s)
            slot_rows.append({
                "slot": i,
                "owner": self._owner_label(owner),
                "trace_id": getattr(getattr(s.key, "req", None),
                                    "trace_id", ""),
                "pos": int(s.pos),
                "cap": int(s.cap),
                "src_tokens": int(s.src_tokens),
                "pages": [int(p) for p in self.pool.pages_of(owner)],
            })
        state = {
            "enabled": True,
            "engine": type(self).__name__,
            "pool": {
                "n_pages": self.pool.n_pages,
                "usable_pages": self.pool.usable_pages,
                "free_pages": self.pool.free_pages(),
                "used_pages": self.pool.used_pages(),
                "occupancy": round(self.occupancy(), 4),
                "page_len": self.page_len,
                "page_bytes": self.page_bytes,
                "max_pages_per_row": self.pool.max_pages_per_row,
                "live_pages": alias["live"],
                "shared_pages": alias["shared"],
                "refs": alias["refs"],
                "refcount_max": alias["max"],
                "cow_alias_ratio": round(self.cow_alias_ratio(), 4),
                "traffic": stats,
            },
            "pages": page_map,
            "rows": {
                "active": n_active,
                "max_rows": self.max_rows,
                "used_tokens": used_tokens,
                "fragmentation": round(self.fragmentation(), 4),
                "slots": slot_rows,
            },
            "counters": counters,
            "last_audit": last_audit,
        }
        if self.prefix is not None:
            state["prefix_cache"] = {
                "entries": self.prefix.entries(),
                "held_tokens": self.prefix.held_tokens(),
                "held_pages": self.prefix.held_pages(),
                "reclaimable_pages":
                    self.prefix.reclaimable_pages(self.pool),
            }
        return state

    def _report_audit(self, violations: List[str], context: str) -> None:
        """One audit failure: loud log, timeline event, flight dump
        naming the fault, counter — the post-mortem must show WHAT was
        corrupted, not just that a round failed."""
        log.error("POOL AUDIT FAILED ({}): {} violation(s): {}", context,
                  len(violations), "; ".join(violations[:4]))
        self._count("audit_failures")
        if hasattr(self, "m_audit_failures"):
            self.m_audit_failures.inc()
        obs.event("pool.audit_failed", context=context,
                  violations=list(violations[:8]))
        obs.FLIGHT.trip_async(
            "pool-audit",
            detail=f"{context}: " + "; ".join(violations[:4]))

    def _chaos_table_corrupt(self) -> None:
        """``pool.table_corrupt`` detection drill (see
        KVPool.chaos_double_free): an armed 'fail' redirects one active
        row's first page-table entry to the trash page while its claim
        still names the real page — the audit's table/claim cross-check
        must catch exactly this."""
        try:
            fp.fault_point("pool.table_corrupt")
        except fp.InjectedFault:
            with self._lock:
                slot = next((i for i, s in enumerate(self._slots)
                             if s is not None), None)
            if slot is not None:
                self._table[slot, 0] = 0

    # host mirrors (worker thread only): allocated lazily so __init__
    # stays importable without numpy churn
    @property
    def _table(self) -> np.ndarray:
        t = getattr(self, "_table_np", None)
        if t is None:
            t = np.zeros((self.max_rows, self.max_pages), np.int32)
            self._table_np = t
        return t

    def _install(self, joiners: List[Tuple[object, List[int], int]]) -> None:
        """Encode the joiners (one bucketed device call) and scatter
        their cross-attention K/V + source masks into their slots. The
        encode runs at the chunk's own LENGTH BUCKET, not the engine's
        src_cap — a 5-token sentence must not pay a max-length-wide
        encoder forward at every join (the cross K/V rows are zero-
        padded to src_cap at scatter time; padded positions are masked,
        so the decode is unchanged)."""
        jb = next((b for b in self.JOIN_BUCKETS if b >= len(joiners)),
                  self.JOIN_BUCKETS[-1])
        for base in range(0, len(joiners), jb):
            chunk = joiners[base:base + jb]
            # halving widths only (src_cap, /2, /4, ...): a handful of
            # compiled encode shapes per join bucket, not one per
            # length bucket — the same closed-shape-set discipline as
            # ROW_BUCKETS (each extra shape is a multi-second inline
            # jit the first join of that shape pays)
            need = max(len(ids) for _, ids, _ in chunk)
            w = self.src_cap
            while w // 2 >= need and w // 2 >= 8:
                w //= 2
            ids_np = np.zeros((jb, w), np.int32)
            mask_np = np.zeros((jb, self.src_cap), np.float32)
            slot_np = np.zeros((jb,), np.int32)
            for i in range(jb):
                # padding rows duplicate joiner 0: their writes land on
                # the same slot with identical content (deterministic)
                key, ids, slot = chunk[min(i, len(chunk) - 1)]
                ids_np[i, :len(ids)] = ids
                mask_np[i, :len(ids)] = 1.0
                slot_np[i] = slot
            fn = self._install_jit.get(0)
            if fn is None:
                # one jit object; its own cache specializes per
                # (jb, w) shape pair
                fn = self._make_install()
                self._install_jit[0] = fn
            if (jb, w) not in self._install_shapes:
                self._install_shapes.add((jb, w))
                jitwit.note_compile_key(
                    self._jitwit_token, ("install", jb, w),
                    domains=(("JOIN_BUCKETS", jb), ("HALVING", w)))
            self._enc_w = w
            self._state, self._src_mask = fn(
                self._state, self._src_mask, self.params,
                jnp.asarray(ids_np), jnp.asarray(mask_np),
                jnp.asarray(slot_np))

    def _state_key_groups(self):
        """Static key classification, computed OUTSIDE the jitted
        closures (their bodies must stay free of Python conditionals);
        the contract lives in ops/pallas/kv_pool.state_key_groups,
        shared with greedy_decode_paged's comparator."""
        from ..ops.pallas.kv_pool import state_key_groups
        return state_key_groups(self._state)

    def _make_install(self):
        model = self.model
        row_keys, _, _ = self._state_key_groups()

        def install(state, src_mask, params, ids, mask, slot_idx):
            # ids arrive at the chunk's length bucket w <= src_cap;
            # mask at full src_cap width (zeros past w)
            w = ids.shape[1]
            enc = model.encode_for_decode(params, ids, mask[:, :w])
            # want_alignment=True forces the unrolled cross-K/V layout,
            # matching the paged state's keys; the tiny dense self
            # caches it allocates are simply not copied
            st = model.start_state(params, enc, mask[:, :w], 1,
                                   want_alignment=True)
            new_state = dict(state)
            for k in row_keys:
                v = st[k].astype(state[k].dtype)
                # zero-pad the source axis out to src_cap: the padded
                # positions are mask-dead, so attention never reads
                # them (deterministic zeros, like the trash page).
                # pad is SHAPE arithmetic (static at trace time); a
                # 0-width pad is a no-op
                pad = state[k].shape[-2] - v.shape[-2]
                v = jnp.pad(v, [(0, 0)] * (v.ndim - 2)
                            + [(0, pad), (0, 0)])
                new_state[k] = state[k].at[slot_idx].set(v)
            new_mask = src_mask.at[slot_idx].set(
                mask.astype(src_mask.dtype))
            return new_state, new_mask

        return jax.jit(install, donate_argnums=(0, 1))

    # buckets: ROW_BUCKETS
    def _make_step(self, rb: int):
        model = self.model
        k_steps = self.steps_per_round
        row_keys, pool_keys, whole_keys = self._state_key_groups()
        # feature plane (ISSUE 16): which per-row extras this engine's
        # compiled step takes is STATIC — a plane-less engine keeps the
        # exact pre-feature jit signature and computation
        plane = self.features
        has_sl = plane is not None and plane.shortlist_gen is not None
        sampling = tuple(plane.sampling) if plane is not None else ()
        has_force = plane is not None and plane.force_decode
        temp = max(float(sampling[-1]), 1e-6) if sampling else 1.0
        topn = int(sampling[1]) if sampling and sampling[0] == "topk" \
            else 0
        seed = int(plane.seed) if plane is not None else 0
        from .beam_search import NEG_INF
        # the jit.closure_vary drill's varying closure constant: 0 in
        # real runs (and folded away); under the armed faultpoint the
        # nonce changes per rebuild, making each rebuilt step a
        # genuinely different traced program — the retrace the witness
        # must catch
        drill_nonce = self._jit_drill_nonce
        jitwit.note_compile_key(self._jitwit_token,
                                ("step", rb, k_steps),
                                domains=(("ROW_BUCKETS", rb),))

        def step(state, src_mask, params, prev, pos, table, *extras):
            # row-indexed leaves run at the bucket prefix; pools and
            # beam-invariant leaves (lsh) stay whole
            sub = {k: state[k][:rb] for k in row_keys}
            for k in whole_keys:
                sub[k] = state[k]
            sm = src_mask[:rb]
            # positional extras, in feature order (host side: _step)
            it = iter(extras)
            sl = next(it) if has_sl else None          # [rb, K] full ids
            sl_len = next(it) if has_sl else None      # [rb] true width
            lane = next(it) if sampling else None      # [rb] RNG lane
            ctr = next(it) if sampling else None       # [rb] step counter
            forced = next(it) if has_force else None   # [rb, k_steps]

            def body(carry, j):
                pools, prev_t, pos_t = carry
                st = dict(sub)
                st.update(pools)
                st["pos"] = pos_t
                st["page_table"] = table
                logits, new_sub = model.step(params, st, prev_t, sm,
                                             shortlist=sl)
                if has_sl:
                    # coords past the row's true (dense-padded) width
                    # are engine padding, not the dense twin's — mask
                    # them out of the argmax/softmax
                    coords = jnp.arange(logits.shape[-1])[None, :]
                    logits = jnp.where(coords < sl_len[:, None],
                                       logits, NEG_INF)
                if sampling:
                    # gumbel-max over logp/temperature, one folded RNG
                    # lane per row (dense twin: beam_search's sampled
                    # top-k; lanes replace the per-batch call counter)
                    lp = jax.nn.log_softmax(
                        logits.astype(jnp.float32), axis=-1)
                    slp = lp / temp
                    if topn:
                        kth = jax.lax.top_k(slp, topn)[0][..., -1:]
                        slp = jnp.where(slp < kth, NEG_INF, slp)
                    keys = jax.vmap(lambda l, c: jax.random.fold_in(
                        jax.random.fold_in(jax.random.key(seed), l),
                        c))(lane, ctr + j)
                    g = jax.vmap(lambda kk: jax.random.gumbel(
                        kk, slp.shape[-1:], jnp.float32))(keys)
                    nxt = jnp.argmax(slp + g, axis=-1).astype(jnp.int32)
                else:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                if has_sl:
                    # shortlist coords → full-vocab ids ON DEVICE: the
                    # next scan step embeds this token, so the map-back
                    # cannot wait for the host
                    nxt = jnp.take_along_axis(
                        sl, nxt[:, None], axis=1)[:, 0]
                if has_force:
                    f = forced[:, j]
                    nxt = jnp.where(f >= 0, f, nxt)
                new_pools = {k: new_sub[k] for k in pool_keys}
                return (new_pools, nxt[:, None], pos_t + 1), nxt

            init = ({k: state[k] for k in pool_keys}, prev,
                    pos + drill_nonce - drill_nonce)
            (pools, _, _), toks = jax.lax.scan(
                body, init, jnp.arange(k_steps))
            new_state = dict(state)
            new_state.update(pools)
            return toks, new_state          # toks [k_steps, rb]

        return jax.jit(step, donate_argnums=(0,))

    def _feature_args(self, rb: int) -> Tuple[object, ...]:
        """Per-row feature arrays for the compiled step, in the extras
        order _make_step unpacks. Idle rows get neutral values (full
        width, lane 0, unconstrained) — their outputs are discarded."""
        plane = self.features
        if plane is None:
            return ()
        extras: List[object] = []
        if plane.shortlist_gen is not None:
            k = plane.k_static
            sl_np = np.zeros((rb, k), np.int32)
            len_np = np.full((rb,), k, np.int32)
            for i in range(rb):
                s = self._slots[i]
                if s is not None and s.feat is not None \
                        and s.feat.shortlist is not None:
                    sl_np[i, :] = s.feat.shortlist
                    len_np[i] = s.feat.sl_len
            extras += [jnp.asarray(sl_np), jnp.asarray(len_np)]
        if plane.sampling:
            lane_np = np.zeros((rb,), np.int32)
            ctr_np = np.zeros((rb,), np.int32)
            for i in range(rb):
                s = self._slots[i]
                if s is not None and s.feat is not None:
                    lane_np[i] = s.feat.lane
                    ctr_np[i] = s.pos
            extras += [jnp.asarray(lane_np), jnp.asarray(ctr_np)]
        if plane.force_decode:
            forced_np = np.full((rb, self.steps_per_round), -1, np.int32)
            for i in range(rb):
                s = self._slots[i]
                if s is not None and s.feat is not None and s.feat.forced:
                    for j in range(self.steps_per_round):
                        forced_np[i, j] = s.feat.forced_at(s.pos + j)
            extras.append(jnp.asarray(forced_np))
        return tuple(extras)

    def _step(self, res: StepResult) -> None:
        # the occupied prefix, rounded up to a compiled row bucket
        top = max(i for i, s in enumerate(self._slots) if s is not None)
        rb = bucket_rows(top + 1, self.row_buckets)
        pos_np = np.full((rb,), -1, np.int32)
        prev_np = np.zeros((rb, 1), np.int32)
        for i in range(rb):
            s = self._slots[i]
            if s is not None:
                pos_np[i] = s.pos
                prev_np[i, 0] = s.prev
        # seeded retrace drill (jit.closure_vary): discard the cached
        # step jit and rebuild it around a varying closure constant —
        # a REAL retrace+recompile of an already-noted key, which the
        # jitwit must flag (tests/test_jitwit.py)
        try:
            fp.fault_point("jit.closure_vary")
        except fp.InjectedFault:
            self._jit_drill_nonce += 1
            self._step_jit.pop(rb, None)
        fn = self._step_jit.get(rb)
        if fn is None:
            fn = self._make_step(rb)
            self._step_jit[rb] = fn
        toks_dev, self._state = fn(
            self._state, self._src_mask, self.params,
            jnp.asarray(prev_np), jnp.asarray(pos_np),
            jnp.asarray(self._table[:rb]), *self._feature_args(rb))
        # the per-round host sync IS the design: the join/evict schedule
        # runs on the host between rounds (the serving scheduler's
        # iteration loop), so each round's tokens must land host-side
        toks = np.asarray(toks_dev)  # mtlint: ok -- iteration-level decode syncs once per round by design; admission runs host-side between rounds
        self._ever_stepped = True
        k_steps = toks.shape[0]
        emitted = 0
        consumed = 0
        finishes: List[_Slot] = []
        for i in range(rb):
            s = self._slots[i]
            if s is None:
                continue
            emitted += 1
            done = False
            for j in range(k_steps):
                tok = int(toks[j, i])
                s.pos += 1
                s.prev = tok
                consumed += 1
                done = tok == EOS_ID or s.pos >= s.cap
                if tok != EOS_ID:
                    s.tokens.append(tok)
                if done:
                    # a row finishing mid-scan self-fed to the end of
                    # the round on device; the host cuts HERE — the
                    # overshoot tokens are discarded and its cache
                    # positions past the cut are never read again
                    finishes.append(s)
                    break
        # ONE locked add per round (not per token — this loop runs on
        # the device-worker hot path against the metrics scrape
        # thread), and it must land BEFORE the evictions below subtract
        # each finished slot's full s.pos: the invariant is
        # _used_tokens == sum(s.pos) over active slots
        with self._lock:
            self._used_tokens += consumed
        for s in finishes:
            text = self.trg_vocab.decode(s.tokens, ignore_eos=True)
            res.finished.append((s.key, text))
            self._evict(s.key, adopt_text=text)
        # streaming rows still decoding emit their text-so-far each
        # round (#stream:, ISSUE 16); finishing rows already delivered
        # their final text above
        for i in range(rb):
            s = self._slots[i]
            if s is not None and s.feat is not None and s.feat.stream:
                res.partials.append(
                    (s.key,
                     self.trg_vocab.decode(s.tokens, ignore_eos=True),
                     s.pos))
        res.rows = emitted
        res.bucket = rb
        res.tokens = consumed
        res.steps += k_steps
        res.enc_bucket = self._enc_w

    # -- direct (non-serving) decoding: tests, benches, warmup smoke --------
    def decode_texts(self, texts: Sequence[str]) -> List[str]:
        """Decode a list of sentences to completion through the slot
        machinery (joins as capacity frees up) — the library-call
        equivalent of the serving loop, used by tests and bench A/Bs."""
        pending = list(enumerate(texts))
        out: Dict[int, str] = {}
        guard = 0
        while pending or not self.idle():
            joins = []
            while pending and len(joins) < self.max_rows:
                joins.append(pending[0])
                pending.pop(0)
            res = self.admit_and_step(joins)
            for key, why in res.rejected:
                if why in FATAL_REASONS:
                    raise ValueError(
                        f"sentence {key} rejected: {why}")
                pending.insert(0, (key, texts[key]))
            for key in res.pool_evicted:
                # serving retries these against the (healthy) engine
                # after the pressure passes; the library call does too
                pending.insert(0, (key, texts[key]))
            for key, text in res.finished:
                out[key] = text
            guard += 1
            if guard > 100000:
                raise RuntimeError("iteration decode failed to converge")
        return [out[i] for i in range(len(texts))]

    def encode_widths(self) -> Tuple[int, ...]:
        """The halving encode-width chain _install draws from:
        src_cap, /2, /4, ... down to 8 — the engine's full encode
        bucket table (descending)."""
        widths = []
        w = self.src_cap
        while True:
            widths.append(w)
            if w // 2 < 8:
                break
            w //= 2
        return tuple(widths)

    def warm_grid(self) -> List[Tuple[int, int, int, float]]:
        """Drive the engine's FULL compile-key grid off the serving
        path (lifecycle warmup, ISSUE 17 satellite): every row bucket
        at the narrowest width, then every encode width at one row —
        after this, steady-state traffic can reach no step or install
        shape that is not already compiled (the closed-shape-set
        claim, asserted by tests/test_iteration.py's jitwit strict
        window). Returns (row_bucket, encode_width, steps, seconds)
        rows for each driven decode; the lifecycle layer folds them
        into PERF's warm ledger under the round-key vocabulary."""
        rows: List[Tuple[int, int, int, float]] = []
        # joiner counts that reach every runtime-reachable bucket: each
        # row bucket as an active-row count (step grid) and each join
        # bucket clamped to capacity (install grid) — the two jit caches
        # key independently, so the count × width double loop closes
        # BOTH tables
        counts = sorted(set(self.row_buckets)
                        | {min(jb, self.max_rows)
                           for jb in self.JOIN_BUCKETS})
        for w in self.encode_widths():
            # enough source tokens that the halving loop stops at w
            # (> w/2), within the engine's source cap
            n_words = max(1, min(w // 2, self.src_cap - 2))
            text = " ".join(["a"] * n_words)
            for n in counts:
                t0 = time.perf_counter()
                self.decode_texts([text] * n)
                rows.append((bucket_rows(n, self.row_buckets),
                             self._enc_w, self.steps_per_round,
                             time.perf_counter() - t0))  # mtlint: ok -- decode_texts returns host strings: every round already synced, the window is wall-clock warmup cost by design
        return rows


class EngineExecutor:
    """The lifecycle plane's executor shape for iteration mode
    (ISSUE 11): a warmed candidate is a whole PagedDecodeEngine (model +
    params + its own device-side page pool), not a ``translate_lines``
    closure. Callable so ``warm_executor``'s golden smoke drives the
    engine's real install/step jits off the serving path; ``.engine`` is
    what the quiesce protocol re-points the scheduler at
    (SwapController._repoint)."""

    def __init__(self, engine: PagedDecodeEngine):
        self.engine = engine

    def __call__(self, lines: List[str]) -> List[str]:
        return self.engine.decode_texts(lines)
