"""The per-row decode-feature plane for paged iteration serving (ISSUE 16).

Request-mode decoding (beam_search.py) ships the full Marian decode
surface — lexical shortlist, output sampling, n-best, force-decode —
as PER-BATCH state: one shortlist per device batch, one sample key per
search, one prefix matrix per dispatch. Iteration mode has no batches:
rows join and leave a resident decode mid-flight, so every one of those
features has to become PER-ROW state that rides in engine slots and is
indexed into the compiled step alongside pos/prev/page_table.

This module is that state:

  FeaturePlane  — engine-wide configuration, parsed once from the same
                  server options the dense path reads (--shortlist,
                  --output-sampling, --n-best, --force-decode), so a
                  flag means the same thing on both paths.  Validates
                  the composition rules up front (see DECODE_SURFACE in
                  server.py for the serving-side table).
  RowFeatures   — one row's slice of the plane, built at JOIN: the
                  row's shortlist index set (dense twin: the per-batch
                  union `beam_search` slices the output GEMM with),
                  its sampling RNG lane, and its forced target trunk.

Parity contract with the dense twin, feature by feature:

  shortlist    The row's index set is EXACTLY what the dense generator
               produces for a single-sentence batch: sorted unique
               union, EOS-padded to a multiple of k_multiple
               (data/shortlist.py).  The engine pads every row to one
               static K (so the compiled step has one shape) and masks
               the coords past the row's true padded length to NEG_INF
               *before* the (log_)softmax: exp(NEG_INF - max)
               underflows to exact 0.0 in f32, so the normalizer — and
               therefore every live coord's logp — is bitwise the
               dense value.  Dense keeps its own EOS-pad duplicates
               live inside its padded length, so ours stay live too.
  sampling     Dense samples gumbel-max over logp/temperature with one
               folded key per batch step.  Rows in an iteration engine
               have no common step clock, so each row gets an RNG
               *lane*: fold_in(fold_in(key(seed), lane), step) where
               lane is the row's join ordinal.  Fixed seed + same join
               schedule ⇒ identical output (the replay pin); two
               identical requests in one engine sample differently
               (distinct lanes), exactly as two dense batches do
               (per-batch call counter).
  n-best       Collected from the beam engine's existing hypothesis
               bookkeeping and formatted through the SAME
               OutputPrinter the dense driver uses — the n-best block
               is byte-identical to request mode's.
  force-decode The forced trunk masks logp to NEG_INF everywhere but
               the forced token, which keeps its TRUE logp (dense:
               beam_search's prefix gate) — scores of a forced decode
               match the dense run.  A forced trunk is appended to the
               prefix-cache key (prefix_cache.py is key-agnostic), so
               repeated CAT/post-editing prefixes become COW forks and
               exact replays, not conflicts.

Composition rules (mirroring the dense path's refusals):
  - shortlist + force-decode is refused: forced ids are full-vocab,
    shortlist logits are not (beam_search.py refuses the same pair).
  - sampling disables the prefix cache for the engine: a sampled decode
    is not a function of the source, so replaying or forking it would
    serve another request's dice roll as a cached "translation".
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..data.shortlist import parse_shortlist_options
from .beam_search import _parse_sampling
from .output_collector import OutputPrinter


class RowFeatures:
    """One decode row's feature state, built at JOIN, carried in the
    engine slot beside pos/cap/tokens."""

    __slots__ = ("shortlist", "sl_len", "forced", "lane", "stream", "sid")

    def __init__(self, shortlist: Optional[np.ndarray] = None,
                 sl_len: int = 0, forced: Optional[List[int]] = None,
                 lane: int = 0, stream: bool = False, sid: int = 0):
        self.shortlist = shortlist   # [k_static] int32 full-vocab ids
        self.sl_len = sl_len         # the row's TRUE padded length (dense K)
        self.forced = forced or []   # forced target trunk (full-vocab ids)
        self.lane = lane             # sampling RNG lane (join ordinal)
        self.stream = stream         # scheduler wants per-round partials
        self.sid = sid               # request-local sentence id (n-best)

    def forced_at(self, pos: int) -> int:
        """Forced token at target position pos, -1 past the trunk."""
        return self.forced[pos] if pos < len(self.forced) else -1


class FeaturePlane:
    """Engine-wide decode-feature configuration + per-row state factory.

    Constructed once where the engine is built (server._engine_for, or a
    test) from the same options namespace the dense Translate driver
    reads; `row_features` is then called at every JOIN.
    """

    def __init__(self, shortlist_gen=None, sampling: tuple = (),
                 seed: int = 1234, n_best: bool = False,
                 force_decode: bool = False, k_static: int = 1024,
                 printer: Optional[OutputPrinter] = None):
        if shortlist_gen is not None and force_decode:
            # dense twin refuses the same pair (beam_search.search_async:
            # prefix ids are full-vocab, shortlist logits are not)
            raise ValueError("--shortlist does not compose with "
                             "--force-decode: forced prefix ids are "
                             "full-vocab, shortlisted logits are not")
        self.shortlist_gen = shortlist_gen
        self.sampling = tuple(sampling or ())
        self.seed = int(seed)
        self.n_best = bool(n_best)
        self.force_decode = bool(force_decode)
        self.printer = printer
        if self.n_best and self.printer is None:
            raise ValueError("n_best FeaturePlane needs an OutputPrinter "
                             "(use FeaturePlane.from_options)")
        # ONE static K for the compiled step. Rows pad up to it with EOS
        # (masked past their true length), rows whose union exceeds it
        # are truncated — same escape hatch as the generator's max_k.
        if shortlist_gen is not None:
            mult = max(1, int(getattr(shortlist_gen, "k_multiple", 128)))
            self.k_static = max(mult, -(-int(k_static) // mult) * mult)
        else:
            self.k_static = 0

    # ---------------------------------------------------------- options
    @classmethod
    def from_options(cls, options, src_vocab, trg_vocab,
                     k_static: int = 1024) -> Optional["FeaturePlane"]:
        """Build the plane from a server/translator options namespace.
        Returns None when no decode-surface feature is on, so engines
        keep their exact pre-feature compiled step."""
        gen = parse_shortlist_options(
            options.get("shortlist", []) or [], src_vocab, trg_vocab)
        sampling = _parse_sampling(options.get("output-sampling", None))
        n_best = bool(options.get("n-best", False))
        force = bool(options.get("force-decode", False))
        if gen is None and not sampling and not n_best and not force:
            return None
        # same default-seed convention as BeamSearch
        seed = int(options.get("seed", 0) or 0) or 1234
        printer = OutputPrinter(options, trg_vocab) if n_best else None
        return cls(shortlist_gen=gen, sampling=sampling, seed=seed,
                   n_best=n_best, force_decode=force, k_static=k_static,
                   printer=printer)

    # ------------------------------------------------------------- rows
    def split_forced(self, text: str, trg_vocab) -> Tuple[str, List[int]]:
        """Split one request line into (source, forced target trunk).

        Iteration serving's force-decode line convention is
        ``source<TAB>target-prefix`` — the wire twin of the dense
        driver's two --input files (source + prefix, translator.py).
        No TAB (or an empty prefix) means unconstrained; the prefix is
        encoded WITHOUT EOS so the hypothesis continues past it.
        """
        if not self.force_decode or "\t" not in text:
            return text, []
        src, _, pfx = text.partition("\t")
        if not pfx.strip():
            return src, []
        return src, [int(t) for t in trg_vocab.encode(pfx, add_eos=False)]

    def row_shortlist(self, src_ids: Sequence[int]
                      ) -> Tuple[Optional[np.ndarray], int]:
        """The row's shortlist: dense single-sentence union, EOS-padded
        to its dense K (the row's live length), then to k_static."""
        if self.shortlist_gen is None:
            return None, 0
        sl = self.shortlist_gen.generate(
            np.unique(np.asarray(src_ids, np.int32)))  # mtlint: ok -- join-time host math over python int ids, no device array in sight
        idx = np.asarray(sl.indices, np.int32)  # mtlint: ok -- same join-time host path; the generator returns np arrays
        true_k = int(idx.shape[0])
        if true_k > self.k_static:
            idx, true_k = idx[:self.k_static], self.k_static
        row = np.full((self.k_static,), int(idx[0]), np.int32)  # EOS pad
        row[:true_k] = idx
        return row, true_k

    def row_features(self, src_ids: Sequence[int],
                     forced: Optional[List[int]] = None, lane: int = 0,
                     stream: bool = False, sid: int = 0) -> RowFeatures:
        row, true_k = self.row_shortlist(src_ids)
        return RowFeatures(shortlist=row, sl_len=true_k,
                           forced=list(forced or []), lane=lane,
                           stream=stream, sid=sid)

    # ----------------------------------------------------- cache compose
    def cache_key(self, src_key: tuple, forced: Sequence[int]) -> tuple:
        """Prefix-cache / fork key for a row: the source token tuple,
        salted with the forced trunk when one is present — a constrained
        prefix IS a shareable trunk, but only among requests constrained
        the same way."""
        if forced:
            return (src_key, ("forced",) + tuple(int(t) for t in forced))
        return src_key

    @property
    def cacheable(self) -> bool:
        """Sampling makes decodes non-deterministic functions of the
        source — the prefix cache must not replay or fork them."""
        return not self.sampling

    # ------------------------------------------------------------ n-best
    def format_nbest(self, sid: int, nbest: List[dict]) -> str:
        """Format a finished row's ranked hypotheses through the SAME
        OutputPrinter as the dense driver (byte-parity with request
        mode's n-best block)."""
        return self.printer.line(sid, nbest)

    def describe(self) -> str:
        on = []
        if self.shortlist_gen is not None:
            on.append(f"shortlist(k_static={self.k_static})")
        if self.sampling:
            on.append("sampling=" + "/".join(str(p) for p in self.sampling))
        if self.n_best:
            on.append("n-best")
        if self.force_decode:
            on.append("force-decode")
        return "+".join(on) or "none"
