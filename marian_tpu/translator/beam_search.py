"""Batched beam search, jit-compiled with static shapes.

Rebuild of reference src/translator/beam_search.cpp :: BeamSearch::search and
translator/nth_element.cu (fused beam×vocab top-k). The reference purges
finished sentences from the batch (shapes shrink every few steps) and appends
to growing K/V tensors; under XLA both become masking over fixed shapes:

- state = (tokens [B,K,L], scores [B,K], finished [B,K], KV caches [B*K,...])
  inside a lax.while_loop over decode positions with an all-finished early
  exit — shapes never change, so ONE compiled program serves every batch of
  the same (B, Ts, L) bucket;
- the reference's NthElement GPU kernel is jax.lax.top_k over the flattened
  beam×vocab axis (XLA lowers to a TPU-native sort/top-k);
- finished beams are frozen by forcing their token distribution to
  {EOS: 0.0} so path scores stop changing;
- beam expansion at t=0 is masked to beam 0 (all beams start identical).

Semantics kept from the reference: Marian's score bookkeeping (cumulative
log-prob; length normalization score/len^alpha and word penalty applied when
ranking finished hypotheses), --allow-unk suppression, n-best, ensembles
(weighted log-prob sum across scorers), lexical shortlist (top-k runs in
shortlist coordinates, tokens mapped back through the per-batch index set).
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..data.vocab import EOS_ID, UNK_ID

NEG_INF = -1e9


@dataclasses.dataclass(frozen=True)
class BeamConfig:
    beam_size: int = 6
    normalize: float = 0.6          # length-normalization alpha (0 = off)
    word_penalty: float = 0.0
    allow_unk: bool = False
    max_length: int = 256           # decode cap L (static)
    n_best: int = 1
    return_alignment: bool = False
    # --output-sampling: () = off; ("full", temp) samples the full softmax;
    # ("topk", k, temp) restricts to the k most probable tokens first.
    # Each beam becomes an independent sample trajectory (gumbel-max over
    # the token log-probs — TPU-friendly: argmax, no host RNG in the loop).
    sampling: tuple = ()
    word_scores: bool = False       # --word-scores: per-token logP in n-best

    @classmethod
    def from_options(cls, options, max_length: int) -> "BeamConfig":
        norm = options.get("normalize", 0.0)
        if norm is True:
            norm = 1.0
        return cls(
            beam_size=int(options.get("beam-size", 6)),
            normalize=float(norm or 0.0),
            word_penalty=float(options.get("word-penalty", 0.0) or 0.0),
            allow_unk=bool(options.get("allow-unk", False)),
            max_length=max_length,
            n_best=int(options.get("beam-size", 6))
            if options.get("n-best", False) else 1,
            return_alignment=options.get("alignment", None) is not None,
            sampling=_parse_sampling(options.get("output-sampling", [])),
            word_scores=bool(options.get("word-scores", False)),
        )


def _parse_sampling(raw) -> tuple:
    """'full [temp]' / 'topk [k] [temp]' → normalized tuple (reference:
    --output-sampling in translator/sampling)."""
    if raw in (None, False, [], ""):
        return ()
    if raw is True:
        return ("full", 1.0)
    parts = [str(p) for p in (raw if isinstance(raw, list) else [raw])]
    mode = parts[0].lower()
    if mode == "full":
        temp = float(parts[1]) if len(parts) > 1 else 1.0
        return ("full", temp)
    if mode == "topk":
        n = int(parts[1]) if len(parts) > 1 else 10
        temp = float(parts[2]) if len(parts) > 2 else 1.0
        return ("topk", n, temp)
    raise ValueError(f"--output-sampling: unknown mode '{mode}' "
                     f"(expected full or topk)")


def _flatten_beams(x: jax.Array) -> jax.Array:
    return x.reshape((-1,) + x.shape[2:])


def _expand_to_beams(x, k: int):
    """[B, ...] → [B*K, ...] by repeat (encoder outputs shared per beam).
    Tuples (multi-source) are expanded leaf-wise."""
    if isinstance(x, (tuple, list)):
        return tuple(_expand_to_beams(e, k) for e in x)
    return jnp.repeat(x, k, axis=0)


def _first(x):
    """First stream of a possibly-multi-source input."""
    return x[0] if isinstance(x, (tuple, list)) else x


def _topk_rows(flat, k: int, mesh):
    """Per-row top-k. Under a 'data' decode mesh this runs per batch
    shard via shard_map: rows are independent, but XLA's TopK
    custom-call is opaque to GSPMD's partitioner, which otherwise
    ALL-GATHERS the sharded batch dim inside the decode loop — at
    transformer-big beam-6 scale that is ~50 MB of ICI traffic per
    step (caught by test_mesh_decode_is_collective_free)."""
    if mesh is None:
        return jax.lax.top_k(flat, k)
    from ..parallel.mesh import compat_shard_map
    nones = (None,) * (flat.ndim - 1)
    spec = P("data", *nones)
    return compat_shard_map(lambda f: tuple(jax.lax.top_k(f, k)), mesh,
                            in_specs=(spec,), out_specs=(spec, spec))(flat)


def beam_search_jit(model, params_list: List[Dict[str, jax.Array]],
                    weights: Sequence[float], cfg: BeamConfig,
                    src_ids: jax.Array, src_mask: jax.Array,
                    shortlist: Optional[jax.Array] = None,
                    sample_key: Optional[jax.Array] = None,
                    prefix: Optional[jax.Array] = None,
                    mesh=None, allow_fused: bool = True):
    """The jittable core. Returns (tokens [B,K,L], raw_scores [B,K],
    lengths [B,K], norm_scores [B,K], alignments [B,K,L,Ts] or None,
    word_scores [B,K,L] — per-step chosen-token logP, --word-scores).

    params_list/weights: ensemble of scorers (reference: scorers.h); each
    scorer keeps its own decode state, log-probs are weight-summed.
    """
    b = _first(src_ids).shape[0]
    k = cfg.beam_size
    L = cfg.max_length
    bk = b * k

    # Fused decode kernel (ops/pallas/decode_attention.py): the beam
    # reorder of the self-attention caches is folded into the kernel's
    # cache READ — the loop carries the chosen backpointers as flat
    # source rows and hands them to the NEXT step instead of gathering
    # the cache leaves here. Caches lag the beam by exactly one step by
    # construction; every read goes through the pending map, so results
    # are identical (tests/test_decode_attention.py pins it). Gated off
    # under a decode mesh AND when the caller says the params/caches are
    # already device-sharded (allow_fused=False — TP/pipe-sharded
    # training params at a validation decode): the pallas call is opaque
    # to GSPMD, which would re-replicate the sharded caches around it —
    # those paths keep the manual shard_map'd flat gather
    # (collective-free pin).
    fused = (mesh is None and allow_fused
             and bool(getattr(model, "fused_decode_reorder", False)))

    # encoder once per scorer; expand rows to B*K (reference: startState then
    # flattened batch×beam decoding)
    src_mask_bk = _expand_to_beams(src_mask, k)
    states = []
    for params in params_list:
        enc = model.encode_for_decode(params, src_ids, src_mask)
        enc_bk = _expand_to_beams(enc, k)
        states.append(model.start_state(params, enc_bk, src_mask_bk, L,
                                        want_alignment=cfg.return_alignment))

    vocab = (shortlist.shape[0] if shortlist is not None
             else model.cfg.trg_vocab)

    tokens0 = jnp.zeros((b, k, L), jnp.int32)
    if cfg.sampling:
        # every beam is an independent sample — all start live at score 0
        scores0 = jnp.zeros((b, k), jnp.float32)
    else:
        scores0 = jnp.where(jnp.arange(k)[None, :] == 0, 0.0, NEG_INF
                            ).astype(jnp.float32).repeat(b, axis=0).reshape(b, k)
    finished0 = jnp.zeros((b, k), bool)
    lengths0 = jnp.zeros((b, k), jnp.int32)
    prev0 = jnp.zeros((bk, 1), jnp.int32)
    aligns0 = (jnp.zeros((b, k, L, _first(src_ids).shape[1]), jnp.float32)
               if cfg.return_alignment else jnp.zeros((0,), jnp.float32))

    def cond(carry):
        (t, _tokens, _scores, finished, _lengths, _prev, _states, _al,
         _ws, _src) = carry
        return jnp.logical_and(t < L, ~jnp.all(finished))

    def body(carry):
        (t, tokens, scores, finished, lengths, prev, states, aligns,
         wscores, src_rows) = carry
        # ensemble log-probs
        logp = None
        align_t = None
        new_states = []
        if fused:
            step_kw = {"beam_src": src_rows}
        elif getattr(model, "fused_decode_reorder", False):
            # mesh decode with the kernel's config gate on: force it
            # OFF inside the step too — the GSPMD-opaque pallas call
            # would re-replicate the sharded caches even with an
            # identity gather (the reorder itself already fell back to
            # the shard_map'd flat gather above)
            step_kw = {"fused_decode": False}
        else:
            step_kw = {}
        for params, st, w in zip(params_list, states, weights):
            if cfg.return_alignment:
                logits, st2, al = model.step(params, st, prev, src_mask_bk,
                                             shortlist=shortlist,
                                             return_alignment=True,
                                             **step_kw)
                align_t = al if align_t is None else align_t + al
            else:
                logits, st2 = model.step(params, st, prev, src_mask_bk,
                                         shortlist=shortlist, **step_kw)
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            logp = w * lp if logp is None else logp + w * lp
            new_states.append(st2)
        logp = logp.reshape(b, k, vocab)

        if not cfg.allow_unk and shortlist is None:
            logp = logp.at[:, :, UNK_ID].set(NEG_INF)

        # frozen finished beams: only EOS, with log-prob 0
        eos_onehot = jnp.where(jnp.arange(vocab)[None, None, :] == _eos_index(shortlist),
                               0.0, NEG_INF)
        logp = jnp.where(finished[:, :, None], eos_onehot, logp)

        if prefix is not None:
            # --force-decode: while t is inside a sentence's prefix, mask
            # the distribution to the forced token — it keeps its TRUE
            # model log-prob, so scores stay comparable after the prefix
            # ends (reference: forced decoding of given target prefixes).
            # prefix arrives padded to L with -1 (= unconstrained).
            ptok = jax.lax.dynamic_index_in_dim(prefix, t, axis=1,
                                                keepdims=False)   # [B]
            forced = ptok >= 0
            onehot_p = (jnp.arange(vocab)[None, None, :]
                        == jnp.maximum(ptok, 0)[:, None, None])
            gate = forced[:, None, None] & ~finished[:, :, None]
            logp = jnp.where(gate & ~onehot_p, NEG_INF, logp)

        if cfg.sampling:
            # --output-sampling: each beam samples its own next token via
            # gumbel-max (argmax of tempered log-probs + gumbel noise — no
            # categorical host round-trip; finished beams keep picking EOS
            # because their distribution is the {EOS: 0} onehot above)
            temp = float(cfg.sampling[-1])
            slp = logp / max(temp, 1e-6)
            if cfg.sampling[0] == "topk":
                n = min(int(cfg.sampling[1]), vocab)
                kth = _topk_rows(slp, n, mesh)[0][..., -1:]
                slp = jnp.where(slp < kth, NEG_INF, slp)
            g = jax.random.gumbel(jax.random.fold_in(sample_key, t),
                                  slp.shape, jnp.float32)
            tok_sl = jnp.argmax(slp + g, axis=-1).astype(jnp.int32)  # [B,K]
            top_scores = scores + jnp.take_along_axis(
                logp, tok_sl[..., None], axis=-1)[..., 0]
            beam_idx = jnp.broadcast_to(jnp.arange(k)[None, :], (b, k))
        else:
            combined = scores[:, :, None] + logp        # [B,K,V]
            flat = combined.reshape(b, k * vocab)
            top_scores, top_idx = _topk_rows(flat, k, mesh)  # [B,K]
            beam_idx = top_idx // vocab                 # [B,K] source beam
            tok_sl = top_idx % vocab                    # token in (shortlist) coords
        tok_full = (shortlist[tok_sl] if shortlist is not None
                    else tok_sl).astype(jnp.int32)

        # reorder beam-carried state by beam_idx
        def reorder(x):  # [B,K,...] gather along K
            return jnp.take_along_axis(
                x, beam_idx.reshape(beam_idx.shape + (1,) * (x.ndim - 2)), axis=1)

        tokens = reorder(tokens)
        tokens = jax.lax.dynamic_update_index_in_dim(
            tokens, tok_full.astype(jnp.int32), t, axis=2)
        if cfg.word_scores:
            # per-word score = this step's cumulative minus the SOURCE
            # beam's previous cumulative (--word-scores output; frozen
            # beams pick EOS at logP 0 so their trace stops moving).
            # Gated: the [B,K,L] carry + per-step reorder/scatter are
            # dead weight for ordinary decodes (cf. aligns0)
            prev_sel = jnp.take_along_axis(scores, beam_idx, axis=1)
            wscores = reorder(wscores)
            wscores = jax.lax.dynamic_update_index_in_dim(
                wscores, top_scores - prev_sel, t, axis=2)
        was_finished = reorder(finished.astype(jnp.int32)).astype(bool)
        lengths = reorder(lengths)
        if cfg.return_alignment:
            aligns = reorder(aligns)
            al = align_t.reshape(b, k, -1)
            al = reorder(al)
            aligns = jax.lax.dynamic_update_index_in_dim(aligns, al, t, axis=2)

        now_eos = tok_full == _eos_token(shortlist)
        new_finished = was_finished | now_eos
        # length counts tokens incl. EOS (Marian hypothesis length)
        lengths = jnp.where(was_finished, lengths, t + 1)
        scores = top_scores

        # reorder each scorer's KV caches: rows are b*k, new row j takes
        # old row (batch*k + beam_idx). Implementations A/B'd on silicon
        # (r5, beam-6 transformer-big sent/s on v5e): flat LEADING-row
        # gather 88.5 — the only gather form the tiled cache layout runs
        # at bandwidth — vs one-hot matmul 61.6 (even unflattened, the
        # tiny-contraction dot relayouts the cache) vs take_along_axis
        # 46-53. The flat gather is opaque to GSPMD (it all-gathers the
        # whole cache per step under a decode mesh), so the mesh path
        # runs the SAME flat gather per batch shard inside a manual
        # 'data' shard_map — collective-free by construction
        # (test_mesh_decode_is_collective_free pins it).
        # MARIAN_BEAM_REORDER={gather,onehot,take} forces a form for
        # A/Bs (gather = the GSPMD-opaque global form, only meaningful
        # off-mesh).
        carried = model.beam_carried_suffixes
        reorder_impl = os.environ.get("MARIAN_BEAM_REORDER", "auto")

        def beam_rows(v, axis):
            shape = v.shape

            def split_rows():
                # [.., B*K, ..] -> [.., B, K, ..]: single-dim split,
                # layout-free (tiling lives on the last two dims)
                return v.reshape(shape[:axis] + (b, k) + shape[axis + 1:])

            def take():
                idx = beam_idx.reshape((1,) * axis + (b, k) +
                                       (1,) * (v.ndim - axis - 1))
                return jnp.take_along_axis(split_rows(), idx,
                                           axis=axis + 1).reshape(shape)

            if reorder_impl == "take" or (
                    reorder_impl == "onehot"
                    and not jnp.issubdtype(v.dtype, jnp.floating)):
                # take also covers integer carried state under the onehot
                # override: int x int einsum exactness is backend-
                # dependent; the gather forms are dtype-agnostic
                return take()

            def flat_gather(vv, idx):
                # rows (axis 0 or 1) indexed by a flat [rows] vector —
                # the ONLY gather form the tiled cache layout runs at
                # bandwidth (leading-row gather)
                bl = idx.shape[0]
                fs = (jnp.arange(bl)[:, None] * k + idx).reshape(-1)
                return vv[:, fs] if axis == 1 else vv[fs]

            if reorder_impl == "gather" or (mesh is None
                                            and reorder_impl != "onehot"):
                return flat_gather(v, beam_idx)
            if reorder_impl == "onehot":
                # one-hot matmul: exact (single 1.0 term per output, f32
                # MXU accumulation), partitionable — kept as an A/B
                # alternative; the shard_map gather below measured faster
                prec = (jax.lax.Precision.HIGHEST
                        if v.dtype == jnp.float32 else
                        jax.lax.Precision.DEFAULT)
                onehot = (beam_idx[:, :, None] ==
                          jnp.arange(k)[None, None, :]).astype(v.dtype)
                eq = "bij,bj...->bi..." if axis == 0 else "bij,lbj...->lbi..."
                return jnp.einsum(eq, onehot, split_rows(),
                                  precision=prec).reshape(shape)
            # decode mesh: the SAME fast flat gather, run PER BATCH SHARD
            # under a manual 'data' shard_map — beam_idx is batch-local
            # (source-beam index within each sentence's own beam), so the
            # local gather touches only local rows: collective-free by
            # construction (test_mesh_decode_is_collective_free), at the
            # single-device gather's measured speed per shard. Left to
            # GSPMD, the flat global gather all-gathers the entire cache
            # every step instead.
            from ..parallel.mesh import compat_shard_map
            row_axis_spec = ["data" if d == axis else None
                             for d in range(v.ndim)]
            spec_v = P(*row_axis_spec)
            return compat_shard_map(
                lambda vv, idx: flat_gather(vv, idx), mesh,
                in_specs=(spec_v, P("data")),
                out_specs=spec_v)(v, beam_idx)

        def reorder_state(st):
            out = {}
            for key, v in st.items():
                if key == "pos":
                    out[key] = v
                elif fused and key.endswith(("_self_k", "_self_v")):
                    # fused decode kernel: the pending backpointers ride
                    # the carry and the NEXT step's cache read applies
                    # them — no gather here
                    out[key] = v
                elif key.endswith(carried):
                    # 'stack_*' = scanned decode caches [L, B*K, ...]:
                    # the batch axis is axis 1
                    out[key] = beam_rows(v, 1 if key.startswith("stack_")
                                         else 0)
                else:  # cross K/V / encoder context are beam-invariant
                    out[key] = v
            return out

        states2 = tuple(reorder_state(st) for st in new_states)
        prev = tok_full.reshape(bk, 1)
        if fused:
            src_rows = (jnp.arange(b, dtype=jnp.int32)[:, None] * k
                        + beam_idx.astype(jnp.int32)).reshape(bk)
        return (t + 1, tokens, scores, new_finished, lengths, prev, states2,
                aligns, wscores, src_rows)

    init = (jnp.zeros((), jnp.int32), tokens0, scores0, finished0, lengths0,
            prev0, tuple(states), aligns0,
            (jnp.zeros((b, k, L), jnp.float32) if cfg.word_scores
             else jnp.zeros((0,), jnp.float32)),
            # pending-backpointer carry: identity before the first top-k
            (jnp.arange(bk, dtype=jnp.int32) if fused
             else jnp.zeros((0,), jnp.int32)))
    (t, tokens, scores, finished, lengths, prev, states, aligns, wscores,
     _src) = jax.lax.while_loop(cond, body, init)

    # unfinished beams at L: length = L
    lengths = jnp.where(finished, lengths, L)
    norm = jnp.ones_like(scores)
    if cfg.normalize > 0:
        norm = jnp.power(lengths.astype(jnp.float32), cfg.normalize)
    norm_scores = scores / norm - cfg.word_penalty * lengths.astype(jnp.float32)
    return tokens, scores, lengths, norm_scores, \
        (aligns if cfg.return_alignment else None), \
        (wscores if cfg.word_scores else None)


def _eos_index(shortlist: Optional[jax.Array]):
    """Index of EOS in (shortlist) coordinates. The shortlist generator always
    places EOS_ID=0 at position 0 (sorted unique ids)."""
    return 0 if shortlist is not None else EOS_ID


def _eos_token(shortlist: Optional[jax.Array]):
    return EOS_ID


class BeamSearch:
    """Host-side wrapper: jit cache per (B, Ts, L) bucket, Histories out
    (reference: BeamSearch::search + translator.h per-batch loop)."""

    def __init__(self, model, params_list, weights: Optional[Sequence[float]],
                 options, trg_vocab):
        self.model = model
        self.params_list = params_list
        n = len(params_list)
        self.weights = list(weights) if weights else [1.0 / max(n, 1)] * n
        self.options = options
        self.trg_vocab = trg_vocab
        self.max_length_factor = float(options.get("max-length-factor", 3.0))
        self.max_length_cap = int(options.get("max-length", 1000))
        self._jitted = {}
        self._sample_calls = 0
        self._sample_seed = int(options.get("seed", 0) or 0) or 1234
        # Data-parallel decode: shard the batch dim over visible devices
        # (reference: translator.h round-robins batches over --devices GPU
        # workers, one model replica per device; the SPMD equivalent is
        # ONE jitted program with the batch sharded over a 'data' mesh —
        # GSPMD partitions every beam-search op along rows). --num-devices
        # caps the mesh; a single visible device means no mesh.
        # local (addressable) devices only: under multi-process (multihost)
        # each process decodes its own batches on its own chips — the same
        # per-worker decomposition as the reference's translator workers
        local = jax.local_devices()
        nd = int(options.get("num-devices", 0) or 0) or len(local)
        nd = max(1, min(nd, len(local)))
        self.mesh = None
        # sharded scorer params (TP/pipe training params at a validation
        # decode) also veto the fused decode kernel: its pallas call is
        # GSPMD-opaque and would all-gather the sharded caches per step
        self._sharded_params = any(self._mesh_sharded(p)
                                   for p in self.params_list)
        if nd > 1 and not self._sharded_params:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec
            self.mesh = Mesh(np.array(local[:nd]), ("data",))
            rep = NamedSharding(self.mesh, PartitionSpec())

            def _replicate(v):
                # multiprocess: a GLOBAL-mesh array (training params at a
                # validation decode) cannot device_put onto the local
                # mesh directly — jax treats it as a cross-host transfer
                # even when a replica is addressable; hop via the local
                # replica on host
                if isinstance(v, jax.Array) and not v.is_fully_addressable:
                    # the extracted local replica is a fully-addressable
                    # single-device array — replicating THAT is a
                    # device-to-device copy, no host round-trip
                    v = v.addressable_data(0)
                return jax.device_put(v, rep)

            # scorer params replicate to every device once, up front
            # (tree_map covers QTensor leaves)
            self.params_list = [jax.tree_util.tree_map(_replicate, p)
                                for p in self.params_list]

    @property
    def fused_decode_engaged(self) -> bool:
        """Whether beam_search_jit will actually run the fused decode
        kernel for this instance — the ONE place the gate's terms live
        (mirrored into beam_search_jit via mesh/allow_fused), so bench
        provenance fields cannot desynchronize from the compiled
        program."""
        return (self.mesh is None and not self._sharded_params
                and bool(getattr(self.model, "fused_decode_reorder",
                                 False)))

    @staticmethod
    def _mesh_sharded(params) -> bool:
        """True if any param leaf is already non-replicated device-sharded
        (TP/pipe-sharded training params reaching a validation decode):
        re-placing those replicated would materialize a full model copy
        per device mid-training — decode with them where they are
        instead (GSPMD handles sharded inputs without our mesh)."""
        for v in jax.tree_util.tree_leaves(params):
            sh = getattr(v, "sharding", None)
            if sh is not None and not getattr(sh, "is_fully_replicated",
                                              True):
                return True
        return False

    def _get_fn(self, cfg: BeamConfig, has_shortlist: bool):
        key = (cfg, has_shortlist)
        if key not in self._jitted:
            model, weights = self.model, tuple(self.weights)

            mesh = self.mesh
            allow_fused = not self._sharded_params

            def fn(params_list, src_ids, src_mask, shortlist=None,
                   sample_key=None, prefix=None):
                return beam_search_jit(model, list(params_list), weights, cfg,
                                       src_ids, src_mask, shortlist,
                                       sample_key=sample_key, prefix=prefix,
                                       mesh=mesh, allow_fused=allow_fused)

            self._jitted[key] = jax.jit(fn, static_argnames=())
        return self._jitted[key]

    def search_async(self, src_ids, src_mask,
                     shortlist=None, prefix=None) -> "_SearchHandle":
        """Dispatch one batch's beam search; returns a handle whose
        ``collect()`` blocks on the device result and extracts n-bests.
        src_ids/src_mask may be tuples of streams (multi-source).
        `prefix` [B, P] int32 (pad -1) force-decodes each sentence's
        target prefix (--force-decode)."""
        if prefix is not None and shortlist is not None:
            raise ValueError("--force-decode cannot be combined with a "
                             "lexical shortlist (prefix ids are full-vocab)")
        if getattr(self.model.cfg, "lm", False):
            raise ValueError("a decoder-only LM (--type transformer-lm) "
                             "has no source to translate; use "
                             "marian-scorer for LM scoring")
        if prefix is not None and getattr(self.model.cfg,
                                          "output_approx_knn", ()):
            raise ValueError("--force-decode cannot be combined with "
                             "--output-approx-knn (a forced token outside "
                             "the LSH candidate set would have no logit)")
        b, ts = _first(src_ids).shape
        n_rows = b
        if self.mesh is not None:
            # pad rows to a multiple of the mesh by REPLICATING row 0
            # (replicated rows decode safely — an all-zero mask row would
            # risk NaNs in fully-masked attention); extras drop at collect
            pad = (-b) % self.mesh.shape["data"]
            if pad:
                def _padrows(x):
                    if isinstance(x, (tuple, list)):
                        return tuple(_padrows(e) for e in x)
                    x = np.asarray(x)
                    return np.concatenate(
                        [x, np.repeat(x[:1], pad, axis=0)], axis=0)
                src_ids = _padrows(src_ids)
                src_mask = _padrows(src_mask)
                if prefix is not None:
                    prefix = _padrows(prefix)
                b += pad
        # static decode cap per source bucket (Marian: factor * src length)
        L = int(min(self.max_length_cap,
                    max(8, round(self.max_length_factor * ts))))
        if prefix is not None:
            plen = int(np.asarray(prefix).shape[1])
            # the forced prefix must fit under the cap with room to continue
            L = max(L, min(self.max_length_cap, plen + 8))
            if plen >= self.max_length_cap:
                raise ValueError(
                    f"--force-decode: prefix length {plen} exceeds "
                    f"--max-length {self.max_length_cap}")
        cfg = BeamConfig.from_options(self.options, L)
        sl_idx = jnp.asarray(shortlist.indices) if shortlist is not None else None
        fn = self._get_fn(cfg, sl_idx is not None)

        def _dev(x):
            if isinstance(x, (tuple, list)):
                return tuple(_dev(e) for e in x)
            x = jnp.asarray(x)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                spec = PartitionSpec("data", *([None] * (x.ndim - 1)))
                x = jax.device_put(x, NamedSharding(self.mesh, spec))
            return x

        sample_key = None
        if cfg.sampling:
            self._sample_calls += 1
            sample_key = jax.random.fold_in(
                jax.random.key(self._sample_seed), self._sample_calls)
        pfx = None
        if prefix is not None:
            # pad/crop to the decode cap with -1 (unconstrained past end)
            pfx = np.full((b, L), -1, np.int32)
            p = np.asarray(prefix)[:, :L]
            pfx[:, :p.shape[1]] = p
            pfx = _dev(pfx)       # same 'data' placement as its siblings
        args = (tuple(self.params_list), _dev(src_ids), _dev(src_mask))
        tokens, scores, lengths, norm_scores, aligns, wscores = fn(
            *args, shortlist=sl_idx, sample_key=sample_key, prefix=pfx)
        # device results stay lazy here — collect() forces them. Callers
        # that pipeline (translator driver) dispatch the NEXT batch's
        # search before collecting this one, so host n-best extraction
        # overlaps device beam steps (the role of the reference
        # translator's worker thread pool, played by XLA async dispatch).
        return _SearchHandle(tokens, scores, lengths, norm_scores, aligns,
                             wscores, cfg, self,
                             n_rows=n_rows if n_rows != b else None)

    def search(self, src_ids, src_mask,
               shortlist=None, prefix=None) -> List[List[dict]]:
        """Returns per-sentence n-best lists of dicts
        {tokens, score, norm_score, alignment}. src_ids/src_mask may be
        tuples of streams (multi-source). `prefix` [B, P] int32 (pad -1)
        force-decodes each sentence's target prefix (--force-decode)."""
        return self.search_async(src_ids, src_mask, shortlist=shortlist,
                                 prefix=prefix).collect()

    def _collect(self, tokens, scores, lengths, norm_scores, aligns,
                 cfg: BeamConfig, wscores=None) -> List[List[dict]]:  # noqa: C901
        b, k, L = tokens.shape
        out = []
        for i in range(b):
            order = np.argsort(-norm_scores[i])
            nbest = []
            for rank in range(min(cfg.n_best, k) if cfg.n_best > 1 else 1):
                j = order[rank]
                ln = int(lengths[i, j])
                toks = tokens[i, j, :ln].tolist()
                if toks and toks[-1] == EOS_ID:
                    toks = toks[:-1]
                entry = {
                    "tokens": toks,
                    "score": float(scores[i, j]),
                    "norm_score": float(norm_scores[i, j]),
                }
                if aligns is not None:
                    entry["alignment"] = aligns[i, j, :ln, :]
                if wscores is not None:
                    # per emitted token, incl. the EOS step (Marian's
                    # WordScores covers the terminating </s>)
                    entry["word_scores"] = [
                        float(x) for x in wscores[i, j, :ln]]
                nbest.append(entry)
            out.append(nbest)
        return out


class _SearchHandle:
    """Lazy result of one dispatched beam search. Holding it costs one
    batch's device output buffers; ``collect()`` forces the transfer and
    runs host n-best extraction. Depth-1 pipelining (dispatch batch i+1,
    then collect batch i) hides the host extraction of every batch but
    the last behind device compute."""

    def __init__(self, tokens, scores, lengths, norm_scores, aligns,
                 wscores, cfg, bs: "BeamSearch", n_rows: Optional[int] = None):
        self._dev = (tokens, scores, lengths, norm_scores, aligns, wscores)
        self._cfg = cfg
        self._bs = bs
        self._n = n_rows                 # original rows before mesh padding

    def collect(self) -> List[List[dict]]:
        tokens, scores, lengths, norm_scores, aligns, ws = self._dev

        def _h(x):
            if x is None:
                return None
            x = np.asarray(x)  # mtlint: ok -- collect() IS the designed sync boundary; depth-1 pipelining hides it behind the next batch's device work
            return x[:self._n] if self._n is not None else x

        return self._bs._collect(
            _h(tokens), _h(scores), _h(lengths), _h(norm_scores),
            _h(aligns) if aligns is not None else None, self._cfg,
            wscores=_h(ws) if self._cfg.word_scores else None)
