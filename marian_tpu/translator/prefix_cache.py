"""Cross-request prefix sharing over the paged KV pool (ISSUE 12).

The heavy-traffic north star serves highly redundant traffic: doc-level
translation re-sends overlapping sources, templated requests differ by a
slot or two, and client retries re-send the whole sentence. Request-mode
serving recomputes every one of them from scratch. This module turns an
exact repeat of a source's token sequence into a PAGE-TABLE HIT instead
of repeated compute, using the same refcount machinery that copy-on-
write beam forking rides (ops/pallas/kv_pool.py):

- LIVE fork: a request whose source matches a sentence that is decoding
  RIGHT NOW joins as a follower — its cross-attention rows are copied
  slot-to-slot (no encoder forward), its page table aliases the
  leader's full (append-only, immutable) pages with refcount++, and
  only the leader's current partial page is content-copied
  (``pool_fork_partial``). The follower resumes at the leader's
  position: the leader's decoded steps are compute the follower never
  pays.
- DONE entry: a finished sentence's pages transfer to the cache (owner
  ``("prefix", key)``, refcounts unchanged) together with its decoded
  tokens. A later exact repeat resolves instantly — greedy decode is
  deterministic, so the cached tokens ARE what a cold decode would
  produce (the bitwise-identity acceptance test pins this), and the
  held pages are what the hit did NOT have to recompute and rewrite.
- LRU under pool pressure: when a fresh claim cannot be satisfied, the
  engine evicts least-recently-used entries (preferring those whose
  pages are refcount-1 — actually freeable now) until the claim fits.

Keys are the EXACT source token sequence (tuple of vocab ids). The
encoder is bidirectional, so a strict token *prefix* of a different
source does not share encoder states — exact match is the correctness
boundary; "shared prefixes" in the traffic sense (retries, templates,
doc re-sends) are exact duplicates at the sentence level, which is what
loadgen ``--prefix-mix`` generates. Entries are stamped with the
engine's model version and each engine owns its own cache, so a hot
swap can never serve stale-version pages (the version-isolation test
pins it).

Threading: mutations happen on the serving scheduler's device worker
thread; the metrics scrape thread reads the gauges — hence the lock.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence, Tuple

from ..common import lockdep


class PrefixEntry:
    __slots__ = ("key", "tokens", "text", "pages", "version")

    def __init__(self, key, tokens: List[int], text: str,
                 pages: List[int], version: str):
        self.key = key
        self.tokens = tokens        # decoded target ids (no EOS)
        self.text = text
        self.pages = pages          # cache-held pool references
        self.version = version


class PrefixCache:
    """(model_version, source-token-sequence) -> shared decode results
    + refcounted KV pages. One instance per engine (per model version).
    """

    def __init__(self, max_entries: int = 64,
                 version: str = "unversioned",
                 registry=None):
        self.max_entries = max(1, int(max_entries))
        self.version = str(version)
        self._lock = lockdep.make_lock("PrefixCache._lock")
        # insertion-ordered: move_to_end on touch makes it the LRU list
        self._done: "collections.OrderedDict[tuple, PrefixEntry]" = \
            collections.OrderedDict()           # guarded-by: _lock
        # src key -> leader row key while that sentence is decoding
        self._live: Dict[tuple, object] = {}    # guarded-by: _lock
        self._held_tokens = 0                   # guarded-by: _lock
        self._declared = False
        if registry is not None:
            self._declare_metrics(registry)

    # -- metrics ------------------------------------------------------------
    def _declare_metrics(self, r) -> None:
        self.m_hits = r.counter(
            "marian_prefix_hits_total",
            "Prefix-cache hits (live forks + completed-entry replays)")
        self.m_misses = r.counter(
            "marian_prefix_misses_total",
            "Prefix-cache lookups that found no shareable source")
        self.m_tokens_saved = r.counter(
            "marian_prefix_tokens_saved_total",
            "Decode steps NOT recomputed thanks to prefix sharing "
            "(leader position at fork time; full decode length on a "
            "completed-entry replay)")
        self.m_pages_reused = r.counter(
            "marian_prefix_pages_reused_total",
            "KV pages served by table aliasing / cache retention "
            "instead of being recomputed and rewritten")
        self.m_evictions = r.counter(
            "marian_prefix_evictions_total",
            "Prefix-cache entries evicted (LRU capacity or pool "
            "pressure); their page references were dropped")
        self.m_entries = r.gauge(
            "marian_prefix_entries",
            "Completed decodes currently held by the prefix cache")
        self.m_entries.set_function(self.entries)
        self._declared = True

    def _note_hit(self, tokens_saved: int, pages_reused: int) -> None:
        if self._declared:
            self.m_hits.inc()
            if tokens_saved:
                self.m_tokens_saved.inc(tokens_saved)
            if pages_reused:
                self.m_pages_reused.inc(pages_reused)

    def note_miss(self) -> None:
        if self._declared:
            self.m_misses.inc()

    # -- capacity / introspection (any thread) ------------------------------
    def entries(self) -> int:
        with self._lock:
            return len(self._done)

    def held_tokens(self) -> int:
        """Tokens resident in cache-held pages (the fragmentation gauge
        folds these in so retained entries don't read as waste)."""
        with self._lock:
            return self._held_tokens

    def held_pages(self) -> int:
        """Page references currently held by completed entries — the
        marian_prefix_held_pages gauge and the /poolz prefix block
        (ISSUE 14). One lock acquisition, any thread."""
        with self._lock:
            return sum(len(e.pages) for e in self._done.values())

    def owner(self, key: tuple):
        return ("prefix", self.version, key)

    def owner_keys(self) -> List[object]:
        with self._lock:
            return [self.owner(k) for k in self._done]

    def owns(self, owner) -> bool:
        return (isinstance(owner, tuple) and len(owner) == 3
                and owner[0] == "prefix" and owner[1] == self.version)

    def reclaimable_pages(self, pool) -> int:
        """Pages evicting the whole cache would free RIGHT NOW (held
        references whose page refcount is 1) — the engine adds this to
        its free-page report so page-priced admission knows pressure can
        be relieved before a claim actually fails. One refcount
        snapshot, not a lock acquisition per page (this runs per
        admission decision and per metrics scrape)."""
        with self._lock:
            pages = [p for e in self._done.values() for p in e.pages]
        if not pages:
            return 0
        refs = pool.refcounts()
        return sum(1 for p in pages if refs.get(p, 0) == 1)

    # -- lookups (device worker thread) -------------------------------------
    # Lock discipline throughout: PrefixCache._lock guards only the
    # cache's own maps and is NEVER held across a pool or metrics call
    # (mutations all happen on the single device worker thread, so the
    # split windows race nothing; the lockdep witness pins the absence
    # of nested acquisition).

    def get(self, key: tuple, version: str) -> Optional[PrefixEntry]:
        """Completed-entry lookup; touches LRU on hit. ``version`` must
        match the entry's stamp — a stale-version entry is never served
        (belt to the per-engine-cache braces)."""
        with self._lock:
            e = self._done.get(key)
            if e is None or e.version != version:
                return None
            self._done.move_to_end(key)
        self._note_hit(len(e.tokens) + 1, len(e.pages))
        return e

    def leader(self, key: tuple) -> Optional[object]:
        """Row key of a live sentence with this exact source, if one is
        decoding (the fork source). The caller verifies the row still
        exists and counts the hit itself (fork setup can still fall
        through to a cold join under pool pressure)."""
        with self._lock:
            return self._live.get(key)

    def note_fork(self, tokens_saved: int, pages_reused: int) -> None:
        self._note_hit(tokens_saved, pages_reused)

    def register_live(self, key: tuple, row_key) -> None:
        with self._lock:
            self._live.setdefault(key, row_key)

    def unregister_live(self, key: tuple, row_key) -> None:
        with self._lock:
            if self._live.get(key) is row_key or \
                    self._live.get(key) == row_key:
                del self._live[key]

    # -- adoption + eviction (device worker thread) -------------------------
    def adopt(self, pool, key: tuple, row_key, tokens: List[int],  # owns: callee -- the finished row's references change hands into the cache
              text: str) -> int:
        """A row with source ``key`` finished normally: transfer its
        page references to the cache (refcounts unchanged) and remember
        its decode. Returns the number of references adopted — 0 (the
        caller releases normally) when an entry already exists or the
        transfer moved nothing."""
        with self._lock:
            if key in self._done:
                return 0
        pages = pool.transfer(row_key, self.owner(key))
        if not pages:
            return 0
        with self._lock:
            self._done[key] = PrefixEntry(key, list(tokens), text,
                                          pages, self.version)
            self._held_tokens += len(tokens) + 1
        self._trim_lru(pool)
        return len(pages)

    def remember(self, pool, key: tuple, tokens: List[int],
                 text: str) -> bool:
        """Pageless completed entry (the beam engine's replay memo: its
        hypotheses' pages are released at finalize — the decode RESULT
        is still deterministic per version, so an exact repeat replays
        it without a decode). Shares the LRU/eviction/version plumbing
        with page-backed entries."""
        with self._lock:
            if key in self._done:
                return False
            self._done[key] = PrefixEntry(key, list(tokens), text,
                                          [], self.version)
        self._trim_lru(pool)
        return True

    def _pop_entry(self, key: tuple) -> Optional[PrefixEntry]:
        with self._lock:
            e = self._done.pop(key, None)
            if e is not None and e.pages:   # pageless memos held none
                self._held_tokens -= len(e.tokens) + 1
        return e

    def _release_entry(self, pool, key: tuple,
                       e: Optional[PrefixEntry]) -> bool:
        if e is None:
            return False
        if e.pages:
            pool.release(self.owner(key))
        if self._declared:
            self.m_evictions.inc()
        return True

    def _trim_lru(self, pool) -> None:
        while True:
            with self._lock:
                if len(self._done) <= self.max_entries:
                    return
                key = next(iter(self._done))
                e = self._done.pop(key)
                if e.pages:
                    self._held_tokens -= len(e.tokens) + 1
            self._release_entry(pool, key, e)

    def evict_for_pages(self, pool, n_needed: int) -> int:
        """Pool pressure: drop LRU entries until ``n_needed`` pages are
        free or the cache is empty — refcount-1 holdings first (those
        actually free pages now; shared ones merely decref). Returns
        entries evicted."""
        evicted = 0
        while pool.free_pages() < n_needed:
            with self._lock:
                # page-BACKED entries only: evicting a pageless memo
                # (beam replay entries) frees nothing — without this
                # filter one dry claim would wipe the whole replay
                # cache for zero pages
                items = [(k, list(e.pages))
                         for k, e in self._done.items() if e.pages]
            if not items:
                break
            refs = pool.refcounts()
            key = next((k for k, pages in items
                        if all(refs.get(p, 0) <= 1 for p in pages)),
                       items[0][0])
            if self._release_entry(pool, key, self._pop_entry(key)):
                evicted += 1
        return evicted

    def drop_all(self, pool) -> int:
        """Release every entry (engine teardown / tests)."""
        n = 0
        while True:
            with self._lock:
                key = next(iter(self._done), None)
            if key is None:
                break
            if self._release_entry(pool, key, self._pop_entry(key)):
                n += 1
        with self._lock:
            self._live.clear()
        return n
