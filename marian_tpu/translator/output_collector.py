"""In-order output collection and printing (reference:
src/translator/output_collector.cpp :: OutputCollector,
output_printer.cpp :: OutputPrinter).

Batches may finish out of order (async device dispatch / multiple streams);
the collector buffers results and flushes them in input order. The printer
formats single-best or n-best lines and hard/soft alignments."""

from __future__ import annotations

import sys
import threading
from typing import Dict, List, Optional, TextIO

import numpy as np

from ..common import lockdep
from ..data.alignment import hard_alignment_from_soft, WordAlignment


class OutputCollector:
    def __init__(self, stream: Optional[TextIO] = None):
        self.stream = stream or sys.stdout
        self._next = 0
        self._pending: Dict[int, str] = {}
        self._lock = lockdep.make_lock("OutputCollector._lock")

    def write(self, sentence_id: int, text: str) -> None:
        with self._lock:
            self._pending[sentence_id] = text
            while self._next in self._pending:
                self.stream.write(self._pending.pop(self._next))
                self.stream.write("\n")
                self._next += 1
            self.stream.flush()

    def flush_remaining(self) -> None:
        with self._lock:
            for sid in sorted(self._pending):
                self.stream.write(self._pending[sid])
                self.stream.write("\n")
            self._pending.clear()
            self.stream.flush()


class OutputPrinter:
    def __init__(self, options, vocab):
        self.vocab = vocab
        self.n_best = bool(options.get("n-best", False))
        # --allow-special: keep </s> / <unk> visible in the output text
        self.allow_special = bool(options.get("allow-special", False))
        # right-left models emit reversed targets; un-reverse for display
        self.right_left = bool(options.get("right-left", False))
        self.feature = options.get("n-best-feature", "Score")
        align = options.get("alignment", None)
        self.align_mode: Optional[str] = None
        self.align_threshold = 1.0
        if align is not None and align is not False:
            if align in ("soft", "hard"):
                self.align_mode = align
                self.align_threshold = 1.0 if align == "hard" else 0.0
            else:
                self.align_mode = "threshold"
                try:
                    self.align_threshold = float(align)
                except (TypeError, ValueError):
                    self.align_mode = "hard"

    def _detok(self, tokens: List[int]) -> str:
        if self.right_left:
            tokens = list(tokens)[::-1]
        return self.vocab.decode(tokens,
                                 ignore_eos=not self.allow_special)

    def _align_str(self, soft: np.ndarray) -> str:
        if self.align_mode == "soft":
            rows = []
            for t in range(soft.shape[0]):
                rows.append(",".join(f"{p:.6f}" for p in soft[t]))
            return " ".join(rows)
        thr = 1.0 if self.align_mode == "hard" else self.align_threshold
        wa = hard_alignment_from_soft(soft, soft.shape[1], soft.shape[0], thr)
        return str(wa)

    def _align_of(self, h) -> np.ndarray:
        a = np.asarray(h["alignment"])
        if self.right_left and len(a) > 1:
            # the hypothesis is displayed re-reversed — mirror the target
            # rows to match the printed word order, but the terminal EOS
            # row stays LAST (training kept EOS terminal: corpus.py
            # reverses ids[-2::-1] + [eos])
            a = np.concatenate([a[-2::-1], a[-1:]], axis=0)
        return a

    def line(self, sentence_id: int, nbest: List[dict]) -> str:
        """Format one sentence's result (reference: OutputPrinter::print)."""
        if not self.n_best:
            h = nbest[0]
            out = self._detok(h["tokens"])
            # Segment order matches Marian's OutputPrinter: alignment
            # directly after the translation, WordScores after it
            # (ADVICE r3 — index-based n-best consumers rely on this).
            if self.align_mode and "alignment" in h:
                out += " ||| " + self._align_str(self._align_of(h))
            if "word_scores" in h:
                # --word-scores applies to single-best output too
                # (reference: OutputPrinter::print appends the segment)
                ws = h["word_scores"]
                if self.right_left and len(ws) > 1:
                    ws = ws[-2::-1] + ws[-1:]
                out += " ||| WordScores= " \
                    + " ".join(f"{x:.6f}" for x in ws)
            return out
        lines = []
        for h in nbest:
            parts = [str(sentence_id), self._detok(h["tokens"])]
            if self.align_mode and "alignment" in h:
                parts.append(self._align_str(self._align_of(h)))
            if "word_scores" in h:
                # --word-scores (reference: OutputPrinter WordScores
                # segment): per emitted token incl. the terminating </s>
                ws = h["word_scores"]
                if self.right_left and len(ws) > 1:
                    ws = ws[-2::-1] + ws[-1:]
                parts.append("WordScores= "
                             + " ".join(f"{x:.6f}" for x in ws))
            parts += [f"{self.feature}= {h['score']:.6f}",
                      f"{h['norm_score']:.6f}"]
            lines.append(" ||| ".join(parts))
        return "\n".join(lines)
