"""Sentence-embedding extraction (reference: src/embedder/ :: Embed<Embedder>)
— encode the source and mean-pool over real positions, one vector per line."""

from __future__ import annotations

import sys
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from .common import io as mio
from .common import logging as log
from .data import BatchGenerator, Corpus, create_vocab
from .models.encoder_decoder import create_model


class Embedder:
    def __init__(self, options):
        self.options = options
        log.create_loggers(options)
        model_path = (list(options.get("models", [])) or [options.get("model")])[0]
        params, cfg_yaml = mio.load_model(model_path)
        from .ops.quantization import wrap_quantized
        self.params = wrap_quantized(
            {k: jnp.asarray(v) for k, v in params.items()})
        from .models.encoder_decoder import apply_embedded_config
        options = self.options = apply_embedded_config(options, cfg_yaml)
        vocab_paths = list(options.get("vocabs", []))
        self.vocabs = [create_vocab(p, options, i)
                       for i, p in enumerate(vocab_paths[:1])]
        self.model = create_model(options, self.vocabs[0],
                                  self.vocabs[0], inference=True)

        # close over a hoisted local, not self.model: the trace bakes in
        # whatever the closure reads, and an instance mutation would
        # silently retrace (MT-JIT-CLOSURE-VARYING)
        model = self.model

        def embed(params, src_ids, src_mask):
            enc = model.encode_for_decode(params, src_ids, src_mask)
            m = src_mask[..., None]
            return (enc * m).sum(1) / jnp.maximum(m.sum(1), 1.0)

        self._fn = jax.jit(embed)

    def run(self, stream=None) -> None:
        stream = stream or sys.stdout
        sets = list(self.options.get("train-sets", [])) or \
            list(self.options.get("input", []))
        similarity = bool(self.options.get("compute-similarity", False))
        n_streams = 2 if similarity else 1
        if similarity and len(sets) < 2:
            raise ValueError("--compute-similarity expects TWO parallel "
                             "text streams (--train-sets A B)")
        corpus = Corpus(sets[:n_streams], self.vocabs * n_streams,
                        self.options.with_(**{"shuffle": "none",
                                              "max-length-crop": True}),
                        inference=True)
        bg = BatchGenerator(corpus, None, mini_batch=64, maxi_batch=10,
                            maxi_batch_sort="src", shuffle_batches=False,
                            prefetch=True)
        out: dict = {}
        # depth-1 pipeline (common/pipeline.py): dispatch batch i+1
        # before forcing batch i's vectors off the device
        from .common.pipeline import pipelined

        def _embed_batch(b):
            if similarity:
                # cosine of the two streams' sentence embeddings
                # (reference: embedder's --compute-similarity mode)
                va = self._fn(self.params, jnp.asarray(b.sub[0].ids),
                              jnp.asarray(b.sub[0].mask))
                vb = self._fn(self.params, jnp.asarray(b.sub[1].ids),
                              jnp.asarray(b.sub[1].mask))
                na = jnp.maximum(jnp.linalg.norm(va, axis=-1), 1e-9)
                nb = jnp.maximum(jnp.linalg.norm(vb, axis=-1), 1e-9)
                return (va * vb).sum(-1) / (na * nb)
            return self._fn(self.params, jnp.asarray(b.src.ids),
                            jnp.asarray(b.src.mask))

        def _finalize(pbatch, dev):
            vecs = np.asarray(dev)
            for row in range(pbatch.size):
                out[int(pbatch.sentence_ids[row])] = vecs[row]

        pipelined(bg, _embed_batch, _finalize)
        for i in sorted(out):
            if similarity:
                stream.write(f"{float(out[i]):.6f}\n")
            else:
                stream.write(" ".join(f"{x:.6f}" for x in out[i]) + "\n")
        stream.flush()


def embed_main(options) -> None:
    Embedder(options).run()
