from .optimizers import OptimizerConfig, init_state, apply_update, smoothed_params
from .schedule import LRSchedule
