"""Train-time compression: model quantization and gradient dropping, both
with error feedback, both running INSIDE the jitted train step.

- Model quantizer (reference: src/optimizers/quantizer.cpp ::
  ModelQuantizer::quantize, --quantize-bits): after each optimizer update,
  snap parameters to a 2^bits-level grid (uniform, or log-based power-of-two
  levels) with the quantization error carried to the next step
  (--quantize-optimization-steps refines the scale by alternating fits).
- Gradient dropping (reference: src/training/gradient_dropping/ ::
  GradientDrop, DGC-style): keep only the largest-|g| fraction of each
  gradient tensor, accumulate the rest as residual error feedback. The
  reference uses this to compress async communication; on TPU the collective
  is dense either way (ICI bandwidth makes sparse wire formats moot), so
  this preserves the TRAINING semantics (sparsified updates + error
  feedback), which is what determines the loss trajectory.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, jax.Array]


# ---------------------------------------------------------------------------
# model quantization (train-time)
# ---------------------------------------------------------------------------

def quantize_tensor(v: jax.Array, bits: int, log_based: bool = False,
                    opt_steps: int = 0, qrange: float = 0.0) -> jax.Array:
    """Quantize one tensor to 2^bits symmetric levels (reference:
    ModelQuantizer::quantizeImpl; opt_steps = the alternating scale fit of
    --quantize-optimization-steps; qrange = --quantize-range, clipping the
    scale at N standard deviations instead of absmax when > 0)."""
    x = v.astype(jnp.float32)
    levels = float(2 ** (bits - 1) - 1)
    if qrange > 0.0:
        s = jnp.maximum(qrange * jnp.std(x), 1e-12)
    else:
        s = jnp.maximum(jnp.max(jnp.abs(x)), 1e-12)
    if log_based:
        # centers at s * 2^-k, k in [0, levels]: round log2 magnitude
        sign = jnp.sign(x)
        mag = jnp.abs(x) / s
        k = jnp.clip(jnp.round(jnp.log2(jnp.maximum(mag, 2.0 ** -60))),
                     -levels, 0.0)
        q = sign * s * jnp.exp2(k)
        # values far below the smallest center snap to zero
        q = jnp.where(mag < 2.0 ** (-levels - 1), 0.0, q)
        return q.astype(v.dtype)
    for _ in range(max(0, opt_steps)):
        qi = jnp.clip(jnp.round(x / s * levels), -levels, levels)
        denom = jnp.maximum(jnp.sum(qi * qi), 1e-12)
        s = jnp.sum(x * qi) / denom * levels
        s = jnp.maximum(jnp.abs(s), 1e-12)
    qi = jnp.clip(jnp.round(x / s * levels), -levels, levels)
    return (qi * (s / levels)).astype(v.dtype)


def quantize_model(params: Params, error: Params, bits: int,
                   log_based: bool = False, opt_steps: int = 0,
                   include_biases: bool = False, qrange: float = 0.0
                   ) -> Tuple[Params, Params]:
    """Quantize the parameter tree with error feedback: the next step sees
    param + carried error, so quantization noise doesn't accumulate
    (reference: ModelQuantizer keeping `errorResidual`)."""
    new_p: Params = {}
    new_e: Params = {}
    for k, v in params.items():
        skip = (v.ndim < 2 or v.shape[0] == 1) and not include_biases
        if skip:
            new_p[k] = v
            new_e[k] = error[k]
            continue
        target = v.astype(jnp.float32) + error[k]
        q = quantize_tensor(target, bits, log_based, opt_steps, qrange)
        new_p[k] = q.astype(v.dtype)
        new_e[k] = target - q.astype(jnp.float32)
    return new_p, new_e


# ---------------------------------------------------------------------------
# gradient dropping (DGC-style top-|g| sparsification)
# ---------------------------------------------------------------------------

def _threshold(x: jax.Array, keep_rate: float, sample: int = 4096) -> jax.Array:
    """|g| threshold keeping ~keep_rate of entries, estimated on a strided
    sample (reference: gradient_dropping/sparse estimates the cutoff on a
    sample too — exact sort per tensor per step is wasteful)."""
    flat = jnp.abs(x.reshape(-1))
    n = flat.shape[0]
    if n > sample:
        stride = max(1, n // sample)
        flat = flat[::stride]
    return jnp.quantile(flat, jnp.clip(1.0 - keep_rate, 0.0, 1.0))


def drop_gradients(grads: Params, residual: Params, drop_rate: float
                   ) -> Tuple[Params, Params]:
    """Keep the largest-|g + residual| (1-drop_rate) fraction per tensor;
    everything else feeds back (reference: GradientDrop::dropGraph with
    error accumulation)."""
    keep = max(1.0 - drop_rate, 0.0)
    new_g: Params = {}
    new_r: Params = {}
    for k, g in grads.items():
        total = g.astype(jnp.float32) + residual[k]
        thr = _threshold(total, keep)
        mask = (jnp.abs(total) >= thr).astype(jnp.float32)
        kept = total * mask
        new_g[k] = kept.astype(g.dtype)
        new_r[k] = total - kept
    return new_g, new_r


def zeros_like_tree(params: Params) -> Params:
    return {k: jnp.zeros(v.shape, jnp.float32) for k, v in params.items()}
