"""Optimizers with Marian's exact semantics (reference:
src/optimizers/optimizers.cpp :: Adam::updateImpl, Adagrad, Sgd;
src/optimizers/exponential_smoothing.h).

Implemented as pure (state, grads) → (state, params) transforms over the
flat param dict, optax-style but hand-rolled so the update math matches the
reference line-for-line:

- Adam with bias correction (denominators 1-beta^t), epsilon INSIDE the
  sqrt-denominator addition, and optional --mini-batch-words-ref scaling of
  lr/eps (OptimizerBase::update's refMBWords logic);
- global-norm clipping computed over the FULL gradient before the shard
  update (GraphGroup order: clip → update), see training/graph_group.py;
- exponential smoothing of params (EMA swapped in for validation/decode).

State arrays are f32 regardless of compute dtype (the reference keeps
optimizer state in fp32 even for fp16 training). Under ZeRO-1 the state trees
carry PartitionSpec('data') while params are replicated (SURVEY.md §2.7).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, jax.Array]


@dataclasses.dataclass
class OptimizerConfig:
    name: str = "adam"                 # adam | adagrad | sgd
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    clip_norm: float = 1.0             # 0 = off  (--clip-norm)
    smoothing: float = 0.0             # --exponential-smoothing
    ref_mb_words: int = 0              # --mini-batch-words-ref
    # train-time compression (optimizers/compression.py)
    quantize_bits: int = 0             # --quantize-bits (0 = off)
    quantize_log: bool = False         # --quantize-log-based
    quantize_biases: bool = False      # --quantize-biases
    quantize_opt_steps: int = 0        # --quantize-optimization-steps
    quantize_range: float = 0.0        # --quantize-range (clip at N stddevs)
    grad_drop_rate: float = 0.0        # --gradient-dropping-rate (0 = off)
    # --optimizer-state-dtype: storage dtype of Adam's FIRST moment only
    # (optax mu_dtype precedent). bfloat16 halves m's HBM footprint and
    # per-step read/write traffic; the math still runs in f32 and the
    # second moment v stays f32 (its sqrt sits in the update denominator,
    # where bf16's 8 mantissa bits would bite). Beyond the reference.
    state_dtype: str = "float32"       # float32 | bfloat16
    # --normalize-gradient: additionally divide gradients by the batch's
    # target-word count (reference: SyncGraphGroup multiplies the update
    # normalizer by updateTrgWords when the flag is set)
    normalize_gradient: bool = False
    # --check-gradient-nan: skip the ENTIRE update (params + optimizer
    # state unchanged) when the gradient norm is non-finite (reference:
    # GraphGroup checkGradientNan); metrics carry skipped=1
    check_gradient_nan: bool = False
    # --dynamic-gradient-scaling FACTOR [log]: track a windowed average
    # of the (log-)gradient norm; when a step's norm exceeds
    # factor x average, scale the gradient down to that threshold
    # (reference: costScaling/dynamic gradient scaling in
    # training/graph_group.cpp — outlier-step protection)
    dyn_scale_factor: float = 0.0      # 0 = off
    dyn_scale_log: bool = False
    norm_window: int = 100             # --gradient-norm-average-window

    @classmethod
    def from_options(cls, options) -> "OptimizerConfig":
        params = [float(x) for x in options.get("optimizer-params", []) or []]
        name = options.get("optimizer", "adam")
        cfg = cls(name=name,
                  clip_norm=float(options.get("clip-norm", 1.0) or 0.0),
                  smoothing=float(options.get("exponential-smoothing", 0.0) or 0.0),
                  ref_mb_words=int(options.get("mini-batch-words-ref", 0) or 0),
                  quantize_bits=int(options.get("quantize-bits", 0) or 0),
                  quantize_log=bool(options.get("quantize-log-based", False)),
                  quantize_biases=bool(options.get("quantize-biases", False)),
                  quantize_opt_steps=int(
                      options.get("quantize-optimization-steps", 0) or 0),
                  quantize_range=float(
                      options.get("quantize-range", 0.0) or 0.0),
                  grad_drop_rate=float(
                      options.get("gradient-dropping-rate", 0.0) or 0.0),
                  state_dtype=str(options.get("optimizer-state-dtype",
                                              "float32") or "float32"),
                  normalize_gradient=bool(
                      options.get("normalize-gradient", False)),
                  check_gradient_nan=bool(
                      options.get("check-gradient-nan", False)),
                  norm_window=int(
                      options.get("gradient-norm-average-window", 100)
                      or 100))
        dyn = options.get("dynamic-gradient-scaling", []) or []
        if dyn is True:
            dyn = ["2"]
        if isinstance(dyn, (str, int, float)):
            dyn = [dyn]
        if dyn:
            cfg.dyn_scale_factor = float(dyn[0])
            cfg.dyn_scale_log = any(str(v).lower() == "log"
                                    for v in dyn[1:])
        if cfg.state_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"--optimizer-state-dtype {cfg.state_dtype}: expected "
                f"float32 or bfloat16")
        if name == "adam":
            if len(params) > 0:
                cfg.beta1 = params[0]
            if len(params) > 1:
                cfg.beta2 = params[1]
            if len(params) > 2:
                cfg.eps = params[2]
        elif name == "adagrad" and params:
            cfg.eps = params[0]
        return cfg


def init_state(cfg: OptimizerConfig, params: Params) -> Dict[str, Any]:
    zeros_like = lambda: {k: jnp.zeros(v.shape, jnp.float32) for k, v in params.items()}
    st: Dict[str, Any] = {"t": jnp.zeros((), jnp.float32)}
    if cfg.name == "adam":
        m_dtype = jnp.dtype(cfg.state_dtype)
        st["m"] = {k: jnp.zeros(v.shape, m_dtype)
                   for k, v in params.items()}
        st["v"] = zeros_like()
    elif cfg.name == "adagrad":
        st["gt"] = zeros_like()
    elif cfg.name != "sgd":
        raise ValueError(f"Unknown optimizer '{cfg.name}'")
    if cfg.smoothing > 0:
        # copy=True: astype on an f32 array is a no-op alias, and aliasing
        # params here makes jit buffer donation see the same buffer twice
        st["avg"] = {k: jnp.array(v, dtype=jnp.float32, copy=True)
                     for k, v in params.items()}
    if cfg.quantize_bits > 0:     # quantization error feedback (quantizer.cpp)
        st["qerr"] = {k: jnp.zeros(v.shape, jnp.float32)
                      for k, v in params.items()}
    if cfg.grad_drop_rate > 0:    # gradient-dropping residual (DGC)
        st["gerr"] = {k: jnp.zeros(v.shape, jnp.float32)
                      for k, v in params.items()}
    if cfg.dyn_scale_factor > 0:  # --dynamic-gradient-scaling statistics
        st["gstat"] = {"avg": jnp.zeros((), jnp.float32),
                       "n": jnp.zeros((), jnp.float32)}
    return st


def apply_update(cfg: OptimizerConfig, state: Dict[str, Any], params: Params,
                 grads: Params, lr: jax.Array,
                 mb_words: Optional[jax.Array] = None
                 ) -> Tuple[Dict[str, Any], Params]:
    """One optimizer step. `mb_words` enables Marian's reference-batch LR
    scaling (Adam::updateImpl multiplies lr and eps by T/Tref)."""
    t = state["t"] + 1.0
    new_state: Dict[str, Any] = {"t": t}
    lr = jnp.asarray(lr, jnp.float32)
    eps = cfg.eps
    if cfg.ref_mb_words and mb_words is not None:
        ratio = mb_words.astype(jnp.float32) / float(cfg.ref_mb_words)
        lr = lr * ratio
        eps = eps * ratio

    if cfg.grad_drop_rate > 0:
        # DGC-style sparsification with error feedback (reference:
        # training/gradient_dropping/; warmup ramps the rate via t)
        from .compression import drop_gradients
        grads, new_state["gerr"] = drop_gradients(
            grads, state["gerr"], cfg.grad_drop_rate)

    out: Params = {}
    if cfg.name == "adam":
        bc1 = 1.0 - jnp.power(cfg.beta1, t)
        bc2 = 1.0 - jnp.power(cfg.beta2, t)
        m_new, v_new = {}, {}
        m_dtype = jnp.dtype(cfg.state_dtype)
        for k, p in params.items():
            g = grads[k].astype(jnp.float32)
            m = cfg.beta1 * state["m"][k].astype(jnp.float32) \
                + (1.0 - cfg.beta1) * g
            v = cfg.beta2 * state["v"][k] + (1.0 - cfg.beta2) * jnp.square(g)
            m_new[k], v_new[k] = m.astype(m_dtype), v
            mhat = m / bc1
            vhat = v / bc2
            out[k] = (p.astype(jnp.float32)
                      - lr * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype)
        new_state["m"], new_state["v"] = m_new, v_new
    elif cfg.name == "adagrad":
        gt_new = {}
        for k, p in params.items():
            g = grads[k].astype(jnp.float32)
            gt = state["gt"][k] + jnp.square(g)
            gt_new[k] = gt
            out[k] = (p.astype(jnp.float32)
                      - lr * g / (jnp.sqrt(gt) + eps)).astype(p.dtype)
        new_state["gt"] = gt_new
    else:  # sgd
        for k, p in params.items():
            out[k] = (p.astype(jnp.float32)
                      - lr * grads[k].astype(jnp.float32)).astype(p.dtype)

    if cfg.quantize_bits > 0:
        # train-time model quantization with error feedback (quantizer.cpp);
        # runs before EMA so the smoothed params track the quantized model
        from .compression import quantize_model
        out, new_state["qerr"] = quantize_model(
            out, state["qerr"], cfg.quantize_bits, cfg.quantize_log,
            cfg.quantize_opt_steps, cfg.quantize_biases,
            qrange=cfg.quantize_range)

    if cfg.smoothing > 0:
        # reference ExponentialSmoothing: avg += tau * (p - avg), with tau
        # effectively scaled by batch size when using labels-based decay; we
        # use the plain per-update form.
        tau = cfg.smoothing
        new_state["avg"] = {
            k: state["avg"][k] + tau * (out[k].astype(jnp.float32) - state["avg"][k])
            for k in params}
    if "gstat" in state:
        # dynamic-gradient-scaling statistics are updated by the caller
        # (zero.py step_fn, which owns the gradient norm) — pass through
        new_state["gstat"] = state["gstat"]
    return new_state, out


def smoothed_params(cfg: OptimizerConfig, state: Dict[str, Any],
                    params: Params) -> Params:
    """Return EMA params for validation/decoding (reference: swapParams)."""
    if cfg.smoothing > 0 and "avg" in state:
        return {k: state["avg"][k].astype(params[k].dtype) for k in params}
    return params
