"""Learning-rate schedule with Marian's warmup + inverse-sqrt decay
(reference: src/training/scheduler.h :: Scheduler::getScheduledLRate).

base * min(step/warmup, 1) * sqrt(warmup / max(step, warmup))   [inv-sqrt]

Both warmup and inv-sqrt accept SchedulingParameters (updates or labels);
the schedule function takes the current count in the matching unit.
Discrete --lr-decay (epoch/batches/stalled strategies) is applied by the
Scheduler as a multiplicative factor on top.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..common.scheduling_parameter import SchedulingParameter, SchedulingUnit


@dataclasses.dataclass
class LRSchedule:
    base_lr: float
    warmup: int = 0                  # in updates (or labels)
    inv_sqrt: int = 0                # warmup constant for inv-sqrt decay
    warmup_start_rate: float = 0.0
    decay_factor: float = 1.0        # multiplicative, set by Scheduler
    warmup_cycle: bool = False       # --lr-warmup-cycle: sawtooth warmup
    warmup_offset: int = 0           # warmup restarts here (--lr-warmup-at-
                                     # reload / --lr-decay-repeat-warmup)

    @classmethod
    def from_options(cls, options) -> "LRSchedule":
        warmup = SchedulingParameter.parse(str(options.get("lr-warmup", "0")))
        inv_raw = options.get("lr-decay-inv-sqrt", ["0"])
        if not isinstance(inv_raw, list):
            inv_raw = [inv_raw]
        inv = SchedulingParameter.parse(str(inv_raw[0]))
        return cls(base_lr=float(options.get("learn-rate", 1e-4)),
                   warmup=warmup.n, inv_sqrt=inv.n,
                   warmup_start_rate=float(
                       options.get("lr-warmup-start-rate", 0.0)),
                   warmup_cycle=bool(options.get("lr-warmup-cycle", False)))

    def host_lr(self, step) -> float:
        """Pure-host mirror of __call__ for display/logging — the training
        hot path must never pay a device round-trip for a scalar the host
        can compute itself (math only, no jnp)."""
        import math
        step = max(float(step), 1.0)
        lr = self.base_lr
        if self.warmup > 0:
            wstep = max(step - float(self.warmup_offset), 1.0)
            if self.warmup_cycle:
                wstep = math.fmod(wstep - 1.0, float(self.warmup)) + 1.0
            frac = min(wstep / float(self.warmup), 1.0)
            start = self.warmup_start_rate
            lr = start + (lr - start) * frac if start > 0 else lr * frac
        if self.inv_sqrt > 0:
            lr = lr * math.sqrt(float(self.inv_sqrt)
                                / max(step, float(self.inv_sqrt)))
        return lr * self.decay_factor

    def __call__(self, step) -> jnp.ndarray:
        """step: 1-based update count (f32 scalar or python int)."""
        step = jnp.maximum(jnp.asarray(step, jnp.float32), 1.0)
        lr = jnp.asarray(self.base_lr, jnp.float32)
        if self.warmup > 0:
            wstep = jnp.maximum(step - float(self.warmup_offset), 1.0)
            if self.warmup_cycle:
                wstep = jnp.mod(wstep - 1.0, float(self.warmup)) + 1.0
            frac = jnp.minimum(wstep / float(self.warmup), 1.0)
            start = self.warmup_start_rate
            lr = start + (lr - start) * frac if start > 0 else lr * frac
        if self.inv_sqrt > 0:
            lr = lr * jnp.sqrt(float(self.inv_sqrt)
                               / jnp.maximum(step, float(self.inv_sqrt)))
        return lr * self.decay_factor
