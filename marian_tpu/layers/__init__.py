from . import initializers
from .loss import RationalLoss, cross_entropy_loss, guided_alignment_loss

__all__ = ["initializers", "RationalLoss", "cross_entropy_loss",
           "guided_alignment_loss"]
