"""Pretrained embedding import: word2vec-format text vectors for
--embedding-vectors and the ULR query/key tables (reference:
src/layers/embedding.cpp :: Embedding loading embFile via
io::load + src/common/file_stream; and ULREmbedding's ulrQueryFile /
ulrKeysFile)."""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..common import logging as log


def load_word2vec(path: str, vocab, dim: int,
                  init: Optional[np.ndarray] = None) -> np.ndarray:
    """Read word2vec TEXT format ('n dim' header optional; then
    'word v1 v2 ...' lines) into a [len(vocab), dim] table. Words missing
    from the file keep their `init` rows (or zeros)."""
    table = (np.array(init, np.float32) if init is not None
             else np.zeros((len(vocab), dim), np.float32))
    found = 0
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        first = fh.readline().split()
        rows = []
        if len(first) == 2 and all(t.lstrip("-").isdigit() for t in first):
            pass                                       # header line
        elif first:
            rows.append(first)
        for line in fh:
            parts = line.rstrip("\n").split(" ")
            if len(parts) > 2:
                rows.append(parts)
        for parts in rows:
            word = parts[0]
            vec = parts[1:]
            if len(vec) != dim:
                raise ValueError(
                    f"{path}: vector for '{word}' has {len(vec)} dims, "
                    f"expected {dim}")
            wid = vocab[word]
            if wid == 1 and word != "<unk>":           # UNK = not in vocab
                continue
            table[wid] = np.asarray(vec, np.float32)
            found += 1
    log.info("Loaded {} pretrained vectors from {} ({} vocab rows)",
             found, path, len(table))
    return table


def load_word2vec_raw(path: str) -> Tuple[list, np.ndarray]:
    """Read a word2vec text file as (words, [n, dim] matrix) without a
    vocabulary — used for the ULR universal key table, whose rows are
    universal tokens, not target-vocab entries."""
    words, vecs = [], []
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        first = fh.readline().split()
        if not (len(first) == 2 and all(t.lstrip("-").isdigit()
                                        for t in first)):
            words.append(first[0])
            vecs.append(np.asarray(first[1:], np.float32))
        for line in fh:
            parts = line.rstrip("\n").split(" ")
            if len(parts) > 2:
                words.append(parts[0])
                vecs.append(np.asarray(parts[1:], np.float32))
    return words, np.stack(vecs) if vecs else np.zeros((0, 0), np.float32)


def normalize_rows(table: np.ndarray, eps: float = 1e-8) -> np.ndarray:
    """--embedding-normalization: unit-L2 rows."""
    norm = np.linalg.norm(table, axis=-1, keepdims=True)
    return table / np.maximum(norm, eps)
