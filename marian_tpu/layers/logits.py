"""Factored output combination and factored embedding composition.

Rebuild of reference src/layers/logits.cpp :: Logits (group-wise factored
softmax) and the factored path of src/layers/embedding.cpp. The reference
keeps one logits tensor per factor group and combines them lazily; under
XLA we compute the unit-axis scores in ONE matmul (all groups share the
output matrix over the unit axis), take a log-softmax per group slice, and
gather-sum back to word space — fully fused, static shapes.

Semantics (same as Marian): P(word) = P(lemma) * Π_g P(factor_g(word)),
each distribution normalized within its own group; absent factors (PAD
unit) contribute log-prob 0.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(eq=False)
class FactorTables:
    """Static per-vocab factor metadata closed over by the jitted model.
    Built from data.factored_vocab.FactoredVocab."""
    n_units: int
    n_lemmas: int
    pad_unit: int
    factor_indices: np.ndarray                 # [V, K] int32 (K = 1+groups)
    group_slices: Tuple[Tuple[str, int, int], ...]

    @classmethod
    def from_vocab(cls, vocab) -> "FactorTables":
        return cls(n_units=vocab.n_units, n_lemmas=vocab.n_lemmas,
                   pad_unit=vocab.pad_unit,
                   factor_indices=np.asarray(vocab.factor_indices, np.int32),
                   group_slices=vocab.group_slices)

    @property
    def vocab_size(self) -> int:
        return self.factor_indices.shape[0]


def factored_embed(table: jax.Array, ft: FactorTables,
                   ids: jax.Array, dtype) -> jax.Array:
    """emb(word) = emb(lemma) + Σ_g emb(factor_g) (reference: factored
    embedding composition). `table` is [n_units, D]; PAD contributions are
    masked out (no trainable PAD bias)."""
    idx = jnp.asarray(ft.factor_indices)[ids]          # [..., K]
    gathered = table[idx].astype(dtype)                # [..., K, D]
    mask = (idx != ft.pad_unit)[..., None].astype(dtype)
    return (gathered * mask).sum(axis=-2)              # [..., D]


def factored_embed_concat(lemma_table: jax.Array, factor_table: jax.Array,
                          ft: FactorTables, ids: jax.Array,
                          dtype) -> jax.Array:
    """--factors-combine concat (reference: src/layers/embedding.cpp
    concatenative composition): emb(word) = [emb(lemma);
    emb(factor_1); ...; emb(factor_G)] with a (dim_emb - G*f)-wide lemma
    table and f-wide per-factor vectors. `factor_table` rows are the factor
    units in unit order with the PAD unit as its LAST row; absent factors
    contribute a zero block (masked, no trainable PAD bias)."""
    idx = jnp.asarray(ft.factor_indices)[ids]              # [..., K]
    parts = [lemma_table[idx[..., 0]].astype(dtype)]       # lemma column
    for kcol in range(1, idx.shape[-1]):
        u = idx[..., kcol] - ft.n_lemmas                   # factor-row index
        mask = (idx[..., kcol] != ft.pad_unit)[..., None].astype(dtype)
        parts.append(factor_table[u].astype(dtype) * mask)
    return jnp.concatenate(parts, axis=-1)


def factored_log_probs(unit_logits: jax.Array, ft: FactorTables,
                       shortlist: Optional[jax.Array] = None,
                       factor_weight: float = 1.0) -> jax.Array:
    """[..., n_units] unit scores → [..., V] word log-probs.

    Per-group log-softmax over each unit slice, then for every word sum the
    log-probs of its units (reference: Logits::getLoss /
    Logits::getLogits combination). With a shortlist, only the shortlisted
    words' rows of the index table are gathered (output [..., K_sl]).
    `factor_weight` (--factor-weight) scales the non-lemma groups'
    contributions (reference: Logits applying factorWeight_)."""
    pieces = []
    for gi, (_name, start, end) in enumerate(ft.group_slices):
        lp = jax.nn.log_softmax(unit_logits[..., start:end], axis=-1)
        if gi > 0 and factor_weight != 1.0:    # group 0 is the lemma
            lp = lp * factor_weight
        pieces.append(lp)
    # PAD unit (last) gets log-prob 0 so absent factors are no-ops
    logp = jnp.concatenate(
        pieces + [jnp.zeros_like(unit_logits[..., -1:])], axis=-1)

    idx_tbl = jnp.asarray(ft.factor_indices)           # [V, K]
    if shortlist is not None:
        idx_tbl = idx_tbl[shortlist]                   # [K_sl, K]
    out = None
    # accumulate per factor column: peak memory [..., V], not [..., V, K]
    for k in range(idx_tbl.shape[1]):
        contrib = jnp.take(logp, idx_tbl[:, k], axis=-1)
        out = contrib if out is None else out + contrib
    return out
