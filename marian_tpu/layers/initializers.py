"""Parameter initializers (reference: src/graph/node_initializers.cpp ::
inits::glorotUniform/glorotNormal/he etc.). All return f32 numpy-compatible
jax arrays; fromItem (checkpoint load) lives in common/io.py."""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def glorot_uniform(key: jax.Array, shape: Sequence[int],
                   fan_in: int = 0, fan_out: int = 0, scale: float = 1.0) -> jax.Array:
    if not fan_in:
        fan_in = shape[0] if len(shape) > 1 else shape[-1]
    if not fan_out:
        fan_out = shape[-1]
    limit = scale * math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, tuple(shape), jnp.float32, -limit, limit)


def glorot_normal(key: jax.Array, shape: Sequence[int],
                  fan_in: int = 0, fan_out: int = 0, scale: float = 1.0) -> jax.Array:
    if not fan_in:
        fan_in = shape[0] if len(shape) > 1 else shape[-1]
    if not fan_out:
        fan_out = shape[-1]
    std = scale * math.sqrt(2.0 / (fan_in + fan_out))
    return jax.random.normal(key, tuple(shape), jnp.float32) * std


def uniform(key: jax.Array, shape: Sequence[int], scale: float = 0.1) -> jax.Array:
    return jax.random.uniform(key, tuple(shape), jnp.float32, -scale, scale)


def normal(key: jax.Array, shape: Sequence[int], std: float = 1.0) -> jax.Array:
    return jax.random.normal(key, tuple(shape), jnp.float32) * std


def zeros(shape: Sequence[int]) -> jax.Array:
    return jnp.zeros(tuple(shape), jnp.float32)


def ones(shape: Sequence[int]) -> jax.Array:
    return jnp.ones(tuple(shape), jnp.float32)
