"""Loss assembly: cross-entropy with label smoothing, cost-type
normalization, guided-alignment aux loss, data weighting.

Rebuild of reference src/layers/loss.cpp :: CrossEntropyLoss/RationalLoss/
MultiRationalLoss and src/layers/guided_alignment.cpp. A loss is carried as
(sum, label_count) — Marian's "rational loss" — so ce-sum / ce-mean /
ce-mean-words / perplexity are different finalizations of the same pair.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.ops import cross_entropy


@dataclasses.dataclass
class RationalLoss:
    loss_sum: jax.Array   # scalar f32
    labels: jax.Array     # scalar f32 (real target labels in batch)

    def value(self, cost_type: str = "ce-sum") -> jax.Array:
        if cost_type in ("ce-sum", "ce-rescore"):
            return self.loss_sum
        if cost_type == "ce-mean-words":
            return self.loss_sum / jnp.maximum(self.labels, 1.0)
        if cost_type == "perplexity":
            return jnp.exp(self.loss_sum / jnp.maximum(self.labels, 1.0))
        if cost_type == "ce-mean":
            # per-sentence mean is handled by caller passing sentence count
            return self.loss_sum / jnp.maximum(self.labels, 1.0)
        raise ValueError(f"Unknown cost-type {cost_type}")


def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       mask: jax.Array, label_smoothing: float = 0.0,
                       data_weights: Optional[jax.Array] = None,
                       unlikelihood: bool = False) -> RationalLoss:
    """logits [B,T,V], labels [B,T], mask [B,T] → summed CE over real tokens.

    unlikelihood (--unlikelihood-loss, reference: layers/loss.h ::
    SequenceUnlikelihoodLoss): the sign of the data weight selects the
    objective per token — weight > 0 trains likelihood (-w·log p), weight
    < 0 trains UNlikelihood (-|w|·log(1-p)), pushing probability away from
    tokens marked as negative evidence."""
    w = mask.astype(jnp.float32)
    if unlikelihood and data_weights is not None:
        dw = jnp.broadcast_to(data_weights.astype(jnp.float32), w.shape)
        pos = dw >= 0
        ce_like = cross_entropy(logits, labels, label_smoothing)      # [B,T]
        logp = -cross_entropy(logits, labels, 0.0)                    # log p
        # log(1-p) = log1p(-exp(logp)), clamped away from logp==0
        log1mp = jnp.log1p(-jnp.exp(jnp.minimum(logp, -1e-6)))
        ce = jnp.where(pos, ce_like, -log1mp)
        w = w * jnp.abs(dw)
        return RationalLoss(jnp.sum(ce * w),
                            jnp.sum(mask.astype(jnp.float32)))
    ce = cross_entropy(logits, labels, label_smoothing)  # [B,T] f32
    if data_weights is not None:
        w = w * jnp.broadcast_to(data_weights.astype(jnp.float32), w.shape)
    return RationalLoss(jnp.sum(ce * w), jnp.sum(mask.astype(jnp.float32)))


def guided_alignment_loss(attn: jax.Array, guided: jax.Array,
                          trg_mask: jax.Array, cost_type: str = "ce",
                          eps: float = 1e-6) -> jax.Array:
    """attn, guided: [B, Tt, Ts] (normalized rows); per-token CE between
    soft attention and the guided alignment (reference:
    guided_alignment.cpp :: guidedAlignmentCost)."""
    a = attn.astype(jnp.float32)
    g = guided.astype(jnp.float32)
    if cost_type == "ce":
        per_tok = -jnp.sum(g * jnp.log(a + eps), axis=-1)
    elif cost_type == "mse":
        per_tok = 0.5 * jnp.sum(jnp.square(a - g), axis=-1)
    elif cost_type == "mult":
        per_tok = -jnp.log(jnp.sum(a * g, axis=-1) + eps)
    else:
        raise ValueError(f"Unknown guided-alignment-cost {cost_type}")
    # only count target positions that have at least one alignment point
    has_pt = (jnp.sum(g, axis=-1) > 0).astype(jnp.float32) * trg_mask.astype(jnp.float32)
    n = jnp.maximum(jnp.sum(has_pt), 1.0)
    return jnp.sum(per_tok * has_pt) / n
