"""mtlint — JAX/TPU-aware static analysis for marian-tpu (ISSUE 2).

Six rule families over stdlib `ast`, no third-party deps and no import of
the linted code:

  trace-safety  MT-TRACE-COND/-CAST/-NUMPY   concretization & recompiles
                                             inside jit/pjit/shard_map
  host-sync     MT-SYNC-TIMER/-TRANSFER      un-synced timing + implicit
                                             device->host copies in hot dirs
  donation      MT-DONATE-READ               use-after-donate_argnums
  dtype         MT-DTYPE-LITERAL/-ARRAY      bf16-upcast hazards in ops/layers
  guarded-by    MT-LOCK-GUARD/-UNKNOWN       `# guarded-by: <lock>` race lint
                                             for the threaded serving layer
  metrics       MT-METRIC-UNUSED/-UNREG      Prometheus registry vs emission

Run `python -m marian_tpu.analysis` (or scripts/mtlint.py); the checked-in
baseline marian_tpu/analysis/baseline.json makes the pass a hard tier-1
gate (tests/test_mtlint.py). Full docs: docs/STATIC_ANALYSIS.md.
"""

from .core import (Config, Finding, Source, apply_baseline,  # noqa: F401
                   load_baseline, run_lint, write_baseline)
from .cli import main  # noqa: F401
