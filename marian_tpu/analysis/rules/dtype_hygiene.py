"""dtype-hygiene (MT-DTYPE-*): bf16-upcast hazards in the compute layers.

On TPU the compute dtype is bf16 wherever we can get away with it; one
f32-dtyped operand silently promotes the whole surrounding expression and
the MXU runs at 1/4 rate (see docs/PERFORMANCE.md). Two statically
detectable shapes, checked in the configured dtype dirs (ops/, layers/):

- MT-DTYPE-LITERAL: arithmetic mixing a bare Python float literal with an
  array whose dtype is not locally pinned. JAX's weak typing makes
  `0.5 * x` harmless when `x` really is bf16 — the hazard is that nothing
  in the expression says what `x` is, so an upstream f32 (a mask built with
  a float32 default, a numpy leak) upcasts the whole chain unnoticed. An
  operand whose dtype is locally evident (`x.astype(d)`, `jnp.zeros(...,
  dtype=d)`, a value assigned from either) is exempt: the literal then
  provably follows the pinned dtype.

- MT-DTYPE-ARRAY: `jnp.array/zeros/ones/full/empty(...)` without an
  explicit dtype — these default to f32 (or weak int), and a f32 constant
  table multiplied into a bf16 activation is exactly the silent upcast.

The inference is per-function and flow-insensitive on purpose: it must
never claim more than the source text shows a reader.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ..core import Config, Finding, Source, ancestors, call_name
from . import Rule, register

# classification lattice values
SCALAR = "scalar"
ARRAY = "array"          # array-typed, dtype not locally evident
PINNED = "pinned"        # array-typed, dtype locally pinned
UNKNOWN = "unknown"

# jnp constructors with a positional dtype slot (index into args)
CTOR_DTYPE_SLOT = {"array": 1, "zeros": 1, "ones": 1, "empty": 1, "full": 2,
                   "asarray": 1}
# constructors MT-DTYPE-ARRAY requires an explicit dtype on (asarray is
# exempt: passing an existing array through preserves its dtype by design)
CTOR_REQUIRE_DTYPE = {"array", "zeros", "ones", "empty", "full"}
# calls that follow their argument's dtype
LIKE_CTORS = {"zeros_like", "ones_like", "full_like", "empty_like"}
SCALAR_ANNOTATIONS = {"int", "float", "bool", "str", "complex"}


def _dtype_given(node: ast.Call, tail: str) -> bool:
    if any(kw.arg == "dtype" for kw in node.keywords):
        return True
    slot = CTOR_DTYPE_SLOT.get(tail)
    return slot is not None and len(node.args) > slot


class _Classifier:
    def __init__(self, env: Dict[str, str]):
        self.env = env

    def classify(self, node: ast.AST) -> str:
        if isinstance(node, ast.Constant):
            return SCALAR
        if isinstance(node, ast.Name):
            return self.env.get(node.id, UNKNOWN)
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return self.classify(node.value)
        if isinstance(node, ast.UnaryOp):
            return self.classify(node.operand)
        if isinstance(node, ast.BinOp):
            left, right = self.classify(node.left), self.classify(node.right)
            if PINNED in (left, right):
                return PINNED
            if ARRAY in (left, right):
                return ARRAY
            if left == right == SCALAR:
                return SCALAR
            return UNKNOWN
        if isinstance(node, ast.Call):
            return self._classify_call(node)
        if isinstance(node, ast.Attribute):
            # x.T / x.dtype etc: attribute of an array is not itself known
            return UNKNOWN
        return UNKNOWN

    def _classify_call(self, node: ast.Call) -> str:
        name = call_name(node) or ""
        parts = name.split(".")
        if parts[-1] == "astype":
            return PINNED
        root, tail = parts[0], parts[-1]
        if root in ("jnp", "jax"):
            if _dtype_given(node, tail):
                return PINNED
            if tail in LIKE_CTORS and node.args:
                return self.classify(node.args[0])
            # elementwise/reduction jnp ops preserve a pinned operand
            if any(self.classify(a) == PINNED for a in node.args):
                return PINNED
            return ARRAY
        return UNKNOWN


def _annotation_class(ann: Optional[ast.AST]) -> str:
    if ann is None:
        return UNKNOWN
    src = ast.dump(ann)
    if any(f"'{t}'" in src for t in SCALAR_ANNOTATIONS) \
            and "Array" not in src:
        return SCALAR
    if "Array" in src or "'jnp'" in src or "'jax'" in src:
        return ARRAY
    return UNKNOWN


def _build_env(fn: ast.AST, classifier_env: Dict[str, str]) -> Dict[str, str]:
    env = dict(classifier_env)
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = fn.args
        for p in [*a.posonlyargs, *a.args, *a.kwonlyargs]:
            env[p.arg] = _annotation_class(p.annotation)
    cls = _Classifier(env)
    # two passes: simple forward propagation through assignments
    for _ in range(2):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                env[node.targets[0].id] = cls.classify(node.value)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                c = _annotation_class(node.annotation)
                if c == UNKNOWN and node.value is not None:
                    c = cls.classify(node.value)
                env[node.target.id] = c
    return env


def _under_astype(node: ast.AST) -> bool:
    """Literal arithmetic that is immediately recast (`(...).astype(d)`)
    cannot leak its promoted dtype downstream."""
    for anc in ancestors(node):
        if isinstance(anc, ast.Attribute) and anc.attr == "astype":
            return True
        if isinstance(anc, ast.stmt):
            break
    return False


@register
class DtypeHygieneRule(Rule):
    family = "dtype"
    ids = ("MT-DTYPE-LITERAL", "MT-DTYPE-ARRAY")

    def check(self, src: Source, config: Config) -> List[Finding]:
        findings: List[Finding] = []
        findings.extend(self._check_ctors(src))
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_literals(src, node))
        return findings

    def _check_ctors(self, src: Source) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            parts = name.split(".")
            if parts[0] != "jnp" or parts[-1] not in CTOR_REQUIRE_DTYPE:
                continue
            if not _dtype_given(node, parts[-1]):
                out.append(src.finding(
                    "MT-DTYPE-ARRAY", node,
                    f"`{name}(...)` without an explicit dtype — defaults to "
                    f"f32 and silently upcasts any bf16 arithmetic it "
                    f"touches",
                    hint="pass dtype= (the compute dtype, or the operand's "
                         "x.dtype)"))
        return out

    def _check_literals(self, src: Source, fn: ast.AST) -> List[Finding]:
        env = _build_env(fn, {})
        cls = _Classifier(env)
        out: List[Finding] = []
        seen = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.BinOp):
                continue
            sides = [(node.left, node.right), (node.right, node.left)]
            for lit, other in sides:
                if not (isinstance(lit, ast.Constant)
                        and isinstance(lit.value, float)):
                    continue
                if cls.classify(other) != ARRAY:
                    continue
                if _under_astype(node):
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                out.append(src.finding(
                    "MT-DTYPE-LITERAL", node,
                    f"float literal `{lit.value}` in arithmetic with an "
                    f"array of locally-unknown dtype — if the array is ever "
                    f"f32 (mask default, numpy leak) the whole chain "
                    f"upcasts off the bf16 path",
                    hint="pin the array operand's dtype in this expression "
                         "(x.astype(d) / a dtype= constructor), or "
                         "`# mtlint: ok -- <why the dtype is safe>`"))
                break
        return out
