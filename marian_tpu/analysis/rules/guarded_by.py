"""guarded-by (MT-LOCK-*): a lightweight static race detector for the
threaded layers (serving/, training/).

Convention (docs/STATIC_ANALYSIS.md): an instance attribute whose
assignment line carries

    self._queued = 0            # guarded-by: _state_lock

may only be touched inside `with self._state_lock:` anywhere in the class.
`__init__` is exempt (construction happens-before publication to other
threads). A helper that is documented to be called with the lock already
held declares it on its `def` line (or the line above):

    def _sweep_locked(self):    # mtlint: holds _state_lock

MT-LOCK-GUARD fires on any other access. MT-LOCK-UNKNOWN fires when an
annotation names a lock the class never assigns — a stale annotation is
worse than none.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from ..core import Config, Finding, Source, ancestors, dotted_name
from . import Rule, register

GUARD_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
HOLDS_RE = re.compile(r"mtlint:\s*holds\s+([A-Za-z_][A-Za-z0-9_]*)")
EXEMPT_METHODS = {"__init__", "__new__", "__del__"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _held_locks(src: Source, fn: ast.FunctionDef) -> Set[str]:
    held: Set[str] = set()
    for line in (fn.lineno, fn.lineno - 1):
        m = HOLDS_RE.search(src.comments.get(line, ""))
        if m:
            held.add(m.group(1))
    return held


def _locks_in_scope(node: ast.AST, fn: ast.AST) -> Set[str]:
    """Locks held at `node` by lexically-enclosing with-blocks inside fn."""
    held: Set[str] = set()
    for anc in ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                d = dotted_name(item.context_expr)
                if d and d.startswith("self."):
                    held.add(d[len("self."):])
        if anc is fn:
            break
    return held


@register
class GuardedByRule(Rule):
    family = "guarded-by"
    ids = ("MT-LOCK-GUARD", "MT-LOCK-UNKNOWN")

    def check(self, src: Source, config: Config) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(src, node))
        return findings

    def _check_class(self, src: Source, cls: ast.ClassDef) -> List[Finding]:
        guarded: Dict[str, str] = {}       # attr -> lock name
        assigned_attrs: Set[str] = set()
        annotation_nodes: Dict[str, ast.AST] = {}
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    assigned_attrs.add(attr)
                    m = GUARD_RE.search(src.comments.get(node.lineno, ""))
                    if m:
                        guarded[attr] = m.group(1)
                        annotation_nodes[attr] = node
        if not guarded:
            return []
        findings: List[Finding] = []
        for attr, lock in guarded.items():
            if lock not in assigned_attrs:
                findings.append(src.finding(
                    "MT-LOCK-UNKNOWN", annotation_nodes[attr],
                    f"`{attr}` is annotated guarded-by: {lock}, but the "
                    f"class never assigns `self.{lock}`",
                    hint="fix the annotation or create the lock in "
                         "__init__"))
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name in EXEMPT_METHODS:
                continue
            declared_held = _held_locks(src, fn)
            for node in ast.walk(fn):
                attr = _self_attr(node)
                if attr is None or attr not in guarded:
                    continue
                lock = guarded[attr]
                if lock in declared_held:
                    continue
                if lock in _locks_in_scope(node, fn):
                    continue
                access = ("write" if isinstance(getattr(node, "ctx", None),
                                                (ast.Store, ast.Del))
                          else "read")
                findings.append(src.finding(
                    "MT-LOCK-GUARD", node,
                    f"{access} of `self.{attr}` in `{fn.name}` outside "
                    f"`with self.{lock}:` (annotated guarded-by: {lock})",
                    hint=f"wrap the access in `with self.{lock}:`, or mark "
                         f"the method `# mtlint: holds {lock}` if every "
                         f"caller provably holds it"))
        return findings
