"""donation (MT-DONATE-READ): use-after-donate.

`jax.jit(..., donate_argnums=(i,))` hands argument i's device buffer to the
compiled program — after the call the caller's reference is a deleted
buffer, and touching it raises (or, on some backends, silently reads
garbage). The classic bug shape:

    step = jax.jit(train_step, donate_argnums=(0,))
    new_params = step(params, batch)
    log_norm(params)          # <- donated buffer

The pass maps names bound to jit-wrapped callables with literal
donate_argnums, then flags reads of a donated (dotted) argument name after
the call in the same function body, unless the name was reassigned first —
the standard `params = step(params, ...)` rebinding is clean.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import (Config, Finding, Source, call_name, const_int_tuple,
                    dotted_name, parent)
from . import Rule, register


def _donating_bindings(tree: ast.Module) -> Dict[str, Set[int]]:
    """name -> donated positions, for `X = jax.jit(f, donate_argnums=...)`
    and `X = pjit(f, donate_argnums=...)` bindings (incl. self.X)."""
    out: Dict[str, Set[int]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        fn = call_name(node.value) or ""
        if fn.split(".")[-1] not in ("jit", "pjit"):
            continue
        donated: Set[int] = set()
        for kw in node.value.keywords:
            if kw.arg == "donate_argnums":
                vals = const_int_tuple(kw.value)
                if vals is None and isinstance(kw.value, ast.IfExp):
                    # `donate_argnums=(0, 1) if flag else ()` — take the
                    # donating branch: a MAY-donate read is still a bug
                    vals = (const_int_tuple(kw.value.body)
                            or const_int_tuple(kw.value.orelse))
                donated.update(vals or ())
        if not donated:
            continue
        for t in node.targets:
            name = dotted_name(t)
            if name:
                out[name] = donated
    return out


def _enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    p = parent(node)
    while p is not None and not isinstance(
            p, (ast.FunctionDef, ast.AsyncFunctionDef)):
        p = parent(p)
    return p


def _assign_targets(stmt: ast.AST) -> Set[str]:
    targets: Set[str] = set()
    if isinstance(stmt, ast.Assign):
        tlist = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        tlist = [stmt.target]
    else:
        return targets
    for t in tlist:
        for n in ast.walk(t):
            d = dotted_name(n)
            if d:
                targets.add(d)
    return targets


@register
class DonationRule(Rule):
    family = "donation"
    ids = ("MT-DONATE-READ",)

    def check(self, src: Source, config: Config) -> List[Finding]:
        donating = _donating_bindings(src.tree)
        if not donating:
            return []
        findings: List[Finding] = []
        for fn in ast.walk(src.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_fn(src, fn, donating))
        return findings

    def _check_fn(self, src: Source, fn: ast.AST,
                  donating: Dict[str, Set[int]]) -> List[Finding]:
        # donated-arg call sites in this function:
        # (call END line — a multi-line call's own args are not "after" it,
        #  arg name, callee)
        donated_at: List[Tuple[int, str, str]] = []
        for node in ast.walk(fn):
            if _enclosing_function(node) is not fn:
                continue
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee not in donating:
                continue
            end = getattr(node, "end_lineno", node.lineno) or node.lineno
            for pos in donating[callee]:
                if pos < len(node.args):
                    arg = dotted_name(node.args[pos])
                    if arg:
                        # `x = step(x, ...)` rebinding in the same statement
                        stmt = parent(node)
                        while stmt is not None and not isinstance(
                                stmt, ast.stmt):
                            stmt = parent(stmt)
                        if stmt is not None and arg in _assign_targets(stmt):
                            continue
                        donated_at.append((end, arg, callee))
        if not donated_at:
            return []
        # reassignment lines per dotted name, read lines per dotted name
        reassigned: Dict[str, List[int]] = {}
        reads: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(fn):
            if _enclosing_function(node) is not fn:
                continue
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for name in _assign_targets(node):
                    reassigned.setdefault(name, []).append(node.lineno)
            d = dotted_name(node)
            if d and isinstance(getattr(node, "ctx", None), ast.Load):
                reads.setdefault(d, []).append(node)
        out: List[Finding] = []
        flagged = set()
        for call_line, arg, callee in donated_at:
            for read in reads.get(arg, []):
                if read.lineno <= call_line:
                    continue
                # a reassignment between the call and the read cleans it
                if any(call_line <= ln <= read.lineno
                       for ln in reassigned.get(arg, [])):
                    continue
                key = (arg, read.lineno)
                if key in flagged:
                    continue
                flagged.add(key)
                out.append(src.finding(
                    "MT-DONATE-READ", read,
                    f"`{arg}` read after being passed to `{callee}` in a "
                    f"donate_argnums position (line {call_line}) — the "
                    f"buffer was donated to the compiled program",
                    hint="rebind the result over the donated name, or drop "
                         "the argument from donate_argnums"))
        return out
