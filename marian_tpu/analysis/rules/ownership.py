"""ownership (MT-OWN-*): static resource-ownership & leak analysis
(ISSUE 15 tentpole — the lock lattice's sibling for resource lifetimes).

The verb registry, annotation vocabulary, and the ownership graph live
in ``analysis/ownership.py``; this module is the per-function
path-sensitive acquire/release dataflow over it:

- **MT-OWN-LEAK** — a resource acquired in a function (a ``KVPool``
  claim, an executor/engine/file/thread handle) reaches a function exit
  — including exception edges (a later registered acquire can raise
  ``PoolExhausted`` while this is held; explicit ``raise``) and early
  returns — with no release/transfer on some path, and no boundary
  annotation blessing the handoff.
- **MT-OWN-DOUBLE** — a release/transfer of the same owner reachable
  twice on one path: the second call decrefs references the owner no
  longer holds (and ``KVPool.release`` of a gone owner is now a loud
  ``ValueError``).
- **MT-OWN-ESCAPE** — an owned handle aliased into a structure that
  outlives the owner (a ``self.*`` attribute, a ``self.*`` container,
  a closure) without a ``# mtlint: transfers`` annotation stating the
  handoff is deliberate.
- **MT-OWN-TRANSFER** — ownership crossing a function boundary through
  an unannotated door: a function that exits still holding what it
  acquired (the ``_claim_pages`` wrapper shape) must say
  ``# owns: caller``; a function that releases/transfers a handle its
  caller passed in (the ``_evict``/``adopt`` shape) must say
  ``# owns: callee`` — mirroring ``# guarded-by:``.

Two obligation styles (see ownership.REGISTRY): **owner-keyed**
(kv-pages — the verb's first argument IS the handle; the owner name
flowing through unrelated code is free, only registered verbs move
ownership; an owner name rebound by a loop/plain assignment denotes
different owners over time and is exempt from DOUBLE) and **binding**
(executor/worker/engine/file — the call RESULT is the handle; passing
it to another callee hands the lifetime to someone else and ends local
analysis, the span-rule precedent). The ``span`` class's per-function
lifetime rules stay with the MT-SPAN family — registering its sites
here without checking them twice.

The rules are deliberately cheap where the runtime side is strong: the
pool auditor catches a leak at runtime, the ownership witness
(common/ownwit.py) fails tier-1 when reality exercises a pairing this
model never derived — "the auditor catches it at runtime, mtlint
proves it can't happen" (docs/STATIC_ANALYSIS.md).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Config, Finding, Source, dotted_name, parent
from ..ownership import (BINDING_CLASSES, OWNER_KEYED_CLASSES,
                         line_transfers, match_verb, owner_expr,
                         owns_annotation)
from . import Rule, register

# obligation state: (held, releases) with releases capped at 2
State = Tuple[int, int]


def _owner_fn(node: ast.AST) -> Optional[ast.AST]:
    cur = parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parent(cur)
    return None


def _fn_params(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = {p.arg for p in (list(a.posonlyargs) + list(a.args)
                             + list(a.kwonlyargs))}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names


def _assigned_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                for nn in ast.walk(t):
                    if isinstance(nn, ast.Name):
                        out.add(nn.id)
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            for nn in ast.walk(n.target):
                if isinstance(nn, ast.Name):
                    out.add(nn.id)
        elif isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                if item.optional_vars is not None:
                    for nn in ast.walk(item.optional_vars):
                        if isinstance(nn, ast.Name):
                            out.add(nn.id)
    return out


class _Obligation:
    __slots__ = ("cls", "owner", "style", "acquire_node", "is_boundary",
                 "rebound")

    def __init__(self, cls: str, owner: str, style: str,
                 acquire_node: ast.Call):
        self.cls = cls
        self.owner = owner          # owner dotted name / binding var
        self.style = style          # "owner" | "binding"
        self.acquire_node = acquire_node
        self.is_boundary = False    # owner is a param or free variable
        self.rebound = False        # owner name rebound by non-verb code


class _Walk:
    """Path-sensitive execution of one function body against ONE
    obligation. States are tiny (held, releases) tuples; joins are set
    unions, loops run to a bounded fixpoint, Try routes the raise
    channel through handlers and finally."""

    def __init__(self, rule: "OwnershipRule", src: Source, fn: ast.AST,
                 ob: _Obligation, findings: List[Finding]):
        self.rule = rule
        self.src = src
        self.fn = fn
        self.ob = ob
        self.findings = findings
        self.reported: Set[Tuple[str, int]] = set()
        # exception states that escape the function (exception edges)
        self.fn_raise: Set[State] = set()
        self.fn_ret: Set[State] = set()

    # -- channels -----------------------------------------------------------
    @staticmethod
    def _ch(fall=frozenset()):
        return {"fall": set(fall), "raise": set(), "ret": set(),
                "brk": set(), "cont": set()}

    @staticmethod
    def _merge(dst, src_ch, skip=("fall",)):
        for k in ("raise", "ret", "brk", "cont"):
            if k not in skip:
                dst[k] |= src_ch[k]

    def _report(self, rule_id: str, node: ast.AST, message: str,
                hint: str = "") -> None:
        key = (rule_id, getattr(node, "lineno", 0))
        if key in self.reported:
            return
        self.reported.add(key)
        self.findings.append(self.src.finding(rule_id, node, message, hint))

    # -- effects of one statement's expressions ------------------------------
    def _events(self, node: ast.AST):
        """(sort_key, kind, astnode, verb) events inside ``node`` in
        source order. Kinds: acquire/release/transfer for this
        obligation; 'mayraise' for registered raisers affecting any
        obligation; binding-style escapes."""
        ob = self.ob
        events = []
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue          # nested defs are their own pass
            if isinstance(n, ast.Call):
                v = match_verb(n)
                if v is not None and v.may_raise:
                    events.append(((n.lineno, n.col_offset, 0),
                                   "mayraise", n, v))
                if v is None or v.cls != ob.cls:
                    if ob.style == "binding":
                        ev = self._binding_escape_in_call(n)
                        if ev:
                            events.append(((n.lineno, n.col_offset, 1),
                                           ev, n, None))
                    continue
                if ob.style == "owner":
                    oe = owner_expr(n, v)
                    if oe is None or dotted_name(oe) != ob.owner:
                        continue
                    events.append(((n.lineno, n.col_offset, 1),
                                   v.kind, n, v))
                else:
                    # binding style: acquire only via THE binding
                    # assignment; release via `var.close()` etc.
                    if n is ob.acquire_node:
                        events.append(((n.lineno, n.col_offset, 1),
                                       "acquire", n, v))
                    elif v.kind in ("release", "transfer") \
                            and self._recv_base(n) == ob.owner:
                        events.append(((n.lineno, n.col_offset, 1),
                                       v.kind, n, v))
        events.sort(key=lambda e: e[0])
        return events

    @staticmethod
    def _recv_base(call: ast.Call) -> Optional[str]:
        d = dotted_name(call.func)
        return d.split(".")[0] if d else None

    @staticmethod
    def _handle_escapes_in(node: ast.AST, var: str) -> bool:
        """True when the HANDLE itself appears in value position under
        ``node``. A Name that is an Attribute receiver (`fh.read()`,
        `ex.submit`) is a use of the handle, not an escape of it."""
        return any(isinstance(n, ast.Name) and n.id == var
                   and not isinstance(parent(n), ast.Attribute)
                   for n in ast.walk(node))

    def _binding_escape_in_call(self, call: ast.Call) -> Optional[str]:
        """The binding handle passed to an unregistered callee: its
        lifetime is someone else's contract (span-rule precedent) —
        silently ends tracking, EXCEPT when the callee is a self-owned
        container/method (`self._x.append(fh)`), which is the
        aliased-into-an-outliving-structure case MT-OWN-ESCAPE names."""
        var = self.ob.owner
        hit = any(self._handle_escapes_in(sub, var)
                  for sub in (list(call.args)
                              + [kw.value for kw in call.keywords]))
        if not hit:
            return None
        callee = dotted_name(call.func) or ""
        return "escape-store" if callee.startswith("self.") \
            else "escape-silent"

    def _apply(self, node: ast.AST, S: Set[State],
               raise_sink: Set[State]) -> Set[State]:
        """Run ``node``'s events over the state set."""
        ob = self.ob
        for _, kind, n, _v in self._events(node):
            if kind == "mayraise":
                raise_sink |= set(S)      # pre-call states escape
                continue                  # a same-call acquire/release
                #                           effect arrives as its own event
            if kind == "acquire":
                S = {(1, rel) for (_h, rel) in S}
            elif kind in ("release", "transfer"):
                for (h, rel) in S:
                    if h == 0 and rel >= 1 and not ob.rebound:
                        self._report(
                            "MT-OWN-DOUBLE", n,
                            f"`{ob.owner}` ({ob.cls}) is released/"
                            f"transferred twice on one path — the second "
                            f"call drops references the owner no longer "
                            f"holds",
                            hint="release exactly once per acquire; a "
                                 "transferred owner is gone")
                S = {(0, min(2, rel + 1)) for (_h, rel) in S}
            elif kind == "escape-store":
                if not line_transfers(self.src, n.lineno):
                    self._report(
                        "MT-OWN-ESCAPE", n,
                        f"owned handle `{ob.owner}` ({ob.cls}) is aliased "
                        f"into a structure that outlives this owner "
                        f"without a `# mtlint: transfers` annotation",
                        hint="annotate the deliberate handoff with "
                             "`# mtlint: transfers -- reason`, or release "
                             "before storing")
                S = {(0, rel) for (_h, rel) in S}
            elif kind == "escape-silent":
                S = {(0, rel) for (_h, rel) in S}
        return S

    def _stores_handle(self, stmt: ast.Assign) -> bool:
        """Binding handle stored into an attribute/subscript target."""
        var = self.ob.owner
        reads = any(isinstance(n, ast.Name) and n.id == var
                    for n in ast.walk(stmt.value))
        if not reads:
            return False
        return any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in stmt.targets)

    # -- statement walk -----------------------------------------------------
    def exec_block(self, stmts, S: Set[State]):
        ch = self._ch(S)
        for stmt in stmts:
            if not ch["fall"]:
                break
            sub = self.exec_stmt(stmt, ch["fall"])
            ch["fall"] = sub["fall"]
            self._merge(ch, sub)
        return ch

    def exec_stmt(self, stmt, S: Set[State]):
        ob = self.ob
        ch = self._ch()
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            ch["fall"] = set(S)
            return ch
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                S = self._apply(stmt.value, S, ch["raise"])
                if ob.style == "binding" and self._handle_escapes_in(
                        stmt.value, ob.owner):
                    # returning the handle: ownership crosses to the
                    # caller — blessed by `# owns: caller`
                    if owns_annotation(self.src, self.fn) != "caller":
                        self._report(
                            "MT-OWN-TRANSFER", stmt,
                            f"ownership of `{ob.owner}` ({ob.cls}) is "
                            f"returned to the caller without an "
                            f"`# owns: caller` annotation on the def",
                            hint="annotate the def line: "
                                 "`# owns: caller -- reason`")
                    S = {(0, rel) for (_h, rel) in S}
            ch["ret"] = set(S)
            return ch
        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                S = self._apply(stmt.exc, S, ch["raise"])
            ch["raise"] |= S
            return ch
        if isinstance(stmt, ast.Break):
            ch["brk"] = self._apply(stmt, S, ch["raise"])
            return ch
        if isinstance(stmt, ast.Continue):
            ch["cont"] = set(S)
            return ch
        if isinstance(stmt, ast.If):
            S = self._apply(stmt.test, S, ch["raise"])
            b = self.exec_block(stmt.body, S)
            o = self.exec_block(stmt.orelse, S)
            ch["fall"] = b["fall"] | o["fall"]
            self._merge(ch, b)
            self._merge(ch, o)
            return ch
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = stmt.test if isinstance(stmt, ast.While) else stmt.iter
            S = self._apply(head, S, ch["raise"])
            cur, seen = set(S), set(S)
            brk: Set[State] = set()
            for _ in range(4):                     # bounded fixpoint
                body = self.exec_block(stmt.body, cur)
                self._merge(ch, body, skip=("fall", "brk", "cont"))
                brk |= body["brk"]
                nxt = body["fall"] | body["cont"]
                if nxt <= seen:
                    break
                seen |= nxt
                cur = nxt
            o = self.exec_block(stmt.orelse, seen)
            self._merge(ch, o)
            ch["fall"] = o["fall"] | brk
            return ch
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                S = self._apply(item.context_expr, S, ch["raise"])
            body = self.exec_block(stmt.body, S)
            ch["fall"] = body["fall"]
            self._merge(ch, body)
            return ch
        if isinstance(stmt, ast.Try):
            body = self.exec_block(stmt.body, S)
            raised = body["raise"]
            fall = body["fall"]
            self._merge(ch, body, skip=("fall", "raise"))
            if stmt.handlers:
                # handlers consume the raised states (over-approx: any
                # handler may see any raise; only re-raises escape)
                for h in stmt.handlers:
                    hch = self.exec_block(h.body, set(raised))
                    fall |= hch["fall"]
                    self._merge(ch, hch)
                raised = set()
            o = self.exec_block(stmt.orelse, fall) if stmt.orelse \
                else self._ch(fall)
            fall = o["fall"]
            self._merge(ch, o)
            if stmt.finalbody:
                def through_final(states):
                    if not states:
                        return set()
                    return self.exec_block(stmt.finalbody, states)["fall"]
                fall = through_final(fall)
                ch["raise"] = through_final(ch["raise"] | raised)
                ch["ret"] = through_final(ch["ret"])
                ch["brk"] = through_final(ch["brk"])
                ch["cont"] = through_final(ch["cont"])
            else:
                ch["raise"] |= raised
            ch["fall"] = fall
            return ch
        # Assign / AugAssign / AnnAssign / Expr / everything else
        if isinstance(stmt, ast.Assign) and ob.style == "binding" \
                and self._stores_handle(stmt):
            S = self._apply(stmt.value, S, ch["raise"])
            if not line_transfers(self.src, stmt.lineno):
                self._report(
                    "MT-OWN-ESCAPE", stmt,
                    f"owned handle `{ob.owner}` ({ob.cls}) is stored "
                    f"into a longer-lived structure without a "
                    f"`# mtlint: transfers` annotation",
                    hint="annotate the deliberate handoff with "
                         "`# mtlint: transfers -- reason`")
            ch["fall"] = {(0, rel) for (_h, rel) in S}
            return ch
        ch["fall"] = self._apply(stmt, S, ch["raise"])
        return ch

    # -- verdict ------------------------------------------------------------
    def run(self) -> None:
        ob = self.ob
        ch = self.exec_block(self.fn.body, {(0, 0)})
        owns = owns_annotation(self.src, self.fn)
        exits = [("fall", ch["fall"]), ("ret", ch["ret"]),
                 ("raise", ch["raise"])]
        held_normal = any(h for kind, states in exits[:2]
                          for (h, _r) in states)
        held_raise = any(h for (h, _r) in ch["raise"])
        if not (held_normal or held_raise):
            return
        if owns == "caller":
            return          # acquisitions outlive this function by design
        if ob.is_boundary:
            self._report(
                "MT-OWN-TRANSFER", ob.acquire_node,
                f"resource acquired for caller-provided owner "
                f"`{ob.owner}` ({ob.cls}) is still held at function exit "
                f"— ownership crosses the boundary without an "
                f"`# owns: caller` annotation",
                hint="annotate the def line `# owns: caller -- reason`, "
                     "or release/transfer before returning")
            return
        where = ("some path to function exit" if held_normal
                 else "an exception path (a registered acquire can raise "
                      "while this is held)")
        self._report(
            "MT-OWN-LEAK", ob.acquire_node,
            f"resource `{ob.owner}` ({ob.cls}) acquired here is not "
            f"released or transferred on {where}",
            hint="release/transfer in a finally (or an except that "
                 "re-raises), annotate the def `# owns: caller`, or mark "
                 "a deliberate handoff `# mtlint: transfers`")


@register
class OwnershipRule(Rule):
    family = "ownership"
    ids = ("MT-OWN-LEAK", "MT-OWN-DOUBLE", "MT-OWN-ESCAPE",
           "MT-OWN-TRANSFER")
    scope = "file"

    def check(self, src: Source, config: Config) -> List[Finding]:
        findings: List[Finding] = []
        for fn in ast.walk(src.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(src, fn, findings)
        return findings

    def _check_function(self, src: Source, fn: ast.AST,
                        findings: List[Finding]) -> None:
        params = _fn_params(fn)
        assigned = _assigned_names(fn)
        obligations: Dict[Tuple[str, str], _Obligation] = {}
        released_only: Dict[Tuple[str, str], ast.Call] = {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or _owner_fn(node) is not fn:
                continue
            v = match_verb(node)
            if v is None or v.cls == "span":
                continue          # span lifetimes: the MT-SPAN family
            if v.cls in OWNER_KEYED_CLASSES:
                oe = owner_expr(node, v)
                owner = dotted_name(oe) if oe is not None else None
                if not owner:
                    continue      # expression-built owner: site only
                key = (v.cls, owner)
                if v.kind == "acquire":
                    ob = obligations.get(key)
                    if ob is None:
                        ob = _Obligation(v.cls, owner, "owner", node)
                        root = owner.split(".")[0]
                        ob.is_boundary = (root in params
                                          or (root != "self"
                                              and root not in assigned))
                        obligations[key] = ob
                elif owner.split(".")[0] in params:
                    released_only.setdefault(key, node)
            elif v.cls in BINDING_CLASSES and v.kind == "acquire":
                stmt = parent(node)
                # only direct `var = <ctor>(...)` bindings and direct
                # `self.x = <ctor>(...)` stores create obligations;
                # with-items own their handle, chained/unbound ctors
                # are out of local scope
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and stmt.value is node:
                    t = stmt.targets[0]
                    if isinstance(t, ast.Name):
                        key = (v.cls, t.id)
                        if key not in obligations:
                            obligations[key] = _Obligation(
                                v.cls, t.id, "binding", node)
                    elif isinstance(t, (ast.Attribute, ast.Subscript)) \
                            and not line_transfers(src, stmt.lineno):
                        findings.append(src.finding(
                            "MT-OWN-ESCAPE", node,
                            f"{v.cls} handle constructed directly into a "
                            f"longer-lived structure without a "
                            f"`# mtlint: transfers` annotation",
                            hint="annotate the deliberate handoff: "
                                 "`# mtlint: transfers -- who releases it "
                                 "and when`"))

        # callee-side boundary: releasing/transferring a caller's handle
        for (cls, owner), node in released_only.items():
            if (cls, owner) in obligations:
                continue
            if owns_annotation(src, fn) != "callee":
                findings.append(src.finding(
                    "MT-OWN-TRANSFER", node,
                    f"releases/transfers `{owner}` ({cls}) received from "
                    f"the caller without an `# owns: callee` annotation "
                    f"on the def",
                    hint="annotate the def line: "
                         "`# owns: callee -- reason`"))

        for ob in obligations.values():
            if ob.style == "owner":
                root = ob.owner.split(".")[0]
                ob.rebound = root in self._loop_or_reassigned(fn, ob)
            if ob.style == "binding" \
                    and self._captured_by_closure(fn, ob.owner):
                if not line_transfers(src, ob.acquire_node.lineno):
                    findings.append(src.finding(
                        "MT-OWN-ESCAPE", ob.acquire_node,
                        f"owned handle `{ob.owner}` ({ob.cls}) is "
                        f"captured by a closure that outlives this "
                        f"owner without `# mtlint: transfers`",
                        hint="annotate the handoff, or keep the handle "
                             "out of the closure"))
                continue          # closure may release it: untrackable
            _Walk(self, src, fn, ob, findings).run()

    @staticmethod
    def _loop_or_reassigned(fn: ast.AST, ob: _Obligation) -> Set[str]:
        """Names whose binding is ITERATION-SCOPED — For targets,
        assignments inside loop bodies, or names assigned more than
        once: the owner name denotes different owners over time (the
        beam `for owner, _ in claimed: release(owner)` cleanup shape),
        so a second release along the merged loop path is not a DOUBLE.
        A single identity-creating assignment (`owner = object()`)
        keeps the obligation fully trackable."""
        out: Set[str] = set()
        assign_count: Dict[str, int] = {}
        for n in ast.walk(fn):
            if isinstance(n, (ast.For, ast.AsyncFor)):
                for nn in ast.walk(n.target):
                    if isinstance(nn, ast.Name):
                        out.add(nn.id)
                for sub in ast.walk(n):
                    if isinstance(sub, ast.Assign):
                        for t in sub.targets:
                            for nn in ast.walk(t):
                                if isinstance(nn, ast.Name):
                                    out.add(nn.id)
            elif isinstance(n, ast.While):
                for sub in ast.walk(n):
                    if isinstance(sub, ast.Assign):
                        for t in sub.targets:
                            for nn in ast.walk(t):
                                if isinstance(nn, ast.Name):
                                    out.add(nn.id)
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    for nn in ast.walk(t):
                        if isinstance(nn, ast.Name):
                            assign_count[nn.id] = \
                                assign_count.get(nn.id, 0) + 1
        out.update(name for name, c in assign_count.items() if c >= 2)
        return out

    @staticmethod
    def _captured_by_closure(fn: ast.AST, var: str) -> bool:
        for n in ast.walk(fn):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and n is not fn:
                if any(isinstance(nn, ast.Name) and nn.id == var
                       for nn in ast.walk(n)):
                    return True
        return False
