"""Compile-cache hygiene rules (family "jit", ISSUE 17 tentpole).

The compile-key domain of every jit boundary must be finite and warm
before steady-state serving touches it — "compile once, serve forever".
Four rules over the project jit model (analysis/jitgraph.py):

MT-JIT-CLOSURE-VARYING  a traced function captures state that can vary
    between calls — ``self.<attr>`` reads inside the traced body, or an
    enclosing-scope local rebound AFTER the jit object was created.
    Every mutation of such state is a silent full retrace: jax caches
    on the Python function object, not on what its closure read last
    time. Hoist the value to a local before creating the jit (the
    ``_make_step`` idiom: ``model = self.model`` then close over
    ``model``).

MT-JIT-STATIC-UNBOUNDED  a compile-key axis drawn from an unbounded
    domain. Two forms: (a) a jit FACTORY parameter (an enclosing-fn
    param the traced body captures — ``_make_step(rb)``'s ``rb``) with
    no ``# buckets: <REGISTRY>`` annotation declaring the finite table
    it is drawn from; (b) a call-site argument in a static position
    (``static_argnums``/``static_argnames``) built from raw ``len()``,
    a float literal, or a dict display instead of a bucket helper
    (``bucket_rows``/``bucket_length``/``pages_for_tokens``) or a
    declared registry. Also fires on an annotation naming a registry
    the project scan cannot find — vocabulary stays honest.

MT-JIT-WEAKTYPE  a bare Python scalar literal passed as a TRACED
    (non-static) argument to a known-jitted callable: weak-typed
    scalars key the cache differently from committed arrays, and a
    literal that later becomes a ``jnp.asarray`` at one call site but
    not another doubles the cache. Wrap in ``jnp.asarray(x, dtype=...)``
    or make the argument static.

MT-JIT-UNWARMED  (project scope) a jit creation site reachable from the
    steady-state serving plane (marian_tpu/serving/, minus lifecycle/)
    but NOT reachable from any warmup root (``warm_executor`` /
    ``smoke_buckets`` / engine ``warm_grid`` — serving/lifecycle/
    warmup.py). Such a site compiles on a live request: the lint form
    of PR 13's ``marian_compile_total{trigger=steady-state}`` incident
    counter. Never baseline this — warm the site or take it off the
    serving path.

Reachability uses the shared callgraph with the ownership-style
override bridge (subclass methods reachable through base quals) plus a
duck-type bridge for the two dynamic hops the graph cannot see: warmup
drives ``executor(...)``/``executor.engine.warm_grid()`` and the
scheduler drives ``self.engine.<method>`` — both resolve to every
matching method on marian_tpu/translator/ classes.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import Rule, register
from ..core import (Config, Finding, Source, ancestors, call_name,
                    dotted_name, parent)
from ..jitgraph import (BUCKET_DERIVERS, JitModel, JitSite,
                        buckets_annotation, collect_jit_sites,
                        collect_registries, _enclosing_func,
                        _func_leafname, _names_read, _param_names,
                        _traced_fn_for)

WARMUP_REL = "marian_tpu/serving/lifecycle/warmup.py"
WARM_ROOT_NAMES = ("warm_executor", "smoke_buckets")
# methods the warmup/scheduler planes reach through dynamic dispatch
# (executor(...) / self.translate_lines(...) / self.engine.<m>):
# bridged to translator/ classes ("run" is Translate.run, the
# request-mode executor the server wires in as translate_lines)
EXECUTOR_BRIDGE_METHODS = frozenset({
    "__call__", "translate_lines", "decode_texts", "warm_grid", "run"})


def _is_serving_rel(rel: str) -> bool:
    return rel.startswith("marian_tpu/serving/") and rel != WARMUP_REL


def _assignments_after(scope: ast.AST, name: str, lineno: int) -> bool:
    """Is `name` rebound anywhere in `scope` after `lineno`? (the
    varying-closure shape: create jit at L, mutate captured local > L)"""
    for n in ast.walk(scope):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)) and n is not scope:
            continue
        tgt_lineno = getattr(n, "lineno", 0)
        if tgt_lineno <= lineno:
            continue
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) \
                else [n.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return True
    return False


def _self_attr_reads(traced: ast.AST) -> List[ast.Attribute]:
    """Loads of ``self.<attr>`` inside a traced body (each is state
    that can vary under the jit's feet)."""
    out = []
    for n in ast.walk(traced):
        if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load) \
                and isinstance(n.value, ast.Name) \
                and n.value.id == "self":
            out.append(n)
    return out


def _is_bucket_derived(expr: ast.AST) -> bool:
    """Expression provably drawn from a bucket table: a bucket-helper
    call, a name/attr whose dotted path mentions a *BUCKETS/*BLOCKS
    registry, or a subscript/min/max/next over such."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            tail = (call_name(n) or "").rsplit(".", 1)[-1]
            if tail in BUCKET_DERIVERS:
                return True
        name = dotted_name(n)
        if name and any(part.endswith(("BUCKETS", "BLOCKS"))
                        for part in name.split(".")):
            return True
    return False


@register
class JitCompileCacheRule(Rule):
    """Static compile-key-domain analysis over every jit boundary."""

    family = "jit"
    ids = ("MT-JIT-CLOSURE-VARYING", "MT-JIT-STATIC-UNBOUNDED",
           "MT-JIT-WEAKTYPE", "MT-JIT-UNWARMED")
    scope = "project"

    # SARIF metadata (cli._sarif): per-rule short descriptions + help
    descriptions = {
        "MT-JIT-CLOSURE-VARYING":
            "jitted function closes over state mutated elsewhere — "
            "each mutation is a silent retrace",
        "MT-JIT-STATIC-UNBOUNDED":
            "compile-key axis drawn from an unbounded domain instead "
            "of a declared # buckets: registry",
        "MT-JIT-WEAKTYPE":
            "python scalar literal crosses the trace boundary — "
            "weak-type retrace",
        "MT-JIT-UNWARMED":
            "serving-reachable compile key no warmup path covers — "
            "compiles on a live request",
    }

    def check_project(self, sources: Sequence[Source],
                      config: Config) -> List[Finding]:
        findings: List[Finding] = []
        model = JitModel.build(sources)
        by_rel = {s.rel: s for s in sources}
        sites = model.sites

        for src in sources:
            if not config.family_applies(self.family, src.rel):
                continue
            findings += self._check_file(src, model)

        findings += self._check_unwarmed(sources, by_rel, sites, config)
        return findings

    # -- per-file checks ----------------------------------------------------

    def _check_file(self, src: Source, model: JitModel) -> List[Finding]:
        out: List[Finding] = []
        from .trace_safety import _jit_decorator_info, \
            _wrapped_jit_functions
        wrapped = _wrapped_jit_functions(src.tree)

        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                name = call_name(node)
                tail = (name or "").rsplit(".", 1)[-1]
                if tail in ("jit", "pjit", "shard_map") \
                        and name is not None \
                        and (name.startswith("jax.") or "." not in name):
                    out += self._check_creation(src, node, model)
                elif tail and tail in wrapped:
                    out += self._check_call_site(
                        src, node, wrapped[tail], model)
        return out

    def _check_creation(self, src: Source, call: ast.Call,
                        model: JitModel) -> List[Finding]:
        out: List[Finding] = []
        encl = _enclosing_func(call)
        traced = _traced_fn_for(call, src)

        # annotation vocabulary honesty: unknown registry name
        ann_line = call.lineno
        if encl is not None and not isinstance(encl, ast.Lambda):
            ann_line = encl.lineno
        declared = buckets_annotation(src, ann_line)
        for reg in declared:
            if not model.known_registry(reg):
                out.append(src.finding(
                    "MT-JIT-STATIC-UNBOUNDED", call,
                    f"# buckets: names unknown registry '{reg}' — the "
                    "project scan found no such bucket table",
                    hint="declare the table as an ALL_CAPS *BUCKETS/"
                         "*BLOCKS constant, or use POW2/HALVING"))

        if traced is not None:
            out += self._check_closure(src, call, encl, traced)

        # factory axes need a declared domain
        from ..jitgraph import _factory_axes
        axes = _factory_axes(encl, traced)
        if axes and not declared:
            fname = _func_leafname(encl)
            out.append(src.finding(
                "MT-JIT-STATIC-UNBOUNDED", call,
                f"jit factory {fname}({', '.join(axes)}) bakes "
                f"{'params' if len(axes) > 1 else 'param'} "
                f"{', '.join(axes)} into the compile key with no "
                "declared domain — every new value is a fresh "
                "trace+compile",
                hint="annotate the factory def with # buckets: "
                     "<REGISTRY> (e.g. ROW_BUCKETS, JOIN_BUCKETS, "
                     "POW2, HALVING) and derive call-site values via "
                     "bucket_rows()/bucket tables"))
        return out

    def _check_closure(self, src: Source, call: ast.Call,
                       encl: Optional[ast.AST],
                       traced: ast.AST) -> List[Finding]:
        out: List[Finding] = []
        # self.<attr> reads inside the traced body vary whenever the
        # instance mutates — unless self is itself a (static) arg
        params = set(_param_names(traced)) \
            if not isinstance(traced, ast.Lambda) \
            else {a.arg for a in traced.args.args}
        if "self" not in params:
            flagged = set()
            for attr in _self_attr_reads(traced):
                if attr.attr in flagged:
                    continue
                flagged.add(attr.attr)
                out.append(src.finding(
                    "MT-JIT-CLOSURE-VARYING", attr,
                    f"traced function reads self.{attr.attr} through "
                    "its closure — any mutation of the instance "
                    "retraces silently (jax caches on the function "
                    "object, not its captured state)",
                    hint=f"hoist: {attr.attr} = self.{attr.attr} "
                         "before creating the jit, close over the "
                         "local"))

        # enclosing-scope locals rebound AFTER the jit creation
        if encl is not None and not isinstance(encl, ast.Lambda):
            captured = _names_read(traced) - params
            for nm in sorted(captured):
                if _assignments_after(encl, nm, call.lineno):
                    out.append(src.finding(
                        "MT-JIT-CLOSURE-VARYING", call,
                        f"traced function captures '{nm}', which is "
                        f"rebound after the jit is created at line "
                        f"{call.lineno} — the trace saw the old "
                        "value; later calls silently diverge or "
                        "retrace",
                        hint="freeze the value before jit creation, "
                             "or pass it as an argument"))
        return out

    def _check_call_site(self, src: Source, call: ast.Call,
                         statics: Tuple[Sequence[int], Sequence[str]],
                         model: JitModel) -> List[Finding]:
        out: List[Finding] = []
        nums, names = statics
        has_annotation = bool(buckets_annotation(src, call.lineno))

        def unbounded(expr: ast.AST) -> Optional[str]:
            if isinstance(expr, ast.Call) \
                    and (call_name(expr) or "") == "len":
                return "raw len()"
            if isinstance(expr, ast.Constant) \
                    and isinstance(expr.value, float):
                return "float literal"
            if isinstance(expr, ast.Dict):
                return "dict display"
            return None

        for i, arg in enumerate(call.args):
            is_static = i in nums
            why = unbounded(arg)
            if is_static and why and not has_annotation \
                    and not _is_bucket_derived(arg):
                out.append(src.finding(
                    "MT-JIT-STATIC-UNBOUNDED", arg,
                    f"static arg {i} fed from {why} — an unbounded "
                    "compile-key domain (each distinct value is a "
                    "fresh compile)",
                    hint="bucket the value (bucket_rows/bucket_length/"
                         "pages_for_tokens) or annotate the call with "
                         "# buckets: <REGISTRY>"))
            elif not is_static and isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, (int, float)) \
                    and not isinstance(arg.value, bool):
                out.append(src.finding(
                    "MT-JIT-WEAKTYPE", arg,
                    f"python scalar literal {arg.value!r} passed as a "
                    "traced argument to a jitted function — weak-typed "
                    "scalars key the compile cache differently from "
                    "committed arrays",
                    hint="wrap in jnp.asarray(..., dtype=...) or make "
                         "the argument static"))
        for kw in call.keywords:
            if kw.arg in names:
                why = unbounded(kw.value)
                if why and not has_annotation \
                        and not _is_bucket_derived(kw.value):
                    out.append(src.finding(
                        "MT-JIT-STATIC-UNBOUNDED", kw.value,
                        f"static kwarg '{kw.arg}' fed from {why} — an "
                        "unbounded compile-key domain",
                        hint="bucket the value or annotate with "
                             "# buckets: <REGISTRY>"))
        return out

    # -- MT-JIT-UNWARMED (project reachability) -----------------------------

    def _check_unwarmed(self, sources: Sequence[Source],
                        by_rel: Dict[str, Source],
                        sites: List[JitSite],
                        config: Config) -> List[Finding]:
        from .. import callgraph as cgmod
        cg = cgmod.build_cached(sources)

        # override dispatch, exactly ownership.py's bridge: a call the
        # type inference resolves to Base.m may run Sub.m at runtime
        # (PagedBeamEngine overrides _make_step/_install and is driven
        # through the inherited admit_and_step)
        overrides: Dict[str, List[str]] = {}
        for mod in cg.modules.values():
            for ci in mod.classes.values():
                for base in ci.mro()[1:]:
                    for name, meth in ci.methods.items():
                        if name in base.methods:
                            overrides.setdefault(
                                base.methods[name].qual,
                                []).append(meth.qual)

        # leaf-name method index over translator/ classes: the
        # duck-type bridge for the two dynamic hops the callgraph
        # cannot resolve — warmup drives `executor(...)` and the
        # scheduler drives `self.engine.<m>`; both land on translator/
        # class methods whose names the bridge set enumerates
        translator_methods: Dict[str, List[str]] = {}
        for qual, f in cg.functions.items():
            if f.rel.startswith("marian_tpu/translator/") and f.cls:
                leaf = qual.rsplit(".", 1)[-1]
                translator_methods.setdefault(leaf, []).append(qual)

        def succ(qual: str) -> List[str]:
            f = cg.functions.get(qual)
            if f is None:
                return []
            out: List[str] = []
            for cs in f.calls:
                if cs.targets:
                    for t in cs.targets:
                        out.append(t)
                        out.extend(overrides.get(t, ()))
                else:
                    # unresolved call: bridge ONLY the enumerated
                    # dynamic-dispatch method names into translator/;
                    # a bare `executor(...)` (warmup's callable param)
                    # reaches every executor entry method
                    leaf = cs.name.rsplit(".", 1)[-1]
                    if leaf in EXECUTOR_BRIDGE_METHODS:
                        out.extend(translator_methods.get(leaf, ()))
                    elif leaf.startswith("executor"):
                        for m in EXECUTOR_BRIDGE_METHODS:
                            out.extend(translator_methods.get(m, ()))
            # nested defs run in the parent's dynamic extent
            out.extend(f.nested)
            return out

        def reach(roots: Set[str]) -> Set[str]:
            seen = set(roots)
            stack = list(roots)
            while stack:
                q = stack.pop()
                for nxt in succ(q):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            return seen

        warm_roots = {q for q, f in cg.functions.items()
                      if f.rel == WARMUP_REL}
        serve_roots = {q for q, f in cg.functions.items()
                       if _is_serving_rel(f.rel)}
        warm = reach(warm_roots)
        serve = reach(serve_roots)

        # map quals -> "<rel>::<leaf co_name>" site ids
        def site_ids(quals: Set[str]) -> Set[str]:
            out = set()
            for q in quals:
                f = cg.functions.get(q)
                if f is None:
                    continue
                leaf = q.rsplit(".", 1)[-1].strip("<>")
                out.add(f"{f.rel}::{leaf}")
            return out

        warm_sites = site_ids(warm)
        serve_sites = site_ids(serve)

        findings: List[Finding] = []
        seen_sites: Set[str] = set()
        for s in sites:
            if s.kind == "scan":
                # scan-inside-jit compiles with its enclosing jit; a
                # bare eager scan is a perf smell other rules own
                continue
            if not (s.rel.startswith("marian_tpu/translator/")
                    or _is_serving_rel(s.rel)):
                continue
            if not config.family_applies(self.family, s.rel):
                continue
            if s.site in seen_sites:
                continue
            if s.site in serve_sites and s.site not in warm_sites:
                seen_sites.add(s.site)
                src = by_rel.get(s.rel)
                node = _FakeNode(s.lineno)
                findings.append(src.finding(
                    "MT-JIT-UNWARMED", node,
                    f"jit site {s.site} is reachable from steady-state "
                    "serving but from no warmup root — it compiles on "
                    "a live request (PR 13's steady-state recompile "
                    "incident, caught statically)",
                    hint="cover the site from warm_executor/"
                         "smoke_buckets/warm_grid, or take it off the "
                         "serving path"))
        return findings


class _FakeNode:
    """Line anchor for project-scope findings (no single ast node)."""

    def __init__(self, lineno: int):
        self.lineno = lineno
        self.col_offset = 0
