"""span-hygiene (MT-SPAN-*): manual span lifetimes must be airtight
(ISSUE 8 satellite; mirrors the metrics-hygiene family's role for the
obs layer).

The span tracer (marian_tpu/obs/trace.py) records a span only when it is
ENDED — a span opened with ``start_span`` and not closed on every path
silently vanishes from /tracez and from flight-recorder dumps, exactly
when the failing path is the one being debugged. And because the ring
holds a REFERENCE to the span object, mutating its attributes after
``end`` rewrites recorded history.

- MT-SPAN-UNCLOSED: a local binding ``sp = <tracer>.start_span(...)``
  with no ``end(sp)`` on all paths through the function.
  An end inside a ``finally`` counts as unconditional; an ``if`` guard
  that tests the binding itself (``if sp is not None: ... end(sp)``) is
  part of the close idiom and does not count as a branch. Bindings that
  ESCAPE local analysis — returned, stored on an object, passed to
  another call (other than ``end``/``use``) — are skipped: their
  lifetime is someone else's contract (the scheduler parks spans on the
  request object; the server hands them to a done-callback).
  The safe default is ``with tracer.span(...):``, which cannot leak.

- MT-SPAN-LATE: ``sp.set_attrs(...)`` / ``sp.attrs[...]`` after an
  unconditional ``end`` in the same suite — the write lands on an
  already-recorded span.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Config, Finding, Source, call_name, parent
from . import Rule, register

START_TAIL = "start_span"
END_TAIL = "end"
USE_TAIL = "use"
ATTR_CALL_TAILS = {"set_attrs"}


def _tail(name: Optional[str]) -> str:
    return (name or "").rsplit(".", 1)[-1]


def _is_end_call(node: ast.Call, var: str) -> bool:
    """``TRACER.end(sp)`` / ``obs.end(sp)`` / ``end(span=sp)`` — the
    span as the first positional arg or the ``span=`` keyword (RULESET
    v5: Tracer.end's parameter is named ``span``, and the keyword form
    previously read as an escape, silencing the UNCLOSED analysis).
    Deliberately NOT a ``sp.end()`` method form: Span has no end()
    method (recording is the tracer's job), so blessing it here would
    approve code that raises AttributeError at runtime."""
    name = call_name(node) or ""
    if _tail(name) != END_TAIL:
        return False
    if name.split(".")[0] == var:          # sp.end(...): not a close —
        return False                       # no such method on Span
    if bool(node.args) and isinstance(node.args[0], ast.Name) \
            and node.args[0].id == var:
        return True
    return any(kw.arg == "span" and isinstance(kw.value, ast.Name)
               and kw.value.id == var for kw in node.keywords)


def _is_use_call(node: ast.Call, var: str) -> bool:
    if _tail(call_name(node)) != USE_TAIL:
        return False
    return any(isinstance(a, ast.Name) and a.id == var
               for a in list(node.args)
               + [kw.value for kw in node.keywords])


def _is_attr_op(node: ast.AST, var: str) -> bool:
    """``sp.set_attrs(...)``, ``sp.attrs[...] = ..``, ``sp.attrs.update``."""
    if isinstance(node, ast.Call):
        name = call_name(node) or ""
        parts = name.split(".")
        if parts[0] == var and (parts[-1] in ATTR_CALL_TAILS
                                or (len(parts) >= 2 and parts[1] == "attrs")):
            return True
    if isinstance(node, ast.Subscript):
        v = node.value
        if isinstance(v, ast.Attribute) and v.attr == "attrs" \
                and isinstance(v.value, ast.Name) and v.value.id == var:
            return True
    return False


def _field_of(stmt: ast.stmt, owner: ast.AST) -> Optional[str]:
    """Which block field of ``owner`` holds ``stmt`` (body/orelse/
    finalbody...) — two statements are same-suite only when both the
    owner AND the field match (If.body and If.orelse share a parent)."""
    for field, value in ast.iter_fields(owner):
        if isinstance(value, list) and stmt in value:
            return field
    return None


def _stmt_of(node: ast.AST, fn: ast.AST) -> Optional[ast.stmt]:
    """The statement containing ``node`` whose own parent is a block
    owner inside ``fn``."""
    cur: Optional[ast.AST] = node
    while cur is not None and cur is not fn:
        p = parent(cur)
        if isinstance(cur, ast.stmt):
            return cur
        cur = p
    return None


def _branch_ancestors(node: ast.AST, fn: ast.AST, var: str
                      ) -> Optional[Set[int]]:
    """ids of conditionality-introducing ancestors of ``node`` up to
    ``fn``: If/While/For bodies, except handlers, nested functions. An
    ``if`` whose test mentions ``var`` is the close-guard idiom and is
    not counted. A statement sitting in a Try ``finally`` drops that Try
    level (the finally always runs). Returns None when ``node`` sits in
    a lambda/comprehension we cannot reason about (treated conditional).
    """
    out: Set[int] = set()
    cur: ast.AST = node
    while cur is not fn:
        p = parent(cur)
        if p is None:
            return None
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and p is not fn:
            out.add(id(p))                 # nested def: may never run
        elif isinstance(p, ast.If):
            guard_names = {n.id for n in ast.walk(p.test)
                           if isinstance(n, ast.Name)}
            if var not in guard_names:
                out.add(id(p))
        elif isinstance(p, (ast.While, ast.For, ast.AsyncFor)):
            if cur in getattr(p, "body", []) \
                    or cur in getattr(p, "orelse", []):
                out.add(id(p))
        elif isinstance(p, ast.ExceptHandler):
            out.add(id(p))
        elif isinstance(p, ast.Try) and cur in p.finalbody:
            pass                           # finally: unconditional
        elif isinstance(p, (ast.Lambda, ast.GeneratorExp, ast.ListComp,
                            ast.SetComp, ast.DictComp)):
            return None
        cur = p
    return out


@register
class SpanHygieneRule(Rule):
    family = "span"
    ids = ("MT-SPAN-UNCLOSED", "MT-SPAN-LATE")
    scope = "file"

    def check(self, src: Source, config: Config) -> List[Finding]:
        findings: List[Finding] = []
        for fn in ast.walk(src.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_function(src, fn))
        return findings

    def _check_function(self, src: Source, fn: ast.AST) -> List[Finding]:
        # local Name bindings of start_span results, innermost-owner
        # only (a binding inside a nested def belongs to that def's pass)
        bindings: Dict[str, ast.Call] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and _tail(call_name(node.value)) == START_TAIL \
                    and self._owner(node, fn) is fn:
                bindings[node.targets[0].id] = node.value
        findings: List[Finding] = []
        for var, start_call in bindings.items():
            findings.extend(self._check_binding(src, fn, var, start_call))
        return findings

    @staticmethod
    def _owner(node: ast.AST, fn: ast.AST) -> Optional[ast.AST]:
        cur = parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parent(cur)
        return None

    def _check_binding(self, src: Source, fn: ast.AST, var: str,
                       start_call: ast.Call) -> List[Finding]:
        ends: List[ast.Call] = []
        attr_ops: List[ast.AST] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if node is start_call:
                    continue
                if _is_end_call(node, var):
                    ends.append(node)
                    continue
                if _is_use_call(node, var):
                    continue
                if _is_attr_op(node, var):
                    attr_ops.append(node)
                    continue
                # any other call receiving the binding: the span escaped
                # (another owner may close it) — out of scope
                for a in list(node.args) \
                        + [kw.value for kw in node.keywords]:
                    for n in ast.walk(a):
                        if isinstance(n, ast.Name) and n.id == var:
                            return self._late_only(src, var, ends,
                                                   attr_ops, fn)
            elif isinstance(node, ast.Subscript) and _is_attr_op(node, var):
                attr_ops.append(node)
            elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and node.value is not None:
                for n in ast.walk(node.value):
                    if isinstance(n, ast.Name) and n.id == var:
                        return self._late_only(src, var, ends, attr_ops, fn)
            elif isinstance(node, ast.Assign) and not (
                    node.value is start_call):
                # aliasing / storing the span somewhere else: escaped
                for n in ast.walk(node.value):
                    if isinstance(n, ast.Name) and n.id == var:
                        return self._late_only(src, var, ends, attr_ops, fn)

        findings = self._late_only(src, var, ends, attr_ops, fn)
        start_stmt = _stmt_of(start_call, fn)
        start_branches = _branch_ancestors(start_stmt, fn, var) \
            if start_stmt is not None else None
        if not ends:
            findings.append(src.finding(
                "MT-SPAN-UNCLOSED", start_call,
                f"span bound to `{var}` is opened but never closed — it "
                f"will not be recorded to /tracez or flight dumps",
                hint="end it in a finally, or use `with tracer.span(...)`"))
            return findings
        if start_branches is None:
            return findings
        for e in ends:
            stmt = _stmt_of(e, fn)
            br = _branch_ancestors(stmt, fn, var) if stmt is not None \
                else None
            if br is not None and br <= start_branches:
                return findings           # at least one unconditional end
        findings.append(src.finding(
            "MT-SPAN-UNCLOSED", start_call,
            f"span bound to `{var}` is not closed on all paths (every "
            f"`end` sits in a conditional branch the open does not)",
            hint="move the end into a finally covering the open, or use "
                 "`with tracer.span(...)`"))
        return findings

    def _late_only(self, src: Source, var: str, ends: List[ast.Call],
                   attr_ops: List[ast.AST], fn: ast.AST) -> List[Finding]:
        """MT-SPAN-LATE: an attr write whose statement FOLLOWS, in the
        same suite, a statement that IS an unconditional end call."""
        findings: List[Finding] = []
        end_stmts: List[Tuple[ast.stmt, ast.AST, Optional[str]]] = []
        for e in ends:
            stmt = _stmt_of(e, fn)
            if stmt is not None and isinstance(stmt, ast.Expr) \
                    and stmt.value is e:
                own = parent(stmt)
                end_stmts.append((stmt, own, _field_of(stmt, own)))
        if not end_stmts:
            return findings
        for op in attr_ops:
            op_stmt = _stmt_of(op, fn)
            if op_stmt is None:
                continue
            op_parent = parent(op_stmt)
            op_field = _field_of(op_stmt, op_parent) \
                if op_parent is not None else None
            for (e_stmt, e_parent, e_field) in end_stmts:
                if e_parent is op_parent and e_field == op_field \
                        and op_stmt.lineno > e_stmt.lineno:
                    findings.append(src.finding(
                        "MT-SPAN-LATE", op,
                        f"attribute set on `{var}` after it was ended — "
                        f"the span is already recorded; this rewrites "
                        f"history in the ring",
                        hint="set attributes before end(), or pass them "
                             "to end(**attrs)"))
                    break
        return findings
