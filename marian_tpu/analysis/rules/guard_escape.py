"""guard-escape (MT-GUARD-ESCAPE): guarded state escaping its lock
(ISSUE 6 tentpole).

MT-LOCK-GUARD (guarded_by.py) checks that every touch of a
``# guarded-by:`` attribute sits inside ``with self.<lock>:`` — but a
lexically-guarded ACCESS can still leak the guarded OBJECT past the
lock's release:

- **returned**: ``with self._lock: return self._pending`` hands the
  caller the live container; every mutation the caller makes races the
  class's own locked writers (an int/bool snapshot is fine — the hazard
  is the shared mutable, so this fires only for attributes initialized
  to a dict/list/set/deque);
- **aliased past the with**: ``with self._lock: snap = self._pending``
  followed by reads of ``snap`` after the block — the name outlives the
  lock but still points at the shared container (``snap =
  dict(self._pending)`` is the fix, and is not flagged; neither is the
  drain-and-swap idiom ``snap = self._pending; self._pending = {}``,
  which detaches the container under the lock so the alias is
  exclusively owned);
- **captured by a closure**: a lambda / nested def inside the with that
  reads the guarded attribute runs LATER, on whatever thread calls it,
  with no lock — lexical nesting satisfies MT-LOCK-GUARD but not the
  discipline (this fires for any guarded attribute: even an int read is
  then unsynchronized).

Accesses MT-LOCK-GUARD already flags (outside any with) are not
re-flagged here — each rule owns its blind spot.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Config, Finding, Source, ancestors, parent
from . import Rule, register
from .guarded_by import (EXEMPT_METHODS, GUARD_RE, _held_locks,
                         _locks_in_scope, _self_attr)

CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                   "OrderedDict", "Counter"}


def _is_container_init(rhs: Optional[ast.AST]) -> bool:
    if rhs is None:
        return False
    if isinstance(rhs, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                        ast.ListComp, ast.SetComp)):
        return True
    if isinstance(rhs, ast.Call):
        name = ""
        f = rhs.func
        while isinstance(f, ast.Attribute):
            name = f.attr
            f = f.value
        if isinstance(f, ast.Name):
            name = name or f.id
        return name in CONTAINER_CTORS
    return False


def _enclosing_closure(node: ast.AST, fn: ast.AST) -> Optional[ast.AST]:
    """The innermost lambda / nested def strictly between node and fn."""
    for anc in ancestors(node):
        if anc is fn:
            return None
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return anc
    return None


@register
class GuardEscapeRule(Rule):
    family = "guard-escape"
    ids = ("MT-GUARD-ESCAPE",)

    def check(self, src: Source, config: Config) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(src, node))
        return findings

    def _guarded_attrs(self, src: Source, cls: ast.ClassDef
                       ) -> Dict[str, str]:
        guarded: Dict[str, str] = {}
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    m = GUARD_RE.search(src.comments.get(node.lineno, ""))
                    if m:
                        guarded[attr] = m.group(1)
        return guarded

    def _container_attrs(self, cls: ast.ClassDef) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None \
                            and _is_container_init(node.value):
                        out.add(attr)
        return out

    def _check_class(self, src: Source,
                     cls: ast.ClassDef) -> List[Finding]:
        guarded = self._guarded_attrs(src, cls)
        if not guarded:
            return []
        containers = self._container_attrs(cls)
        findings: List[Finding] = []
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name in EXEMPT_METHODS:
                continue
            declared = _held_locks(src, fn)
            for node in ast.walk(fn):
                attr = _self_attr(node)
                if attr is None or attr not in guarded:
                    continue
                lock = guarded[attr]
                locked_here = lock in _locks_in_scope(node, fn) \
                    or lock in declared
                if not locked_here:
                    continue          # MT-LOCK-GUARD's territory
                closure = _enclosing_closure(node, fn)
                if closure is not None:
                    if lock in _locks_in_scope(node, closure):
                        continue      # the closure re-takes the lock
                    findings.append(src.finding(
                        "MT-GUARD-ESCAPE", node,
                        f"`self.{attr}` (guarded-by: {lock}) captured by "
                        f"a closure inside `{fn.name}` — the closure runs "
                        f"later, without the lock",
                        hint=f"pass a snapshot into the closure, or take "
                             f"`with self.{lock}:` inside it"))
                    continue
                if attr not in containers:
                    continue          # scalar snapshots are fine
                p = parent(node)
                if isinstance(p, ast.Return) and p.value is node:
                    findings.append(src.finding(
                        "MT-GUARD-ESCAPE", node,
                        f"`{fn.name}` returns the guarded container "
                        f"`self.{attr}` itself (guarded-by: {lock}) — the "
                        f"caller gets the live object after the lock is "
                        f"released",
                        hint=f"return a copy (dict(self.{attr}) / "
                             f"list(...)) built under the lock"))
                    continue
                findings.extend(self._check_alias(src, fn, node, attr,
                                                  lock))
        return findings

    def _check_alias(self, src: Source, fn: ast.AST, node: ast.AST,
                     attr: str, lock: str) -> List[Finding]:
        """`x = self._attr` inside the with, `x` used after it ends."""
        p = parent(node)
        if not (isinstance(p, ast.Assign) and p.value is node
                and len(p.targets) == 1
                and isinstance(p.targets[0], ast.Name)):
            return []
        alias = p.targets[0].id
        # the innermost with that holds the guarding lock
        guard_with = None
        for anc in ancestors(node):
            if anc is fn:
                break
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    d = _self_attr_of(item.context_expr)
                    if d == lock:
                        guard_with = anc
                        break
                if guard_with is not None:
                    break
        if guard_with is None:
            return []
        # drain-and-swap: `snap = self._attr` followed by
        # `self._attr = {}` under the SAME lock detaches the container —
        # the alias is then exclusively owned, and using it after the
        # with is the whole point of the idiom (flush without holding
        # the lock). Only a rebind AFTER the alias counts (rebound
        # first, the alias would point at the new, still-shared object),
        # and only a rebind in the with's straight-line body — one
        # buried in an if/try branch does not dominate the exit, so some
        # paths leave the alias live.
        for stmt in guard_with.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)) \
                    and stmt.lineno >= p.lineno:
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                if any(_self_attr_of(t) == attr for t in targets):
                    return []
        end = getattr(guard_with, "end_lineno", guard_with.lineno)
        out: List[Finding] = []
        # a post-with rebind of the alias only detaches it for reads it
        # DOMINATES: every branch construct enclosing the rebind must
        # also enclose the read (`if flag: snap = {}` leaves the
        # flag-false path reading the live container)
        rebinds: List[Set[int]] = []
        for use in sorted(
                (n for n in ast.walk(fn)
                 if isinstance(n, ast.Name) and n.id == alias
                 and n.lineno > end),
                key=lambda n: (n.lineno, n.col_offset)):
            # an AugAssign target has Store ctx but `snap += ...` READS
            # and mutates the aliased container in place — a use, not a
            # detaching rebind
            aug = isinstance(use.ctx, ast.Store) \
                and isinstance(parent(use), ast.AugAssign)
            if isinstance(use.ctx, ast.Store) and not aug:
                rebinds.append(_branch_ids(use, fn))
                continue
            if isinstance(use.ctx, ast.Load) or aug:
                if any(s <= _branch_ids(use, fn) for s in rebinds):
                    continue           # rebound on every path to here
                if lock in _locks_in_scope(use, fn):
                    continue           # re-acquired around this use —
                    # same exemption the closure path grants
                out.append(src.finding(
                    "MT-GUARD-ESCAPE", use,
                    f"`{alias}` aliases the guarded container "
                    f"`self.{attr}` (guarded-by: {lock}) and is used "
                    f"after the `with self.{lock}:` block ends",
                    hint=f"alias a copy instead: `{alias} = "
                         f"dict(self.{attr})` under the lock"))
                break                  # one finding per alias is enough
        return out


_BRANCHY = (ast.If, ast.For, ast.AsyncFor, ast.While, ast.Try,
            ast.ExceptHandler, ast.Match)


def _branch_ids(node: ast.AST, fn: ast.AST) -> Set[tuple]:
    """(id, arm) of each branch/loop construct between node and fn.

    A Store dominates a lexically-later Load iff every such (construct,
    arm) enclosing the Store also encloses the Load — then the Store
    sits in straight-line flow relative to the Load and runs first on
    every path that reaches it. The arm matters: a rebind in an
    if-body does not cover a read in the orelse.
    """
    out: Set[tuple] = set()
    child: ast.AST = node
    for anc in ancestors(node):
        if anc is fn:
            break
        if isinstance(anc, _BRANCHY):
            arm = ""
            for field, value in ast.iter_fields(anc):
                if value is child or (isinstance(value, list)
                                      and child in value):
                    arm = field
                    break
            out.add((id(anc), arm))
        child = anc
    return out


def _self_attr_of(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    return None
