"""mtlint rule registry. A rule family is one module exporting Rule
subclasses registered with @register; `all_rules()` imports every family
module once and returns the instances (stable order: registration order)."""

from __future__ import annotations

from typing import List

from ..core import Config, Finding, Source


class Rule:
    """Base class. `family` groups ids for config scoping ("trace-safety",
    "host-sync", "donation", "dtype", "guarded-by", "metrics", "faults",
    "lock-order", "lock-blocking", "guard-escape", "span", "ownership",
    "jit"); `scope` is "file"
    (check per Source) or "project" (check_project over all in-scope
    sources at once — cross-file rules like metrics hygiene and the
    call-graph lock rules)."""

    family: str = ""
    ids: tuple = ()           # rule ids this family can emit (docs/tests)
    scope: str = "file"
    descriptions: dict = {}   # optional rule-id -> short description
    #                           (surfaced as SARIF rule metadata)

    def check(self, src: Source, config: Config) -> List[Finding]:
        return []

    def check_project(self, sources: List[Source],
                      config: Config) -> List[Finding]:
        return []


_RULES: List[Rule] = []


def register(cls):
    _RULES.append(cls())
    return cls


def all_rules() -> List[Rule]:
    _load()
    return list(_RULES)


_loaded = False


def _load() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    from . import (trace_safety, host_sync, donation,  # noqa: F401
                   dtype_hygiene, guarded_by, metrics_hygiene,
                   fault_hygiene, lock_order, lock_blocking,
                   guard_escape, span_hygiene, ownership, jit)
