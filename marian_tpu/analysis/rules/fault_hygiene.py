"""fault-hygiene (MT-FAULT-*): the fault-injection catalog and the code
that crosses it must agree — project-scoped analysis, the crash-safety
mirror of the metrics-hygiene rule (ISSUE 4).

- MT-FAULT-UNKNOWN: a ``fault_point("name")`` call site whose name is not
  declared in ``common/faultpoints.py :: CATALOG``. An undeclared point
  can never be armed from a MARIAN_FAULTS spec (parse_spec validates
  against the catalog), so it is dead code pretending to be covered.

- MT-FAULT-UNTESTED: a declared fault point that no test ever references
  (its name appears as a string in no file under ``tests/``). A fault
  point nobody injects is a crash-safety claim nobody verifies — exactly
  the rot this registry exists to prevent. scripts/chaos.py randomizes
  over the catalog at runtime, but the DETERMINISTIC per-point kill/fail
  coverage must live in the test suite.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Config, Finding, Source, call_name, tests_string_corpus
from . import Rule, register

FAULTPOINTS_FILE = "faultpoints.py"


def _catalog_names(sources: List[Source]) -> Tuple[Optional[Source],
                                                   Set[str]]:
    """String keys of the ``CATALOG = {...}`` literal in faultpoints.py."""
    for src in sources:
        if not src.rel.endswith(FAULTPOINTS_FILE):
            continue
        for node in ast.walk(src.tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):   # CATALOG: Dict[...] = {}
                targets = [node.target]
            if targets and isinstance(getattr(node, "value", None), ast.Dict) \
                    and any(isinstance(t, ast.Name) and t.id == "CATALOG"
                            for t in targets):
                names = {k.value for k in node.value.keys
                         if isinstance(k, ast.Constant)
                         and isinstance(k.value, str)}
                return src, names
    return None, set()


def _tests_text(config: Config) -> str:
    """The 'is this point ever injected' corpus: every string constant
    under tests/ (core.tests_string_corpus — shared with the metrics
    UNTESTED rule since RULESET v5). Fault names live inside spec
    strings ("ckpt.commit=kill@2") which an identifier walk would miss,
    while a name mentioned only in a comment must NOT count."""
    return tests_string_corpus(config)


@register
class FaultHygieneRule(Rule):
    family = "faults"
    ids = ("MT-FAULT-UNKNOWN", "MT-FAULT-UNTESTED")
    scope = "project"

    def check_project(self, sources: List[Source],
                      config: Config) -> List[Finding]:
        cat_src, catalog = _catalog_names(sources)
        # call sites: fault_point("name") / fp.fault_point("name")
        sites: Dict[str, List[Tuple[Source, ast.Call]]] = {}
        for src in sources:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node) or ""
                if name.split(".")[-1] != "fault_point":
                    continue
                if not (node.args and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                sites.setdefault(node.args[0].value, []).append((src, node))

        findings: List[Finding] = []
        unknown: Set[str] = set()
        if catalog:
            for fname, occs in sorted(sites.items()):
                if fname in catalog:
                    continue
                unknown.add(fname)
                src, node = occs[0]
                findings.append(src.finding(
                    "MT-FAULT-UNKNOWN", node,
                    f"fault point '{fname}' is not declared in "
                    f"faultpoints.CATALOG — it can never be armed from a "
                    f"MARIAN_FAULTS spec",
                    hint="declare it in CATALOG (with a description) or "
                         "fix the name"))

        if not sites and cat_src is None:
            return findings          # tree without the registry: nothing to do

        tests = _tests_text(config)
        for fname in sorted(set(sites) | catalog):
            if fname in tests or fname in unknown:   # UNKNOWN already said it
                continue
            if fname in sites:
                src, node = sites[fname][0]
                findings.append(src.finding(
                    "MT-FAULT-UNTESTED", node,
                    f"fault point '{fname}' is never exercised by any "
                    f"test — an uninjected fault point is a crash-safety "
                    f"claim nobody verifies",
                    hint="add a test that arms it (faultpoints.active / "
                         "MARIAN_FAULTS) and asserts the recovery "
                         "behavior"))
            elif cat_src is not None:
                # declared but never even placed in code — anchor at the
                # catalog itself
                node = _catalog_key_node(cat_src, fname)
                findings.append(cat_src.finding(
                    "MT-FAULT-UNTESTED", node or cat_src.tree,
                    f"catalog fault point '{fname}' has no call site and "
                    f"no test coverage",
                    hint="thread fault_point() through the code path it "
                         "describes, or drop the catalog entry"))
        return findings


def _catalog_key_node(src: Source, fname: str) -> Optional[ast.AST]:
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Constant) and node.value == fname:
            return node
    return None
