"""trace-safety (MT-TRACE-*): Python control flow and host casts on traced
values inside jit-compiled functions.

Inside a function compiled by `jax.jit` / `pjit` / `shard_map`, the
arguments are tracers: `if x > 0`, `while`, `int(x)`, `bool(x)`, `.item()`
all force a concrete value — a ConcretizationTypeError at best, and at
worst (when the value happens to be concrete at trace time, e.g. a captured
constant) a silent RETRACE per distinct value, which is the classic
accidental-recompile bug. `np.*` calls on traced values bounce the
computation through the host.

The analysis is a lightweight per-function taint pass: non-static
parameters are tainted; assignments whose RHS mentions a tainted name
propagate. Conservative where it must be (static_argnums/static_argnames
literals are honored; `x is None` tests and isinstance() are trace-safe and
skipped).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ..core import (Config, Finding, Source, call_name, const_int_tuple,
                    const_str_tuple, dotted_name, names_in, parent)
from . import Rule, register

JIT_TAILS = {"jit", "pjit", "shard_map"}

# np.<attr> access that is trace-safe (dtypes/constants, not computation)
NP_SAFE_ATTRS = {
    "float32", "float64", "float16", "bfloat16", "int8", "int16", "int32",
    "int64", "uint8", "uint16", "uint32", "uint64", "bool_", "complex64",
    "complex128", "dtype", "ndarray", "newaxis", "pi", "e", "inf", "nan",
    "finfo", "iinfo", "issubdtype", "floating", "integer", "generic",
}

CAST_FUNCS = {"int", "float", "bool", "complex"}
CAST_METHODS = {"item", "tolist", "__float__", "__int__"}

# attributes of a tracer that are static metadata, not traced data:
# `if x.ndim == 2` or `int(x.shape[0])` are trace-safe and idiomatic
STATIC_ATTRS = {"dtype", "shape", "ndim", "size", "sharding", "aval",
                "weak_type"}


def _jit_decorator_info(dec: ast.AST) -> Optional[Tuple[Set[int], Set[str]]]:
    """If `dec` marks the function as jit-compiled, return the static
    argument (positions, names); else None."""
    name = dotted_name(dec)
    if name and name.split(".")[-1] in JIT_TAILS:
        return set(), set()
    if isinstance(dec, ast.Call):
        fn = dotted_name(dec.func)
        if fn is None:
            return None
        tail = fn.split(".")[-1]
        if tail in JIT_TAILS:
            return _static_args(dec)
        if tail == "partial" and dec.args:
            inner = dotted_name(dec.args[0])
            if inner and inner.split(".")[-1] in JIT_TAILS:
                return _static_args(dec)
    return None


def _static_args(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums.update(const_int_tuple(kw.value) or ())
        elif kw.arg == "static_argnames":
            names.update(const_str_tuple(kw.value) or ())
    return nums, names


def _wrapped_jit_functions(tree: ast.Module):
    """`step = jax.jit(fn, ...)` at any level: map function NAME ->
    (static positions, static names) so the def itself is checked."""
    out = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = call_name(node)
        if fn is None or fn.split(".")[-1] not in JIT_TAILS:
            continue
        if node.args and isinstance(node.args[0], ast.Name):
            out[node.args[0].id] = _static_args(node)
    return out


def _param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs])


def _tainted_params(fn: ast.FunctionDef, static_nums: Set[int],
                    static_names: Set[str]) -> Set[str]:
    params = _param_names(fn)
    tainted = set()
    for i, p in enumerate(params):
        if i in static_nums or p in static_names or p in ("self", "cls"):
            continue
        # params annotated as Python scalars/strings are static by contract
        ann = ([*fn.args.posonlyargs, *fn.args.args,
                *fn.args.kwonlyargs][i].annotation)
        if ann is not None:
            ann_src = ast.dump(ann)
            if any(f"'{t}'" in ann_src
                   for t in ("int", "float", "bool", "str")) \
                    and "Array" not in ann_src:
                continue
        tainted.add(p)
    return tainted


def _propagate(fn: ast.FunctionDef, tainted: Set[str]) -> Set[str]:
    """Fixpoint over simple assignments and for-targets."""
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = node.value
                if value is None or not (names_in(value) & tainted):
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and n.id not in tainted:
                            tainted.add(n.id)
                            changed = True
            elif isinstance(node, ast.For):
                if names_in(node.iter) & tainted:
                    for n in ast.walk(node.target):
                        if isinstance(n, ast.Name) and n.id not in tainted:
                            tainted.add(n.id)
                            changed = True
    return tainted


def _traced_uses(node: ast.AST, tainted: Set[str]) -> bool:
    """True if a tainted name is used as traced DATA under `node` — uses
    that only read static metadata (`x.shape`, `x.dtype`, ...) don't
    count."""
    for n in ast.walk(node):
        if not (isinstance(n, ast.Name) and n.id in tainted):
            continue
        p = parent(n)
        if isinstance(p, ast.Attribute) and p.attr in STATIC_ATTRS:
            continue
        return True
    return False


def _test_is_trace_safe(test: ast.AST) -> bool:
    """`x is None` / `x is not None` and isinstance() branch on static
    structure, not on traced values."""
    if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
        return True
    if isinstance(test, ast.Call):
        fn = call_name(test)
        if fn in ("isinstance", "hasattr", "callable", "len"):
            return True
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _test_is_trace_safe(test.operand)
    if isinstance(test, ast.BoolOp):
        return all(_test_is_trace_safe(v) for v in test.values)
    return False


@register
class TraceSafetyRule(Rule):
    family = "trace-safety"
    ids = ("MT-TRACE-COND", "MT-TRACE-CAST", "MT-TRACE-NUMPY")

    def check(self, src: Source, config: Config) -> List[Finding]:
        findings: List[Finding] = []
        wrapped = _wrapped_jit_functions(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            statics: Optional[Tuple[Set[int], Set[str]]] = None
            for dec in node.decorator_list:
                statics = _jit_decorator_info(dec)
                if statics is not None:
                    break
            if statics is None and node.name in wrapped:
                statics = wrapped[node.name]
            if statics is None:
                continue
            findings.extend(self._check_jitted(src, node, *statics))
        return findings

    def _check_jitted(self, src: Source, fn: ast.FunctionDef,
                      static_nums: Set[int],
                      static_names: Set[str]) -> List[Finding]:
        out: List[Finding] = []
        tainted = _propagate(fn, _tainted_params(fn, static_nums,
                                                 static_names))
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                if _test_is_trace_safe(node.test):
                    continue
                if _traced_uses(node.test, tainted):
                    kw = "while" if isinstance(node, ast.While) else "if"
                    out.append(src.finding(
                        "MT-TRACE-COND", node.test,
                        f"Python `{kw}` on a value traced through "
                        f"jit-compiled `{fn.name}` — concretizes the tracer "
                        f"(error) or retraces per value (recompile storm)",
                        hint="use jnp.where/lax.cond/lax.while_loop, or mark "
                             "the argument static"))
            elif isinstance(node, ast.IfExp):
                if not _test_is_trace_safe(node.test) \
                        and _traced_uses(node.test, tainted):
                    out.append(src.finding(
                        "MT-TRACE-COND", node.test,
                        f"conditional expression on a traced value inside "
                        f"jit-compiled `{fn.name}`",
                        hint="use jnp.where(cond, a, b)"))
            elif isinstance(node, ast.Call):
                name = call_name(node)
                if name in CAST_FUNCS and node.args \
                        and _traced_uses(node.args[0], tainted):
                    out.append(src.finding(
                        "MT-TRACE-CAST", node,
                        f"`{name}()` on a traced value inside jit-compiled "
                        f"`{fn.name}` — forces host concretization",
                        hint="keep it on-device (jnp cast) or mark static"))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in CAST_METHODS \
                        and _traced_uses(node.func.value, tainted):
                    out.append(src.finding(
                        "MT-TRACE-CAST", node,
                        f"`.{node.func.attr}()` on a traced value inside "
                        f"jit-compiled `{fn.name}` — host sync under trace",
                        hint="return the array and convert outside jit"))
                elif name is not None and name.split(".")[0] in ("np",
                                                                 "numpy"):
                    attr = name.split(".", 1)[1] if "." in name else ""
                    if attr.split(".")[0] not in NP_SAFE_ATTRS:
                        out.append(src.finding(
                            "MT-TRACE-NUMPY", node,
                            f"`{name}(...)` inside jit-compiled `{fn.name}` "
                            f"— numpy executes on host at trace time "
                            f"(constant-folded or concretization error)",
                            hint="use the jnp equivalent"))
        return out
