"""host-sync (MT-SYNC-*): hidden host<->device synchronization in hot files.

Two patterns, both restricted to directories marked hot in [tool.mtlint]
(ops/, translator/, training/ by default):

- MT-SYNC-TIMER: a function brackets work between two wall-clock reads
  (`time.perf_counter` / `time.time` / `time.monotonic`) but never calls
  `block_until_ready`. Under JAX's async dispatch the second read fires
  when the work is ENQUEUED, not done — the timer measures dispatch, and
  the first later sync silently absorbs the real device time. (A function
  that deliberately measures wall-clock across a deferred-sync window
  should say so with `# mtlint: ok -- reason`.)

- MT-SYNC-TRANSFER: implicit device->host transfers on the hot path:
  `np.asarray(x)` / `np.array(x)` on a non-literal, `.tolist()`, and
  `print(...)` of non-constant values. Each is a blocking round-trip that
  stalls the dispatch pipeline.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Config, Finding, Source, call_name
from . import Rule, register

TIMER_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
               "perf_counter", "monotonic"}
TRANSFER_NP = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
SYNC_MARKERS = ("block_until_ready",)


def _own_nodes(fn: ast.AST):
    """Nodes lexically in `fn` EXCLUDING nested def/async-def subtrees —
    nested functions get their own visit, and their timer reads / sync
    calls must not be attributed to the enclosing function (ast.walk
    alone cannot prune a subtree)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _is_literalish(node: ast.AST) -> bool:
    """Constants and (nested) tuples/lists of constants — np.array on these
    is host-side data prep, not a device transfer."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_literalish(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_literalish(node.operand)
    return False


@register
class HostSyncRule(Rule):
    family = "host-sync"
    ids = ("MT-SYNC-TIMER", "MT-SYNC-TRANSFER")

    def check(self, src: Source, config: Config) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_timers(src, node))
        findings.extend(self._check_transfers(src))
        return findings

    def _check_timers(self, src: Source,
                      fn: ast.FunctionDef) -> List[Finding]:
        timer_calls = []
        other_call_lines = []
        synced = False
        for node in _own_nodes(fn):
            if isinstance(node, ast.Call):
                name = call_name(node) or ""
                if name in TIMER_CALLS:
                    timer_calls.append(node)
                elif any(m in name for m in SYNC_MARKERS) or \
                        (isinstance(node.func, ast.Attribute)
                         and node.func.attr in SYNC_MARKERS):
                    synced = True
                else:
                    other_call_lines.append(node.lineno)
        if synced or len(timer_calls) < 2:
            return []
        timer_calls.sort(key=lambda n: n.lineno)
        first, last = timer_calls[0].lineno, timer_calls[-1].lineno
        if not any(first < ln < last for ln in other_call_lines):
            return []  # nothing measured between the reads
        return [src.finding(
            "MT-SYNC-TIMER", timer_calls[-1],
            f"`{fn.name}` times work between wall-clock reads without "
            f"block_until_ready — async dispatch makes this measure "
            f"enqueue time, not device time",
            hint="jax.block_until_ready(result) before the closing read, "
                 "or annotate a deliberate deferred-sync window with "
                 "`# mtlint: ok -- reason`")]

    def _check_transfers(self, src: Source) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            if name in TRANSFER_NP and node.args \
                    and not _is_literalish(node.args[0]):
                out.append(src.finding(
                    "MT-SYNC-TRANSFER", node,
                    f"`{name}(...)` on the hot path — if the argument is a "
                    f"device array this is a blocking device->host copy",
                    hint="keep hot-path data in jnp, or move the transfer "
                         "behind an explicit sync boundary"))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "tolist":
                out.append(src.finding(
                    "MT-SYNC-TRANSFER", node,
                    "`.tolist()` on the hot path — blocking device->host "
                    "transfer plus Python object materialization",
                    hint="use np.asarray at an explicit sync point instead"))
            elif name == "print" and node.args \
                    and not all(_is_literalish(a) for a in node.args):
                out.append(src.finding(
                    "MT-SYNC-TRANSFER", node,
                    "`print(...)` of computed values on the hot path — "
                    "printing a device array blocks on its result",
                    hint="log at a sync boundary (common.logging), or print "
                         "only host scalars"))
        return out
