"""metrics-hygiene (MT-METRIC-*): the Prometheus registry and the code that
emits into it must agree — project-scoped (cross-file) analysis.

- MT-METRIC-UNUSED: a metric registered via a Registry factory
  (`.counter("name", ...)` / `.gauge` / `.histogram`, or the module-level
  conveniences) whose binding is never emitted into anywhere in the tree
  (no .inc/.dec/.set/.observe/.set_function/.labels). Dead series still
  render on every /metrics scrape and rot into dashboards nobody can
  populate.

- MT-METRIC-UNREG: an emission on a metric-shaped binding (`m_*` / `_m_*`
  naming convention) that was never bound from a Registry factory —
  including direct `Counter(...)` construction, which bypasses the registry
  so the series silently never appears on /metrics.

- MT-METRIC-UNTESTED (RULESET v5, ISSUE 9): a registered metric name
  that appears in no string constant under ``tests/`` — the metrics
  mirror of MT-FAULT-UNTESTED. A series nobody scrapes in a test is an
  observability claim nobody verifies: it can silently stop being
  emitted, or break the exposition format, without a test going red.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import (Config, Finding, Source, call_name, dotted_name,
                    parent, tests_string_corpus)
from . import Rule, register

FACTORY_METHODS = {"counter", "gauge", "histogram"}
EMIT_METHODS = {"inc", "dec", "set", "observe", "set_function", "labels"}
DIRECT_CLASSES = {"Counter", "Gauge", "Histogram"}


def _binding_segment(call: ast.Call) -> Optional[str]:
    """Last attribute/name segment the call result is assigned to
    (`self._m_fill = r.histogram(...)` -> "_m_fill")."""
    stmt = parent(call)
    if isinstance(stmt, ast.Assign) and stmt.value is call:
        for t in stmt.targets:
            d = dotted_name(t)
            if d:
                return d.split(".")[-1]
    if isinstance(stmt, ast.AnnAssign) and stmt.value is call:
        d = dotted_name(stmt.target)
        if d:
            return d.split(".")[-1]
    return None


def _emission_receiver(node: ast.Call) -> Optional[str]:
    """Receiver segment of `<recv>.inc()` — follows one `.labels(...)`
    chain link (`self.m_shed.labels("x").inc()` -> "m_shed")."""
    if not isinstance(node.func, ast.Attribute):
        return None
    recv = node.func.value
    if isinstance(recv, ast.Call) and isinstance(recv.func, ast.Attribute) \
            and recv.func.attr == "labels":
        recv = recv.func.value
    d = dotted_name(recv)
    if d is None:
        return None
    return d.split(".")[-1]


def _metric_shaped(segment: str) -> bool:
    return segment.startswith("m_") or segment.startswith("_m_")


@register
class MetricsHygieneRule(Rule):
    family = "metrics"
    ids = ("MT-METRIC-UNUSED", "MT-METRIC-UNREG", "MT-METRIC-UNTESTED")
    scope = "project"

    def check_project(self, sources: List[Source],
                      config: Config) -> List[Finding]:
        # metric name -> [(source, call node, binding segment)]
        registrations: Dict[str, List[Tuple[Source, ast.Call,
                                            Optional[str]]]] = {}
        emitted_segments: Set[str] = set()
        emissions: List[Tuple[Source, ast.Call, str]] = []
        direct_bound: Set[str] = set()

        for src in sources:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node) or ""
                tail = name.split(".")[-1]
                if tail in FACTORY_METHODS and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    metric = node.args[0].value
                    registrations.setdefault(metric, []).append(
                        (src, node, _binding_segment(node)))
                elif tail in DIRECT_CLASSES and name == tail:
                    seg = _binding_segment(node)
                    if seg:
                        direct_bound.add(seg)
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in EMIT_METHODS:
                    seg = _emission_receiver(node)
                    if seg:
                        emitted_segments.add(seg)
                        emissions.append((src, node, seg))

        registered_segments = {seg for regs in registrations.values()
                               for (_s, _n, seg) in regs if seg}
        findings: List[Finding] = []
        for metric, regs in sorted(registrations.items()):
            segments = [seg for (_s, _n, seg) in regs if seg]
            if any(seg in emitted_segments for seg in segments):
                continue
            src, node, _seg = regs[0]
            what = ("its binding is never emitted into"
                    if segments else "its result is discarded")
            findings.append(src.finding(
                "MT-METRIC-UNUSED", node,
                f"metric '{metric}' is registered but {what} — a dead "
                f"series on every /metrics scrape",
                hint="emit it (.inc/.observe/.set/.set_function) or delete "
                     "the registration"))
        seen: Set[Tuple[str, str, int]] = set()
        for src, node, seg in emissions:
            if not _metric_shaped(seg):
                continue
            if seg in registered_segments:
                continue
            key = (src.rel, seg, node.lineno)
            if key in seen:
                continue
            seen.add(key)
            why = ("bound by direct construction, bypassing the registry"
                   if seg in direct_bound else
                   "never bound from a registry factory")
            findings.append(src.finding(
                "MT-METRIC-UNREG", node,
                f"emission on metric-shaped `{seg}` which is {why} — the "
                f"series will never appear on /metrics",
                hint="register it via Registry.counter/gauge/histogram "
                     "(get-or-create) instead"))
        if registrations:
            tests = tests_string_corpus(config)
            for metric, regs in sorted(registrations.items()):
                if metric in tests:
                    continue
                src, node, _seg = regs[0]
                findings.append(src.finding(
                    "MT-METRIC-UNTESTED", node,
                    f"metric '{metric}' is exercised by no test (its "
                    f"name appears in no string under tests/) — a "
                    f"series nobody scrapes in a test can silently stop "
                    f"being emitted",
                    hint="assert the name appears in a real registry "
                         "render/scrape in a test (the metric-census "
                         "tests are the usual home)"))
        return findings
