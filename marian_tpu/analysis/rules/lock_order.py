"""lock-order (MT-LOCK-ORDER / MT-LOCK-NAME): a static deadlock detector
for the serving control plane's lock lattice (ISSUE 6 tentpole).

Built on the project call graph (analysis/callgraph.py): each function's
may-be-held-at-entry lock set is computed interprocedurally — seeded from
``with self._lock:`` blocks and ``# mtlint: holds <lock>`` declarations —
and every acquisition of lock B while lock A may be held adds edge A→B to
a global lock-acquisition-order graph. A CYCLE in that graph is two call
chains that can acquire the same pair of locks in opposite orders: a
deadlock waiting for the right thread interleaving. Reentrant
re-acquisition of the same lock (the SwapController RLock pattern) adds
no edge.

MT-LOCK-NAME keeps the runtime witness honest: a lock created through
``lockdep.make_lock("<name>")`` must name itself exactly
``<OwningClass>.<attr>`` (or ``<module>.<NAME>`` at module level) — the
identity the static graph uses — or the witness would compare apples to
oranges (common/lockdep.py, docs/STATIC_ANALYSIS.md).

The graph itself is inspectable: ``python -m marian_tpu.analysis
--format dot`` renders it (snapshot: docs/lock_order.dot), and the
runtime lockdep witness (``MARIAN_LOCKDEP=1`` in the tier-1 serving +
lifecycle suites) fails tier-1 on any OBSERVED acquisition edge the
static graph missed.
"""

from __future__ import annotations

from typing import List

from .. import callgraph as cg
from ..core import Config, Finding, Source
from . import Rule, register


@register
class LockOrderRule(Rule):
    family = "lock-order"
    ids = ("MT-LOCK-ORDER", "MT-LOCK-NAME")
    scope = "project"

    def check_project(self, sources: List[Source],
                      config: Config) -> List[Finding]:
        graph = cg.build_cached(sources)
        by_rel = {s.rel: s for s in sources}
        findings: List[Finding] = []

        edges = {(e.src, e.dst): e for e in graph.lock_edges()}
        for cycle in graph.lock_cycles():
            # anchor the finding at the acquire site of the cycle's
            # first edge; render the full ring + one example chain per
            # edge so the report is actionable without re-running
            ring = " -> ".join(cycle + [cycle[0]])
            steps = []
            anchor = None
            for a, b in zip(cycle, cycle[1:] + [cycle[0]]):
                e = edges.get((a, b))
                if e is None:
                    continue
                if anchor is None:
                    anchor = e
                via = f" via {e.chain} -> {e.func}" if e.chain \
                    else f" in {e.func}"
                steps.append(f"{a} then {b} at "
                             f"{e.rel}:{e.lineno}{via}")
            if anchor is None:
                continue
            src = by_rel.get(anchor.rel)
            if src is None:
                continue
            findings.append(src.finding(
                "MT-LOCK-ORDER", _node_at(anchor),
                f"lock-order cycle {ring}: opposite acquisition orders "
                f"can deadlock ({'; '.join(steps)})",
                hint="pick one global order for these locks and release "
                     "before acquiring against it (docs/STATIC_ANALYSIS.md "
                     "'Lock order')"))

        for e in graph.self_deadlocks():
            # re-acquiring a plain (non-reentrant) Lock that may already
            # be held: the inner acquire can never succeed
            src = by_rel.get(e.rel)
            if src is None:
                continue
            via = (f" (held via {e.chain} -> {e.func})" if e.chain
                   else f" in {e.func}")
            findings.append(src.finding(
                "MT-LOCK-ORDER", _node_at(e),
                f"re-acquiring non-reentrant lock {e.src} while it is "
                f"already held{via}: a plain Lock self-deadlocks",
                hint="use an RLock if re-entry is intended, or release "
                     "before calling back into the acquiring path"))

        for qual, decl in sorted(graph.locks.items()):
            if decl.lockdep_name is None or decl.lockdep_name == qual:
                continue
            src = by_rel.get(decl.rel)
            if src is None:
                continue
            findings.append(src.finding(
                "MT-LOCK-NAME", decl.node,
                f"lockdep lock named {decl.lockdep_name!r} but the static "
                f"graph knows it as {qual!r} — the runtime witness would "
                f"cross-check against the wrong node",
                hint=f"name it {qual!r} (owning class + attribute)"))

        for qual, decls in sorted(graph.lock_collisions.items()):
            # two same-named classes in different modules declared the
            # same `Class.attr` identity: the graph (and the witness)
            # would fuse two unrelated locks into one node — false
            # cycles, or worse, a real runtime ordering vacuously
            # whitelisted. The first declaration keeps the identity;
            # flag every later one at its own site.
            sites = ", ".join(f"{d.rel}:{d.lineno}" for d in decls)
            for d in decls[1:]:
                src = by_rel.get(d.rel)
                if src is None:
                    continue
                findings.append(src.finding(
                    "MT-LOCK-NAME", d.node,
                    f"ambiguous lock identity {qual!r}: declared at "
                    f"{sites} — same-named classes would merge into one "
                    f"node in the lock-order graph and the runtime "
                    f"witness",
                    hint="rename one class (or the lock attribute) so "
                         "every lock has a unique <Class>.<attr> "
                         "identity"))
        return findings


class _Anchor:
    """Minimal node-shaped object for Source.finding anchoring."""

    def __init__(self, lineno: int):
        self.lineno = lineno
        self.col_offset = 0


def _node_at(edge: "cg.LockEdge") -> _Anchor:
    return _Anchor(edge.lineno)
