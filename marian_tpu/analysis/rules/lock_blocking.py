"""lock-blocking (MT-LOCK-BLOCKING): blocking operations reachable while
a lock is held (ISSUE 6 tentpole).

The serving invariants "warmup happens off the serving path" and "swap
is an atomic between-batches re-point" are really claims that nothing
slow ever runs under the control-plane locks: a model load, a jit
compile, a file read, an untimed ``future.result()`` under
``SwapController._lock`` stalls ``route()`` — and with it every device
batch — for the duration. This rule makes the claim checkable: using the
call graph's interprocedural held-set propagation (the same machinery as
MT-LOCK-ORDER), any call classified as blocking that executes while ANY
known lock may be held is a finding, anchored at the blocking call with
an example holder chain in the message.

Blocking classification (the host-sync rule's call-table approach,
extended):

- named calls: ``time.sleep``, ``open``, ``subprocess.run/call/
  check_call/check_output/Popen``, ``urllib.request.urlopen``,
  ``socket.create_connection``, ``np.load/save/savez``,
  ``jax.block_until_ready`` / ``jax.device_put`` (device sync /
  transfer), and ``warm_executor`` (model load + jit compile + golden
  smoke — THE warmup-off-the-serving-path sentinel);
- zero-argument ``.result()`` / ``.join()`` / ``.wait()`` / ``.get()``
  attribute calls: without a timeout these block forever (a
  zero-argument ``dict.get()`` is a TypeError, so the no-arg form really
  is the queue/future/thread one);
- ``await``-ed calls are exempt: an awaited coroutine yields the event
  loop instead of wedging the thread (and asyncio code holds no
  threading locks across awaits in this tree).

Deliberate blocking-under-lock (the native library's one-time lazy
g++ build, fault injection's hang mode) is acknowledged inline with
``# mtlint: ok -- reason`` at the blocking site.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .. import callgraph as cg
from ..core import Config, Finding, Source
from . import Rule, register

BLOCKING_NAMED = {
    "time.sleep": "time.sleep",
    "open": "file open",
    "subprocess.run": "subprocess",
    "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.Popen": "subprocess",
    "urllib.request.urlopen": "network request",
    "socket.create_connection": "network connect",
    "np.load": "file IO",
    "numpy.load": "file IO",
    "np.save": "file IO",
    "np.savez": "file IO",
    "numpy.savez": "file IO",
    "os.fsync": "fsync",
    "jax.block_until_ready": "device sync",
    "jax.device_put": "device transfer",
    "warm_executor": "model warmup (load + jit compile + golden smoke)",
}

# zero-argument forms of these attribute calls block without a timeout
BLOCKING_NOARG_ATTRS = {
    "result": "future.result() without timeout",
    "join": "join() without timeout",
    "wait": "wait() without timeout",
    "get": "blocking get() without timeout",
}


def classify(site: "cg.CallSite") -> Optional[str]:
    """A human label when the call site is a blocking operation."""
    if site.awaited or site.spawn:
        return None
    name = site.name
    if name in BLOCKING_NAMED:
        return BLOCKING_NAMED[name]
    node = site.node
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in BLOCKING_NOARG_ATTRS \
            and not node.args and not node.keywords:
        return BLOCKING_NOARG_ATTRS[node.func.attr]
    return None


@register
class LockBlockingRule(Rule):
    family = "lock-blocking"
    ids = ("MT-LOCK-BLOCKING",)
    scope = "project"

    def check_project(self, sources: List[Source],
                      config: Config) -> List[Finding]:
        graph = cg.build_cached(sources)
        by_rel = {s.rel: s for s in sources}
        findings: List[Finding] = []
        for qual in sorted(graph.functions):
            fn = graph.functions[qual]
            src = by_rel.get(fn.rel)
            if src is None:
                continue
            entry = graph.entry_held(fn.qual)
            seen_lines = set()
            for site in fn.calls:
                label = classify(site)
                if label is None:
                    continue
                held = entry | set(site.held)
                if not held or site.node.lineno in seen_lines:
                    continue
                seen_lines.add(site.node.lineno)
                lock = sorted(held)[0]
                if lock in site.held:
                    how = "held here"
                else:
                    chain = graph.holder_chain(fn.qual, lock)
                    how = (f"held by caller chain {chain} -> {fn.display}"
                           if chain else "held at entry")
                more = f" (+{len(held) - 1} more)" if len(held) > 1 else ""
                findings.append(src.finding(
                    "MT-LOCK-BLOCKING", site.node,
                    f"blocking {label} reachable while `{lock}`{more} is "
                    f"{how} — everything contending that lock stalls for "
                    f"the duration",
                    hint="move the blocking work outside the lock "
                         "(snapshot under the lock, act after release), "
                         "add a timeout, or acknowledge a deliberate "
                         "stall with `# mtlint: ok -- reason`"))
        return findings
