"""Static jit compile-cache model for mtlint (ISSUE 17 tentpole).

Marian's speed story is "compile once, serve forever" (PAPER.md): the
set of XLA compile keys a serving process can reach must be provably
FINITE, or ROADMAP item 5's AOT compile cache is unwinnable and the
``marian_compile_backend_seconds_total`` ledger (PR 13) melts one
unbucketed shape at a time. This module is the static half of that
discipline, in the mold of analysis/ownership.py: enumerate every jit
boundary in the project, derive each site's COMPILE-KEY DOMAIN, and
keep the enumeration honest with a runtime witness
(common/jitwit.py) that fails tier-1 when a backend compile fires at a
site the model never predicted.

Three things live here, shared by the rule family (rules/jit.py) and
the witness cross-check:

- **The jit-site scan** (:func:`collect_jit_sites`): every
  ``jax.jit``/``pjit``/``shard_map`` creation (decorator, ``partial``
  decorator, wrapper binding, inline call) and every ``lax.scan`` call,
  identified ``<rel>::<function>`` — exactly what a runtime stack
  frame's ``(co_filename, co_name)`` resolves to. A site whose
  enclosing function takes parameters that the traced inner function
  captures is a **jit factory** (``_make_step(rb)``): those parameters
  ARE compile-key axes.

- **The bucket-registry vocabulary**, mirroring ``# guarded-by:`` /
  ``# owns:``: a ``# buckets: <REGISTRY>`` comment on a jit factory's
  ``def`` line (or the line above) declares which finite table the
  factory's key axes are drawn from. Registries are discovered
  statically (:func:`collect_registries`): any module/class-level
  ``ALL_CAPS`` assignment whose name ends in ``BUCKETS`` or ``BLOCKS``
  with integer contents (``ROW_BUCKETS``, ``JOIN_BUCKETS``,
  ``KERNEL_BLOCKS``, ``DEFAULT_LENGTH_BUCKETS``), plus the two virtual
  registries ``POW2`` (power-of-two domains: the beam fork pads) and
  ``HALVING`` (the encode width chain src_cap, /2, /4, ... >= 8).
  MT-JIT-STATIC-UNBOUNDED fires on an unannotated factory axis and on
  an annotation naming a registry the scan never found.

- **The compile-capability map** (:class:`JitModel`): per function,
  whether a backend compile may legitimately originate there — it
  creates a jit object, it references a jit binding (calls through
  ``self._step_jit[rb]`` / a wrapped name), or it runs eager
  jnp/lax ops (each new eager op shape compiles once too). The runtime
  witness asserts every observed backend compile's attribution site is
  compile-capable; an unknown site means a jit boundary this model
  never scanned — extend the model, never baseline it.

Documented limits (deliberate, witness-kept-honest): call-key domains
that live inside jax's own per-shape caches (one jit object
specializing per input shape, the ``_install`` pattern) are modeled at
the creating site, not per shape — the engines note their shape keys
to the witness explicitly; factories invoked through locals bound to
callables are modeled as sites but their call-site argument derivation
is checked only through direct-name calls.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Source, ancestors, call_name, dotted_name

# -- annotation vocabulary ---------------------------------------------------

BUCKETS_RE = re.compile(r"buckets:\s*([A-Za-z_][A-Za-z0-9_]*"
                        r"(?:\s*,\s*[A-Za-z_][A-Za-z0-9_]*)*)")

# virtual registries: finite by construction, membership is a predicate
# (common/jitwit.py implements it), not a value table
VIRTUAL_REGISTRIES = frozenset({"POW2", "HALVING"})

# registry-name shape the scan accepts (module/class-level constants)
_REGISTRY_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*(BUCKETS|BLOCKS)$")

# calls that derive a value FROM a declared bucket table — an argument
# built through one of these is bucket-bounded without an annotation
BUCKET_DERIVERS = frozenset({"bucket_rows", "bucket_length",
                             "pages_for_tokens"})

JIT_TAILS = {"jit", "pjit", "shard_map"}


def buckets_annotation(src: Source, lineno: int) -> Tuple[str, ...]:
    """Registry names from a ``# buckets: A[, B]`` comment on the line
    or the line above it (the ``# owns:`` placement convention)."""
    for ln in (lineno, lineno - 1):
        m = BUCKETS_RE.search(src.comments.get(ln, ""))
        if m:
            return tuple(p.strip() for p in m.group(1).split(","))
    return ()


# -- registry discovery ------------------------------------------------------

def _int_leaves(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """Every int constant under a tuple/list/dict literal; None when the
    node holds anything non-constant (a computed table is not a
    registry the witness can check values against)."""
    vals: List[int] = []
    for n in ast.walk(node):
        if isinstance(n, ast.expr_context):
            continue
        if isinstance(n, ast.Constant):
            if isinstance(n.value, bool):
                return None
            if isinstance(n.value, int):
                vals.append(n.value)
            elif not isinstance(n.value, str):
                return None
        elif not isinstance(n, (ast.Tuple, ast.List, ast.Dict)):
            return None
    return tuple(sorted(set(vals))) if vals else None


def collect_registries(sources: Sequence[Source]) -> Dict[str,
                                                          Tuple[int, ...]]:
    """NAME -> sorted int values for every module/class-level constant
    matching the registry name shape (``*BUCKETS`` / ``*BLOCKS``).
    ``KERNEL_BLOCKS``'s nested dicts flatten to their int leaves — the
    capacity numbers are the domain."""
    out: Dict[str, Tuple[int, ...]] = {}
    for src in sources:
        bodies = [src.tree.body]
        bodies.extend(n.body for n in ast.walk(src.tree)
                      if isinstance(n, ast.ClassDef))
        for body in bodies:
            for stmt in body:
                if isinstance(stmt, ast.Assign) \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    target = stmt.targets[0]
                elif isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name) \
                        and stmt.value is not None:
                    target = stmt.target   # ROW_BUCKETS: Tuple[...] = (...)
                else:
                    continue
                name = target.id
                if not _REGISTRY_NAME_RE.match(name):
                    continue
                vals = _int_leaves(stmt.value)
                if vals:
                    # first declaration wins (ROW_BUCKETS re-exported
                    # through translator imports is the same table)
                    out.setdefault(name, vals)
    return out


# -- jit-site extraction -----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class JitSite:
    rel: str
    lineno: int
    func: str                  # enclosing function leaf name (co_name)
    site: str                  # "<rel>::<func>"
    kind: str                  # "decorator" | "wrapper" | "inline" | "scan"
    inner_name: str            # traced function's name ("" for lambda/expr)
    factory_params: Tuple[str, ...]   # enclosing-fn params the traced
    #                                   body captures: compile-key axes
    buckets: Tuple[str, ...]   # declared registries for those axes
    static_nums: Tuple[int, ...] = ()
    static_names: Tuple[str, ...] = ()


def _enclosing_func(node: ast.AST) -> Optional[ast.AST]:
    for p in ancestors(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return p
    return None


def _func_leafname(node: Optional[ast.AST]) -> str:
    if node is None:
        return "<module>"
    if isinstance(node, ast.Lambda):
        return "<lambda>"
    return node.name


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs])


def _bool_like_param(fn: ast.AST, name: str) -> bool:
    """Params that are structurally two-valued are a bounded key axis by
    themselves: bool-annotated, bool-defaulted, or has_*/is_*/use_*/
    want_*-named flags."""
    if name.startswith(("has_", "is_", "use_", "want_", "allow_")):
        return True
    a = fn.args
    params = [*a.posonlyargs, *a.args]
    defaults = a.defaults
    for i, p in enumerate(params):
        if p.arg != name:
            continue
        ann = p.annotation
        if ann is not None and isinstance(ann, ast.Name) \
                and ann.id == "bool":
            return True
        di = i - (len(params) - len(defaults))
        if 0 <= di < len(defaults):
            d = defaults[di]
            if isinstance(d, ast.Constant) and isinstance(d.value, bool):
                return True
    return False


_INTLIKE_NAME_RE = re.compile(
    r"^(rb|jb|n|k|w|h)$|rows|width|steps|bucket|size|num|count|updates"
    r"|length|_len$|^len_")


def _intlike_param(fn: ast.AST, name: str) -> bool:
    """Params that look like SHAPE/COUNT knobs — the unbounded-domain
    risk. Object captures (model, cfg, masks) pin Python identity into
    the jit's key instead: bounded by the owner's lifetime, and not a
    per-call shape axis, so they are not treated as key axes."""
    if _INTLIKE_NAME_RE.search(name):
        return True
    a = fn.args
    params = [*a.posonlyargs, *a.args]
    defaults = a.defaults
    for i, p in enumerate(params):
        if p.arg != name:
            continue
        ann = p.annotation
        if ann is not None and isinstance(ann, ast.Name) \
                and ann.id in ("int", "float"):
            return True
        di = i - (len(params) - len(defaults))
        if 0 <= di < len(defaults):
            d = defaults[di]
            if isinstance(d, ast.Constant) \
                    and isinstance(d.value, (int, float)) \
                    and not isinstance(d.value, bool):
                return True
    return False


def _names_read(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _traced_fn_for(call: ast.Call, src: Source) -> Optional[ast.AST]:
    """The function ast a ``jax.jit(...)`` creation call traces: a
    lambda argument, or a sibling local ``def`` matched by name."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Lambda):
        return arg
    name = dotted_name(arg)
    if name is None or "." in name:
        return None
    scope = _enclosing_func(call)
    body_holder = scope if scope is not None else src.tree
    for n in ast.walk(body_holder):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n.name == name:
            return n
    return None


def _static_args(call: ast.Call) -> Tuple[Tuple[int, ...],
                                          Tuple[str, ...]]:
    from .core import const_int_tuple, const_str_tuple
    nums: Tuple[int, ...] = ()
    names: Tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = const_int_tuple(kw.value) or ()
        elif kw.arg == "static_argnames":
            names = const_str_tuple(kw.value) or ()
    return nums, names


def collect_jit_sites(sources: Sequence[Source]) -> List[JitSite]:
    """Every jit/scan boundary in ``sources`` (deterministic order)."""
    out: List[JitSite] = []
    for src in sources:
        # decorated defs: @jax.jit / @partial(jax.jit, ...)
        from .rules.trace_safety import _jit_decorator_info
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    info = _jit_decorator_info(dec)
                    if info is None:
                        continue
                    encl = _enclosing_func(node)
                    fname = _func_leafname(encl) if encl is not None \
                        else node.name
                    out.append(JitSite(
                        rel=src.rel, lineno=node.lineno, func=fname,
                        site=f"{src.rel}::{fname}", kind="decorator",
                        inner_name=node.name,
                        factory_params=_factory_axes(encl, node),
                        buckets=buckets_annotation(
                            src, (encl or node).lineno),
                        static_nums=tuple(sorted(info[0])),
                        static_names=tuple(sorted(info[1]))))
                    break
            elif isinstance(node, ast.Call):
                name = call_name(node)
                tail = (name or "").rsplit(".", 1)[-1]
                if tail == "scan" and name \
                        and name.split(".")[-2:-1] == ["lax"]:
                    encl = _enclosing_func(node)
                    fname = _func_leafname(encl)
                    out.append(JitSite(
                        rel=src.rel, lineno=node.lineno, func=fname,
                        site=f"{src.rel}::{fname}", kind="scan",
                        inner_name="", factory_params=(), buckets=()))
                    continue
                if tail not in JIT_TAILS or name in (None, "jit"):
                    # bare `jit(` without a jax-ish qualifier is too
                    # ambiguous to claim; the repo idiom is jax.jit
                    pass
                if tail in JIT_TAILS and name is not None \
                        and (name.startswith("jax.") or "." not in name):
                    encl = _enclosing_func(node)
                    fname = _func_leafname(encl)
                    traced = _traced_fn_for(node, src)
                    nums, snames = _static_args(node)
                    kind = "wrapper" if traced is not None else "inline"
                    out.append(JitSite(
                        rel=src.rel, lineno=node.lineno, func=fname,
                        site=f"{src.rel}::{fname}", kind=kind,
                        inner_name=_func_leafname(traced)
                        if traced is not None else "",
                        factory_params=_factory_axes(encl, traced),
                        buckets=buckets_annotation(
                            src, encl.lineno if encl is not None
                            and not isinstance(encl, ast.Lambda)
                            else node.lineno),
                        static_nums=nums, static_names=snames))
    out.sort(key=lambda s: (s.rel, s.lineno, s.kind))
    return out


def _factory_axes(encl: Optional[ast.AST],
                  traced: Optional[ast.AST]) -> Tuple[str, ...]:
    """Enclosing-function parameters the traced body captures — the
    compile-key axes of a jit factory (``_make_step(rb)``: rb)."""
    if encl is None or traced is None \
            or isinstance(encl, ast.Lambda):
        return ()
    reads = _names_read(traced)
    axes = []
    for p in _param_names(encl):
        if p in ("self", "cls"):
            continue
        if p in reads and not _bool_like_param(encl, p) \
                and _intlike_param(encl, p):
            axes.append(p)
    return tuple(axes)


# -- the project model -------------------------------------------------------

# attribute-name shape of engine-managed jit caches (self._step_jit,
# self._install_jit, self._fork_jit, ...): reading one of these from a
# function marks it a potential jit CALL site
_JIT_BINDING_ATTR_RE = re.compile(r"(_jit$|^_jit|_jitted)")


class JitModel:
    """Project-wide jit-boundary model: sites, registries, and the
    compile-capability map the runtime witness checks against."""

    def __init__(self):
        self.sites: List[JitSite] = []
        self.registries: Dict[str, Tuple[int, ...]] = {}
        # site id -> declared bucket registries (factory annotations)
        self.site_buckets: Dict[str, Tuple[str, ...]] = {}
        # "<rel>::<func>" where a backend compile may originate
        self.compile_capable: Set[str] = set()
        # jit-creating site ids only (the strict set the rules use)
        self.jit_site_ids: Set[str] = set()

    def known_registry(self, name: str) -> bool:
        return name in self.registries or name in VIRTUAL_REGISTRIES

    def registry_values(self, name: str) -> Optional[Tuple[int, ...]]:
        return self.registries.get(name)

    @classmethod
    def build(cls, sources: Sequence[Source]) -> "JitModel":
        m = cls()
        m.registries = collect_registries(sources)
        m.sites = collect_jit_sites(sources)
        for s in m.sites:
            m.jit_site_ids.add(s.site)
            if s.buckets:
                prev = m.site_buckets.get(s.site, ())
                m.site_buckets[s.site] = tuple(
                    dict.fromkeys(prev + s.buckets))
        # compile capability: creators, jit-binding referencers, eager
        # jnp/lax users — walked per function over every source. In a
        # module that imports jax at all, EVERY function is capable:
        # eager dispatch compiles wherever arrays flow (iterating a key
        # array compiles a gather in the iterating frame, with no
        # jnp/jax name in sight), so the honest claim is per-module.
        # Functions in jax-free modules (the serving scheduler, the
        # analysis layer) stay non-capable — a compile attributed there
        # is a real finding.
        for src in sources:
            jax_module = m._imports_jax(src.tree)
            funcs: List[Tuple[str, ast.AST]] = [("<module>", src.tree)]
            funcs += [(n.name, n) for n in ast.walk(src.tree)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))]
            for fname, node in funcs:
                if jax_module or m._compile_capable_body(fname, node):
                    m.compile_capable.add(f"{src.rel}::{fname}")
        m.compile_capable |= m.jit_site_ids
        return m

    @staticmethod
    def _imports_jax(tree: ast.Module) -> bool:
        for n in ast.walk(tree):
            if isinstance(n, ast.Import):
                if any(a.name == "jax" or a.name.startswith("jax.")
                       for a in n.names):
                    return True
            elif isinstance(n, ast.ImportFrom):
                if n.module and (n.module == "jax"
                                 or n.module.startswith("jax.")):
                    return True
        return False

    @staticmethod
    def _compile_capable_body(fname: str, node: ast.AST) -> bool:
        for n in ast.walk(node):
            # don't credit a parent for a nested def's body — the
            # nested function is its own frame at runtime
            if n is not node and isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fname != "<module>":
                continue
            if isinstance(n, ast.Name) and n.id in ("jnp", "jax", "lax"):
                return True
            if isinstance(n, ast.Attribute) \
                    and _JIT_BINDING_ATTR_RE.search(n.attr):
                return True
        return False


_MODEL_CACHE: Dict[tuple, JitModel] = {}


def _model_cached(sources) -> JitModel:
    # same one-entry content-keyed policy as callgraph.build_cached:
    # the witnesses re-check at every module teardown over unchanged
    # sources, so the rebuild would be pure repeated work
    key = tuple(sorted((s.rel, hash(s.text)) for s in sources))
    m = _MODEL_CACHE.get(key)
    if m is None:
        _MODEL_CACHE.clear()
        m = _MODEL_CACHE[key] = JitModel.build(sources)
    return m


def static_jit_model(root) -> JitModel:
    """The jit model for the repo at ``root`` — what the runtime
    retrace witness (common/jitwit.py) cross-checks observed backend
    compiles against. Stdlib-only, never imports the analyzed code."""
    from pathlib import Path

    from .core import Config, collect_sources_cached
    root = Path(root)
    config = Config.load(root)
    sources = collect_sources_cached([root / "marian_tpu"], config)
    return _model_cached(sources)
