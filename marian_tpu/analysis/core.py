"""mtlint core: findings, parsed sources, configuration, baseline.

The analysis layer is stdlib-only on purpose — `python -m marian_tpu.analysis`
must run (and the tier-1 gate must fail fast) on a box with no jax installed,
and importing the linted package would execute it. Everything works on `ast`
trees plus the token stream (for comments: `# guarded-by:` annotations and
`# mtlint:` suppressions live there).

Baseline semantics: a finding is identified by (rule, path, stripped source
line) rather than line NUMBER, so unrelated edits above a pre-existing
finding don't resurrect it; duplicate keys are counted, so adding a SECOND
violation identical to a baselined one is still reported.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import tokenize
from collections import Counter
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SUPPRESS_TAG = "mtlint:"

# Bumped whenever any rule's behavior changes: the incremental result
# cache (cli --changed / --cache) is dropped wholesale on a mismatch, so
# a rule upgrade can never serve stale per-file verdicts.
# v4: MT-SPAN family (span_hygiene) + callgraph resolves package
#     re-export calls (obs.event -> Tracer.event lock edges).
# v5: MT-METRIC-UNTESTED (every registered metric name must be exercised
#     by tests/ — the metrics mirror of MT-FAULT-UNTESTED) +
#     MT-SPAN-UNCLOSED recognizes the keyword close form `end(span=sp)`.
# v6: MT-OWN family (ownership) — static resource-ownership & leak
#     analysis over the KVPool/prefix-cache/executor/engine/file verb
#     registry, with the `# owns: caller|callee` / `# mtlint: transfers`
#     annotation vocabulary (validated at runtime by common/ownwit.py).
# v7: MT-JIT family (jit) — static compile-cache analysis over every
#     jax.jit/pjit/shard_map/lax.scan boundary: compile-key domains,
#     the `# buckets: <REGISTRY>` annotation vocabulary, and warmup
#     reachability (validated at runtime by common/jitwit.py).
RULESET_VERSION = 7


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str          # rule id, e.g. "MT-LOCK-GUARD"
    path: str          # posix path relative to the project root
    line: int          # 1-based
    col: int           # 0-based
    message: str
    hint: str = ""
    code: str = ""     # stripped source line — the baseline identity

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.code)

    def render(self) -> str:
        s = f"{self.path}:{self.line}:{self.col + 1}: {self.rule} {self.message}"
        if self.hint:
            s += f" [hint: {self.hint}]"
        return s

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)


class SourceError(Exception):
    """A file that should lint but cannot even be parsed."""


class Source:
    """One parsed Python file: AST with parent links, raw lines, and the
    comment text per line (end-of-line comments carry annotations)."""

    def __init__(self, path: Path, rel: str, text: Optional[str] = None):
        self.path = path
        self.rel = rel.replace("\\", "/")
        if text is None:
            text = path.read_text(encoding="utf-8")
        self.text = text
        self.lines = text.splitlines()
        try:
            self.tree = ast.parse(text, filename=str(path))
        except SyntaxError as e:
            raise SourceError(f"{rel}: syntax error at line {e.lineno}: "
                              f"{e.msg}") from e
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._mtlint_parent = parent  # type: ignore[attr-defined]
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string.lstrip("#").strip()
        except tokenize.TokenError:
            pass  # trailing-garbage tolerable; the ast parse already passed

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str,
                hint: str = "") -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=self.rel, line=line, col=col,
                       message=message, hint=hint,
                       code=self.line_text(line))

    def suppressed(self, finding: Finding) -> bool:
        """`# mtlint: ok` / `# mtlint: disable=MT-XXX[,MT-YYY]` on the
        finding's line (an optional trailing reason after ' -- ' is for
        humans). Family prefixes work: disable=MT-DTYPE covers both
        MT-DTYPE-LITERAL and MT-DTYPE-ARRAY."""
        comment = self.comments.get(finding.line, "")
        if not comment.startswith(SUPPRESS_TAG):
            return False
        body = comment[len(SUPPRESS_TAG):].split("--", 1)[0].strip()
        if body == "ok" or body.startswith("ok "):
            return True
        if body.startswith("disable="):
            rules = [r.strip() for r in body[len("disable="):].split(",")]
            return any(finding.rule == r or finding.rule.startswith(r + "-")
                       for r in rules if r)
        return False


# ---------------------------------------------------------------------------
# AST helpers shared by the rules
# ---------------------------------------------------------------------------

def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_mtlint_parent", None)


def ancestors(node: ast.AST) -> Iterable[ast.AST]:
    p = parent(node)
    while p is not None:
        yield p
        p = parent(p)


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def names_in(node: ast.AST) -> set:
    """All bare Name identifiers read anywhere under `node`."""
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def const_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """Literal int / tuple-or-list-of-ints, e.g. donate_argnums=(0, 1)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            vals.append(elt.value)
        return tuple(vals)
    return None


def const_str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)):
                return None
            vals.append(elt.value)
        return tuple(vals)
    return None


_TESTS_CORPUS_CACHE: Dict[str, str] = {}


def tests_string_corpus(config: "Config") -> str:
    """Every STRING CONSTANT in every file under ``<root>/tests``,
    newline-joined — the "is this name ever exercised by a test" corpus
    shared by the fault- and metrics-hygiene UNTESTED rules. String
    constants (not raw text) so a name mentioned only in a comment does
    not count as coverage; a file that fails to parse falls back to raw
    text so one broken test file cannot mass-flag a catalog.

    Memoized per root for the life of the process: both UNTESTED rules
    call it on every project run, and re-parsing the whole tests/ tree
    twice per lint would grow pre-commit latency with every PR. (A CLI
    run is one-shot; in a long-lived process edits to tests/ after the
    first lint are not picked up — acceptable for an advisory corpus.)
    """
    key = str(config.root.resolve())
    cached = _TESTS_CORPUS_CACHE.get(key)
    if cached is not None:
        return cached
    tests_dir = config.root / "tests"
    chunks: List[str] = []
    if tests_dir.is_dir():
        for p in sorted(tests_dir.rglob("*.py")):
            try:
                text = p.read_text(encoding="utf-8")
            except OSError:
                continue
            try:
                tree = ast.parse(text)
            except SyntaxError:
                chunks.append(text)
                continue
            chunks.extend(n.value for n in ast.walk(tree)
                          if isinstance(n, ast.Constant)
                          and isinstance(n.value, str))
    corpus = "\n".join(chunks)
    _TESTS_CORPUS_CACHE[key] = corpus
    return corpus


# ---------------------------------------------------------------------------
# configuration — pyproject.toml [tool.mtlint]
# ---------------------------------------------------------------------------

# Directory scoping defaults (overridable from pyproject): rules whose cost/
# noise profile only makes sense on specific layers run only there.
DEFAULT_RULE_DIRS: Dict[str, List[str]] = {
    # host-sync: files "marked hot" — the decode/train/op layers where an
    # accidental device->host transfer costs a pipeline stall
    "host-sync": ["marian_tpu/ops", "marian_tpu/translator",
                  "marian_tpu/training"],
    # dtype hygiene: bf16 compute paths
    "dtype": ["marian_tpu/ops", "marian_tpu/layers"],
    # guarded-by + escape analysis: the threaded layers
    "guarded-by": ["marian_tpu/serving", "marian_tpu/training"],
    "guard-escape": ["marian_tpu/serving", "marian_tpu/training"],
    # everywhere: trace-safety, donation, metrics, fault hygiene, and the
    # call-graph lock rules (lock-order/lock-blocking need the WHOLE tree
    # — a serving lock can reach a blocking call in common/ via two hops)
    "trace-safety": [],
    "donation": [],
    "metrics": [],
    "faults": [],
    "lock-order": [],
    "lock-blocking": [],
    # span hygiene runs everywhere the tracer API can be used (obs
    # itself, serving, server, training, scripts)
    "span": [],
    # resource ownership (MT-OWN-*): everywhere — the KVPool verb
    # surface lives in translator/, but executors/threads/engines/file
    # handles are acquired across the whole tree
    "ownership": [],
    # compile-cache hygiene (MT-JIT-*): everywhere — jit boundaries
    # live in ops/, translator/, training/ and the UNWARMED
    # reachability walks serving/ -> translator/ across layers
    "jit": [],
}

DEFAULT_EXCLUDE = ["marian_tpu/analysis"]


@dataclasses.dataclass
class Config:
    root: Path
    exclude: List[str] = dataclasses.field(
        default_factory=lambda: list(DEFAULT_EXCLUDE))
    rule_dirs: Dict[str, List[str]] = dataclasses.field(
        default_factory=lambda: {k: list(v)
                                 for k, v in DEFAULT_RULE_DIRS.items()})
    disabled: List[str] = dataclasses.field(default_factory=list)

    def family_enabled(self, family: str) -> bool:
        return family not in self.disabled

    def family_applies(self, family: str, rel: str) -> bool:
        if not self.family_enabled(family):
            return False
        dirs = self.rule_dirs.get(family, [])
        if not dirs:
            return True
        rel = rel.replace("\\", "/")
        return any(rel == d or rel.startswith(d.rstrip("/") + "/")
                   for d in dirs)

    def excluded(self, rel: str) -> bool:
        rel = rel.replace("\\", "/")
        return any(rel == d or rel.startswith(d.rstrip("/") + "/")
                   for d in self.exclude)

    @classmethod
    def load(cls, root: Path) -> "Config":
        cfg = cls(root=root)
        pyproject = root / "pyproject.toml"
        if not pyproject.exists():
            return cfg
        data = _read_toml_tables(pyproject.read_text(encoding="utf-8"))
        top = data.get("tool.mtlint", {})
        if "exclude" in top:
            cfg.exclude = list(top["exclude"])
        if "disable" in top:
            cfg.disabled = list(top["disable"])
        # per-directory rule enablement: [tool.mtlint.rules.<family>]
        # dirs = [...] limits the family to those directory prefixes
        # (empty list = run everywhere); enabled = false turns it off.
        for table, kv in data.items():
            prefix = "tool.mtlint.rules."
            if not table.startswith(prefix):
                continue
            family = table[len(prefix):]
            if kv.get("enabled") is False and family not in cfg.disabled:
                cfg.disabled.append(family)
            if "dirs" in kv:
                cfg.rule_dirs[family] = list(kv["dirs"])
        return cfg


def _read_toml_tables(text: str) -> Dict[str, Dict]:
    """Minimal TOML-subset reader (this tree runs Python 3.10 — no tomllib,
    and mtlint must stay dependency-free). Supports [table.headers] and
    `key = value` with string / bool / int / float / array-of-strings
    values, including multi-line arrays. Unknown value shapes are skipped,
    never fatal — mtlint only consumes the [tool.mtlint*] tables."""
    tables: Dict[str, Dict] = {}
    current: Optional[Dict] = None
    pending_key: Optional[str] = None
    pending_buf = ""
    for raw in text.splitlines():
        line = raw.strip()
        if pending_key is not None:
            pending_buf += " " + line
            if _brackets_balanced(pending_buf):
                if current is not None:
                    val = _parse_toml_value(pending_buf)
                    if val is not None:
                        current[pending_key] = val
                pending_key, pending_buf = None, ""
            continue
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            name = line.strip("[]").strip().strip('"')
            current = tables.setdefault(name, {})
            continue
        if current is None or "=" not in line:
            continue
        key, _, rhs = line.partition("=")
        key, rhs = key.strip().strip('"'), rhs.strip()
        if rhs.startswith("[") and not _brackets_balanced(rhs):
            pending_key, pending_buf = key, rhs
            continue
        val = _parse_toml_value(rhs)
        if val is not None:
            current[key] = val
    return tables


def _brackets_balanced(s: str) -> bool:
    depth = 0
    in_str: Optional[str] = None
    for ch in s:
        if in_str:
            if ch == in_str:
                in_str = None
        elif ch in "\"'":
            in_str = ch
        elif ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        elif ch == "#" and depth == 0:
            break
    return depth <= 0


def _parse_toml_value(rhs: str):
    rhs = rhs.strip()
    if rhs.startswith("["):
        end = rhs.rfind("]")
        if end < 0:
            return None
        items = []
        for piece in _split_toml_array(rhs[1:end]):
            piece = piece.strip()
            if not piece:
                continue
            v = _parse_toml_value(piece)
            if v is None:
                return None
            items.append(v)
        return items
    if rhs[:1] in "\"'":
        q = rhs[0]
        end = rhs.find(q, 1)
        return rhs[1:end] if end > 0 else None
    word = rhs.split("#", 1)[0].strip()
    if word == "true":
        return True
    if word == "false":
        return False
    try:
        return int(word)
    except ValueError:
        pass
    try:
        return float(word)
    except ValueError:
        return None


def _split_toml_array(body: str) -> List[str]:
    parts, buf, in_str = [], "", None
    for ch in body:
        if in_str:
            buf += ch
            if ch == in_str:
                in_str = None
        elif ch in "\"'":
            in_str = ch
            buf += ch
        elif ch == ",":
            parts.append(buf)
            buf = ""
        else:
            buf += ch
    parts.append(buf)
    return parts


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: Path) -> Counter:
    """Baseline file -> Counter of finding keys (duplicates counted)."""
    if not path.exists():
        return Counter()
    data = json.loads(path.read_text(encoding="utf-8"))
    keys: Counter = Counter()
    for item in data.get("findings", []):
        keys[(item["rule"], item["path"], item.get("code", ""))] += 1
    return keys


def write_baseline(findings: Sequence[Finding], path: Path) -> None:
    items = [{"rule": f.rule, "path": f.path, "line": f.line,
              "code": f.code, "message": f.message}
             for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))]
    payload = {
        "version": BASELINE_VERSION,
        "comment": "Pre-existing mtlint findings suppressed from the tier-1 "
                   "gate. Regenerate with scripts/mtlint.py --update-baseline "
                   "(see docs/STATIC_ANALYSIS.md). Fix entries out of this "
                   "file; never add to it to get a PR green.",
        "findings": items,
    }
    path.write_text(json.dumps(payload, indent=1) + "\n", encoding="utf-8")


def apply_baseline(findings: Sequence[Finding],
                   baseline: Counter) -> Tuple[List[Finding], List[Finding]]:
    """-> (new findings, baselined findings). Each baseline entry absorbs at
    most as many findings as it was recorded times."""
    remaining = Counter(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        if remaining.get(f.key(), 0) > 0:
            remaining[f.key()] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


# ---------------------------------------------------------------------------
# incremental result cache (cli --changed / --cache)
# ---------------------------------------------------------------------------
#
# Per-file, content-hash-keyed verdicts for FILE-scope rules only:
# a file whose bytes did not change since the cached run keeps its cached
# findings (stored post-inline-suppression — suppression comments are part
# of the content hash). Project-scope rules (metrics/fault hygiene, the
# call-graph lock rules) are cross-file by definition and always re-run.
# The cache invalidates wholesale on a RULESET_VERSION bump or any change
# to the effective configuration. The full uncached run stays the CI
# source of truth (tests/test_mtlint.py::TestTier1Gate).

DEFAULT_CACHE = ".mtlint-cache.json"


def file_hash(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


_RULESET_HASH: Optional[str] = None


def ruleset_hash() -> str:
    """sha256 over the analysis package's own sources: editing a rule
    invalidates cached verdicts even when the developer forgets the
    RULESET_VERSION bump (which stays the documented covenant for
    behavior changes — this is the mechanical backstop)."""
    global _RULESET_HASH
    if _RULESET_HASH is None:
        h = hashlib.sha256()
        pkg = Path(__file__).resolve().parent
        for f in sorted(pkg.rglob("*.py")):
            h.update(f.relative_to(pkg).as_posix().encode())
            h.update(f.read_bytes())
        _RULESET_HASH = h.hexdigest()
    return _RULESET_HASH


def config_fingerprint(config: "Config",
                       rule_filter: Optional[Sequence[str]]) -> str:
    return json.dumps({
        "exclude": sorted(config.exclude),
        "dirs": {k: sorted(v) for k, v in sorted(config.rule_dirs.items())},
        "disabled": sorted(config.disabled),
        "filter": sorted(rule_filter) if rule_filter else None,
        "rule_sources": ruleset_hash(),
    }, sort_keys=True)


def load_result_cache(path: Path, config: "Config",
                      rule_filter: Optional[Sequence[str]] = None) -> Dict:
    fp = config_fingerprint(config, rule_filter)
    fresh = {"ruleset": RULESET_VERSION, "config": fp, "files": {}}
    try:
        data = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return fresh
    if not isinstance(data, dict) \
            or data.get("ruleset") != RULESET_VERSION \
            or data.get("config") != fp \
            or not isinstance(data.get("files"), dict):
        return fresh                     # version bump / config change
    return data


def save_result_cache(path: Path, cache: Dict) -> None:
    # atomic rewrite: a concurrent run (pre-commit racing an editor
    # lint) or a kill mid-write must never leave a truncated JSON —
    # load fails open, so a torn cache silently disables incrementality.
    # pid-unique tmp so two racing runs can't truncate each other's
    # staging file; last replace wins with a complete cache either way
    tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
    try:
        tmp.write_text(json.dumps(cache, indent=0) + "\n",
                       encoding="utf-8")
        os.replace(tmp, path)
    except OSError:
        try:
            tmp.unlink()
        except OSError:
            pass
        # a cache is advisory, never fatal


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

def collect_sources(paths: Sequence[Path], config: Config,
                    errors: Optional[List[str]] = None) -> List[Source]:
    files: List[Path] = []
    for p in paths:
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    sources: List[Source] = []
    seen = set()
    for f in files:
        try:
            rel = f.resolve().relative_to(config.root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        if rel in seen or config.excluded(rel):
            continue
        seen.add(rel)
        try:
            sources.append(Source(f, rel))
        except (SourceError, OSError, UnicodeDecodeError) as e:
            if errors is not None:
                errors.append(str(e))
    return sources


_SOURCES_CACHE: Dict[Tuple[str, Tuple[str, ...]],
                     Tuple[Tuple, List[Source]]] = {}


def _tree_signature(paths: Sequence[Path], config: Config) -> Tuple:
    """Stat signature (rel, mtime_ns, size) of every file
    :func:`collect_sources` would read for ``paths`` — cheap enough
    (no reads, no parses) to recompute on every cache probe."""
    sig = []
    for p in paths:
        if p.is_dir():
            files = sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            files = [p]
        else:
            continue
        for f in files:
            try:
                rel = f.resolve().relative_to(
                    config.root.resolve()).as_posix()
            except ValueError:
                rel = f.as_posix()
            if config.excluded(rel):
                continue
            try:
                st = f.stat()
            except OSError:
                continue
            sig.append((rel, st.st_mtime_ns, st.st_size))
    return tuple(sig)


def collect_sources_cached(paths: Sequence[Path],
                           config: Config) -> List[Source]:
    """:func:`collect_sources` memoized on the tree's stat signature.
    The runtime witnesses (common/{lockdep,ownwit,jitwit}
    ``check_against_static``) re-derive their static model at EVERY
    tier-1 module teardown; re-reading and re-parsing the whole
    package each time turns a pure cross-check into real suite wall
    time. One tree is kept per (root, paths) key; any file edit,
    addition, or deletion changes the signature and re-parses."""
    key = (str(config.root.resolve()),
           tuple(str(p) for p in paths))
    sig = _tree_signature(paths, config)
    hit = _SOURCES_CACHE.get(key)
    if hit is not None and hit[0] == sig:
        return hit[1]
    sources = collect_sources(paths, config)
    _SOURCES_CACHE[key] = (sig, sources)
    return sources


def run_lint(paths: Sequence[Path], config: Config,
             rule_filter: Optional[Sequence[str]] = None,
             errors: Optional[List[str]] = None,
             cache: Optional[Dict] = None) -> List[Finding]:
    """Run every registered rule over the given files/dirs; returns findings
    sorted by location with inline-suppressed ones removed. With `cache`
    (load_result_cache), file-scope rules reuse cached per-file verdicts
    for files whose content hash is unchanged; project-scope rules always
    re-run."""
    from .rules import all_rules
    sources = collect_sources(paths, config, errors=errors)
    by_rel = {s.rel: s for s in sources}
    rules = [r for r in all_rules()
             if (not rule_filter or r.family in rule_filter)
             and config.family_enabled(r.family)]
    findings: List[Finding] = []
    for rule in rules:
        if rule.scope != "project":
            continue
        scoped = [s for s in sources
                  if config.family_applies(rule.family, s.rel)]
        findings.extend(f for f in rule.check_project(scoped, config)
                        if not (f.path in by_rel
                                and by_rel[f.path].suppressed(f)))
    file_rules = [r for r in rules if r.scope != "project"]
    for src in sources:
        h = file_hash(src.text) if cache is not None else None
        ent = cache["files"].get(src.rel) if cache is not None else None
        if not isinstance(ent, dict):   # corrupt entry: advisory, not fatal
            ent = None
        if ent is not None and ent.get("hash") == h:
            try:
                replay = [Finding(**d) for d in ent["findings"]]
            except (KeyError, TypeError):
                # schema drift (a Finding field changed without a
                # RULESET_VERSION bump) or a corrupt entry: the cache is
                # advisory, never fatal — fall through and re-analyze
                replay = None
            if replay is not None:
                findings.extend(replay)
                continue
        fs: List[Finding] = []
        for rule in file_rules:
            if config.family_applies(rule.family, src.rel):
                fs.extend(rule.check(src, config))
        fs = [f for f in fs if not src.suppressed(f)]
        if cache is not None:
            cache["files"][src.rel] = {
                "hash": h, "findings": [f.to_json() for f in fs]}
        findings.extend(fs)
    if cache is not None:
        # prune entries for files that vanished from the scanned tree
        # (deleted/renamed), else the cache grows without bound. Only
        # within the scanned prefixes — a subset run must not evict the
        # rest of the tree's entries.
        prefixes = []
        for p in paths:
            try:
                prefixes.append(
                    p.resolve().relative_to(config.root.resolve()).as_posix())
            except ValueError:
                prefixes.append(p.as_posix())
        for rel in [r for r in cache["files"]
                    if r not in by_rel and any(
                        pre == "." or r == pre or r.startswith(pre + "/")
                        for pre in prefixes)]:
            del cache["files"][rel]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
