"""mtlint command line.

    python -m marian_tpu.analysis [paths...] [options]
    scripts/mtlint.py             [paths...] [options]

Exit codes: 0 = clean (no non-baselined findings), 1 = findings, 2 = usage
or parse errors. The tier-1 gate (tests/test_mtlint.py) is this command
with the checked-in baseline.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from .core import (Config, apply_baseline, load_baseline, run_lint,
                   write_baseline)

DEFAULT_BASELINE = "marian_tpu/analysis/baseline.json"


def find_root(start: Path) -> Path:
    """Nearest ancestor with a pyproject.toml (where [tool.mtlint] and
    baseline paths are anchored); falls back to cwd."""
    cur = start.resolve()
    for cand in (cur, *cur.parents):
        if (cand / "pyproject.toml").exists():
            return cand
    return start


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mtlint",
        description="JAX/TPU-aware static analysis for marian-tpu "
                    "(trace-safety, host-sync, donation, dtype, guarded-by, "
                    "metrics hygiene, fault-point hygiene)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories to lint (default: marian_tpu/)")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help="suppress findings recorded in FILE "
                        f"(default when present: {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline; report everything")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline with all current findings "
                        "and exit 0")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--rules", metavar="FAMILIES", default=None,
                   help="comma-separated rule families to run (default all): "
                        "trace-safety,host-sync,donation,dtype,guarded-by,"
                        "metrics,faults")
    p.add_argument("--root", metavar="DIR", default=None,
                   help="project root (default: nearest pyproject.toml)")
    p.add_argument("--list-rules", action="store_true",
                   help="print rule families and ids, then exit")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print findings the baseline suppresses")
    return p


def _list_rules() -> int:
    from .rules import all_rules
    for rule in all_rules():
        print(f"{rule.family:14s} {', '.join(rule.ids)}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        return _list_rules()

    root = Path(args.root) if args.root else find_root(Path.cwd())
    config = Config.load(root)
    paths = [Path(p) for p in (args.paths or [root / "marian_tpu"])]
    for p in paths:
        if not p.exists():
            print(f"mtlint: path does not exist: {p}", file=sys.stderr)
            return 2
    rule_filter = ([f.strip() for f in args.rules.split(",") if f.strip()]
                   if args.rules else None)

    errors: List[str] = []
    findings = run_lint(paths, config, rule_filter=rule_filter,
                        errors=errors)
    for e in errors:
        print(f"mtlint: {e}", file=sys.stderr)

    baseline_path: Optional[Path] = None
    if not args.no_baseline:
        if args.baseline:
            baseline_path = Path(args.baseline)
        elif (root / DEFAULT_BASELINE).exists() or args.update_baseline:
            baseline_path = root / DEFAULT_BASELINE

    if args.update_baseline:
        if baseline_path is None:
            baseline_path = root / DEFAULT_BASELINE
        baseline_path.parent.mkdir(parents=True, exist_ok=True)
        write_baseline(findings, baseline_path)
        print(f"mtlint: baseline written: {baseline_path} "
              f"({len(findings)} findings)")
        return 0

    baseline = load_baseline(baseline_path) if baseline_path else None
    if baseline is not None:
        new, old = apply_baseline(findings, baseline)
    else:
        new, old = list(findings), []

    if args.format == "json":
        payload = {
            "findings": [f.to_json() for f in new],
            "baselined": len(old),
            "errors": errors,
        }
        if args.show_baselined:
            payload["baselined_findings"] = [f.to_json() for f in old]
        print(json.dumps(payload, indent=1))
    else:
        for f in new:
            print(f.render())
        if args.show_baselined:
            for f in old:
                print(f"[baselined] {f.render()}")
        summary = f"mtlint: {len(new)} finding(s)"
        if old:
            summary += f", {len(old)} baselined"
        print(summary, file=sys.stderr)

    if errors:
        return 2
    return 1 if new else 0
