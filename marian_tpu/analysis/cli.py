"""mtlint command line.

    python -m marian_tpu.analysis [paths...] [options]
    scripts/mtlint.py             [paths...] [options]

Exit codes: 0 = clean (no non-baselined findings), 1 = findings, 2 = usage
or parse errors. The tier-1 gate (tests/test_mtlint.py) is this command
with the checked-in baseline.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import List, Optional

from .core import (DEFAULT_CACHE, Config, apply_baseline, collect_sources,
                   load_baseline, load_result_cache, run_lint,
                   save_result_cache, write_baseline)

DEFAULT_BASELINE = "marian_tpu/analysis/baseline.json"


def find_root(start: Path) -> Path:
    """Nearest ancestor with a pyproject.toml (where [tool.mtlint] and
    baseline paths are anchored); falls back to cwd."""
    cur = start.resolve()
    for cand in (cur, *cur.parents):
        if (cand / "pyproject.toml").exists():
            return cand
    return start


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mtlint",
        description="JAX/TPU-aware static analysis for marian-tpu "
                    "(trace-safety, host-sync, donation, dtype, guarded-by, "
                    "metrics hygiene, fault-point hygiene)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories to lint (default: marian_tpu/)")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help="suppress findings recorded in FILE "
                        f"(default when present: {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline; report everything")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline with all current findings "
                        "and exit 0")
    p.add_argument("--format",
                   choices=("text", "json", "sarif", "dot",
                            "ownership-dot"),
                   default="text",
                   help="text/json/sarif print findings (sarif = SARIF "
                        "2.1.0, renders as code annotations in CI and "
                        "editors); dot prints the lock-order graph "
                        "(Graphviz) instead of linting — the committed "
                        "snapshot is docs/lock_order.dot; ownership-dot "
                        "prints the resource-ownership graph — the "
                        "committed snapshot is docs/ownership.dot")
    p.add_argument("--rules", metavar="FAMILIES", default=None,
                   help="comma-separated rule families to run (default all): "
                        "trace-safety,host-sync,donation,dtype,guarded-by,"
                        "metrics,faults,lock-order,lock-blocking,"
                        "guard-escape,span,ownership,jit")
    p.add_argument("--changed", action="store_true",
                   help="incremental mode (scripts/mtlint-precommit.sh): "
                        "exit immediately when git reports no dirty .py "
                        "files under the lint paths, and use the result "
                        "cache so unchanged files are not re-analyzed "
                        "(full run stays the CI source of truth)")
    p.add_argument("--cache", action="store_true",
                   help="arm the content-hash result cache for file-scope "
                        "rules (implied by --changed; invalidated on "
                        "rule-source or config changes)")
    p.add_argument("--cache-file", metavar="FILE", default=None,
                   help="result cache location, implies --cache "
                        f"(default: <root>/{DEFAULT_CACHE})")
    p.add_argument("--root", metavar="DIR", default=None,
                   help="project root (default: nearest pyproject.toml)")
    p.add_argument("--list-rules", action="store_true",
                   help="print rule families and ids, then exit")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print findings the baseline suppresses")
    return p


def _list_rules() -> int:
    from .rules import all_rules
    for rule in all_rules():
        print(f"{rule.family:14s} {', '.join(rule.ids)}")
    return 0


def git_dirty_py(root: Path, paths: List[Path],
                 exts: tuple = (".py",)) -> Optional[List[str]]:
    """Dirty (staged + unstaged + untracked) files under `paths` with a
    suffix in `exts`, as git sees them; None when git is unavailable /
    not a repo (callers fall back to a full run — incremental mode must
    fail open)."""
    try:
        # -uall: without it git collapses a brand-new directory to one
        # `?? dir/` line whose name fails the suffix check, and a new
        # subpackage full of .py files would read as "nothing dirty"
        proc = subprocess.run(
            ["git", "-C", str(root), "status", "--porcelain",
             "--untracked-files=all", "--"]
            + [str(p) for p in paths],
            capture_output=True, text=True, timeout=60)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    dirty: List[str] = []
    for line in proc.stdout.splitlines():
        if len(line) < 4:
            continue
        name = line[3:].strip()
        if " -> " in name:           # rename: lint the new side
            name = name.split(" -> ", 1)[1]
        name = name.strip('"')
        if name.endswith(exts):
            dirty.append(name)
    return dirty


def _sarif(findings, errors: List[str]) -> dict:
    """SARIF 2.1.0 log for the given findings — uploadable to GitHub
    code scanning / renderable as inline annotations in editors (the
    CI satellite of ISSUE 15). Non-baselined findings only, matching
    the text/json verdicts; parse errors become toolExecution
    notifications."""
    from .core import RULESET_VERSION
    from .rules import all_rules
    rules_meta = []
    for rule in all_rules():
        for rid in rule.ids:
            meta = {"id": rid,
                    "properties": {"family": rule.family}}
            desc = rule.descriptions.get(rid)
            if desc:
                # rule metadata renders in code-scanning rule pages;
                # families that declare descriptions (jit) get them
                meta["name"] = rid.replace("MT-", "").title() \
                    .replace("-", "")
                meta["shortDescription"] = {"text": desc}
                meta["defaultConfiguration"] = {"level": "warning"}
            rules_meta.append(meta)
    results = []
    for f in findings:
        text = f.message + (f" [hint: {f.hint}]" if f.hint else "")
        results.append({
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": text},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                },
            }],
        })
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "mtlint",
                "version": f"{RULESET_VERSION}",
                "rules": rules_meta,
            }},
            "results": results,
            "invocations": [{
                "executionSuccessful": not errors,
                "toolExecutionNotifications": [
                    {"level": "error", "message": {"text": e}}
                    for e in errors],
            }],
        }],
    }


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        return _list_rules()

    # resolve: the --changed skip path hands root-relative pathspecs
    # (pyproject, tests/, baseline, the analysis package) to
    # `git -C <root>` — with a RELATIVE root they would resolve to
    # root/root/... and silently match nothing, false-skipping on a
    # dirty config (same class of bug as `paths` below)
    root = (Path(args.root).resolve() if args.root
            else find_root(Path.cwd()))
    config = Config.load(root)
    # resolve against the CALLER's cwd now: git_dirty_py hands these to
    # `git -C <root>`, where a cwd-relative pathspec would silently match
    # nothing and --changed would skip files the lint phase does see
    paths = [Path(p).resolve()
             for p in (args.paths or [root / "marian_tpu"])]
    for p in paths:
        if not p.exists():
            print(f"mtlint: path does not exist: {p}", file=sys.stderr)
            return 2
    rule_filter = ([f.strip() for f in args.rules.split(",") if f.strip()]
                   if args.rules else None)

    errors: List[str] = []
    if args.format == "dot":
        # render the lock-order graph instead of linting (the committed
        # snapshot docs/lock_order.dot; freshness is a tier-1 test)
        from . import callgraph as cg
        sources = collect_sources(paths, config, errors=errors)
        for e in errors:
            print(f"mtlint: {e}", file=sys.stderr)
        sys.stdout.write(cg.build_cached(sources).to_dot())
        return 2 if errors else 0
    if args.format == "ownership-dot":
        # the resource-ownership graph (ISSUE 15): acquire/release/
        # transfer sites + pairable edges, snapshotted at
        # docs/ownership.dot (freshness is a tier-1 test) and
        # cross-checked by the runtime witness (common/ownwit.py)
        from .ownership import OwnershipGraph
        sources = collect_sources(paths, config, errors=errors)
        for e in errors:
            print(f"mtlint: {e}", file=sys.stderr)
        sys.stdout.write(OwnershipGraph.build(sources).to_dot())
        return 2 if errors else 0

    if args.changed:
        dirty = git_dirty_py(root, paths)
        # lint RESULTS also depend on files outside the lint paths:
        # [tool.mtlint] in pyproject.toml gates rules, the faults family
        # scans tests/ for coverage, and the EXIT CODE depends on the
        # baseline. A commit touching only those must still run — only
        # skip when they are clean too (the result cache's config
        # fingerprint never engages on the skip path). --update-baseline
        # is an explicit write request and never skips; --no-baseline
        # changes the verdict itself (baselined findings resurface), so
        # "nothing changed since the commit" no longer implies exit 0.
        if dirty is not None and not dirty \
                and not args.update_baseline and not args.no_baseline:
            bl = Path(args.baseline).resolve() if args.baseline \
                else root / DEFAULT_BASELINE    # resolve: see `paths`
            # the analysis package itself is a result-changer too: when
            # this repo lints itself, an edited rule must not be skipped
            # just because the lint paths are a subset that excludes it
            # (the ruleset hash only guards the CACHE, which the skip
            # path never consults; in repos without the package the
            # pathspec matches nothing and is harmless)
            extra = git_dirty_py(
                root, [root / "pyproject.toml", root / "tests", bl,
                       root / "marian_tpu" / "analysis"],
                exts=(".py", ".toml", ".json"))
            if extra is not None and not extra:
                print("mtlint: no changed Python files under "
                      f"{', '.join(str(p) for p in paths)} (config, "
                      f"tests/ and baseline clean) — skipping",
                      file=sys.stderr)
                if args.format == "json":
                    # keep piped consumers parseable on the skip path
                    print(json.dumps({"findings": [], "baselined": 0,
                                      "errors": [], "skipped": True}))
                return 0
        args.cache = True            # --changed implies the result cache

    cache = cache_path = None
    if args.cache or args.cache_file:
        # an explicit file resolves against the CALLER's cwd (like
        # paths/--baseline); only the default lives under the root
        cache_path = (Path(args.cache_file).resolve() if args.cache_file
                      else root / DEFAULT_CACHE)
        cache = load_result_cache(cache_path, config, rule_filter)

    findings = run_lint(paths, config, rule_filter=rule_filter,
                        errors=errors, cache=cache)
    if cache_path is not None:
        save_result_cache(cache_path, cache)
    for e in errors:
        print(f"mtlint: {e}", file=sys.stderr)

    baseline_path: Optional[Path] = None
    if not args.no_baseline:
        if args.baseline:
            baseline_path = Path(args.baseline)
        elif (root / DEFAULT_BASELINE).exists() or args.update_baseline:
            baseline_path = root / DEFAULT_BASELINE

    if args.update_baseline:
        if baseline_path is None:
            baseline_path = root / DEFAULT_BASELINE
        baseline_path.parent.mkdir(parents=True, exist_ok=True)
        write_baseline(findings, baseline_path)
        print(f"mtlint: baseline written: {baseline_path} "
              f"({len(findings)} findings)")
        return 0

    baseline = load_baseline(baseline_path) if baseline_path else None
    if baseline is not None:
        new, old = apply_baseline(findings, baseline)
    else:
        new, old = list(findings), []

    if args.format == "json":
        payload = {
            "findings": [f.to_json() for f in new],
            "baselined": len(old),
            "errors": errors,
        }
        if args.show_baselined:
            payload["baselined_findings"] = [f.to_json() for f in old]
        print(json.dumps(payload, indent=1))
    elif args.format == "sarif":
        print(json.dumps(_sarif(new, errors), indent=1))
    else:
        for f in new:
            print(f.render())
        if args.show_baselined:
            for f in old:
                print(f"[baselined] {f.render()}")
        summary = f"mtlint: {len(new)} finding(s)"
        if old:
            summary += f", {len(old)} baselined"
        print(summary, file=sys.stderr)

    if errors:
        return 2
    return 1 if new else 0
