"""Project-wide call graph + lock model (ISSUE 6 tentpole).

The per-file rules (PR 7) see one function at a time; the serving control
plane's invariants — "warmup happens off the serving path", "swap is an
atomic between-batches re-point", "the controller→registry→scheduler→
metrics lock lattice is acyclic" — live in call chains ACROSS modules.
This module builds the whole-program model those rules need, with the
same zero-dependency discipline as core.py (stdlib ``ast`` only, never
imports the analyzed code):

- **Name/type index**: modules (dotted names derived from the repo
  layout), classes (with base resolution), module-level functions and
  instances, imports (absolute + relative, aliases, symbol imports).
- **Minimal type inference**, just enough to resolve the receivers the
  serving layer actually uses: ``self`` attributes assigned in any
  method from constructor calls / annotated parameters / methods with
  return annotations / ternaries; annotated parameters; module-level
  ``NAME = ClassName()`` instances; ``Optional[X]`` and string
  annotations.
- **Lock discovery**: ``self.X = threading.Lock()/RLock()`` or
  ``lockdep.make_lock/make_rlock(...)`` attributes (named
  ``<OwningClass>.<attr>`` — the class whose method ASSIGNS the attr,
  so subclasses share the base's lock identity), and module-level
  ``NAME = threading.Lock()`` (named ``<module>.<NAME>``). The
  ``lockdep`` name literal is kept for MT-LOCK-NAME cross-checking
  against the runtime witness (common/lockdep.py).
- **Per-function facts**: lock acquisitions (``with`` statements) and
  every call site, each annotated with the LEXICALLY held lock set;
  ``# mtlint: holds <lock>`` declarations seed entry-held sets.
  Callable references passed as arguments (``threading.Thread(target=
  self._run)``, ``loop.call_at(dl, self._expire, req)``,
  ``run_in_executor(ex, fn)``, ``set_function(self.queued_units)``)
  become SPAWN edges: reachable for reporting, but the spawning
  thread's held locks do not propagate into them — the target runs on
  another thread (or later on this one) where those locks are not held.
- **Interprocedural held-set propagation**: a fixpoint over call edges
  computes every function's may-be-held-at-entry lock set, with an
  example caller chain kept per (function, lock) for diagnostics.
- **Lock-order graph**: acquiring B while A is held adds edge A→B
  (reentrant re-acquisition of the same lock name adds nothing — the
  serving controller's RLock is reentrant by design). Cycles in this
  graph are static deadlock candidates (MT-LOCK-ORDER); the DOT render
  is ``python -m marian_tpu.analysis --format dot`` and the committed
  snapshot docs/lock_order.dot.

Known, documented limits (kept deliberately — each would cost far more
machinery than its findings are worth in this tree): calls through
locals bound to callables (``fn = self._foo; fn()``) are spawn edges,
not inline calls; ``lock.acquire()`` outside a ``with`` is not modeled;
lambdas contribute no body facts. The runtime lockdep witness exists
exactly to keep these blind spots honest: an observed acquisition edge
the static graph missed fails tier-1.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Source, ancestors, dotted_name, parent

LOCK_CTORS = {"threading.Lock": "lock", "threading.RLock": "rlock",
              "Lock": "lock", "RLock": "rlock"}
LOCKDEP_CTORS = {"make_lock": "lock", "make_rlock": "rlock"}


@dataclasses.dataclass
class LockDecl:
    qual: str                      # "Class.attr" or "pkg.mod._NAME"
    kind: str                      # "lock" | "rlock"
    rel: str
    lineno: int
    node: ast.AST
    lockdep_name: Optional[str] = None   # literal given to lockdep.make_*
    owner_class: Optional[str] = None
    attr: Optional[str] = None


@dataclasses.dataclass
class CallSite:
    node: ast.Call
    name: str                      # dotted source text of the callee
    targets: Tuple[str, ...]       # resolved FuncInfo quals (may be empty)
    held: frozenset                # lexically held lock quals at the site
    awaited: bool = False
    spawn: bool = False


@dataclasses.dataclass
class Acquire:
    lock: str
    node: ast.AST
    held: frozenset                # lexically held (excluding this lock)


class FuncInfo:
    __slots__ = ("qual", "node", "rel", "module", "cls", "declared_holds",
                 "acquires", "calls", "param_types", "nested", "display")

    def __init__(self, qual: str, node, rel: str, module: "ModuleInfo",
                 cls: Optional["ClassInfo"]):
        self.qual = qual
        self.node = node
        self.rel = rel
        self.module = module
        self.cls = cls
        self.declared_holds: Set[str] = set()
        self.acquires: List[Acquire] = []
        self.calls: List[CallSite] = []
        self.param_types: Dict[str, "ClassInfo"] = {}
        self.nested: Dict[str, "FuncInfo"] = {}
        # short human name for diagnostics: "Class.meth" or "func"
        self.display = qual.split("::", 1)[1] if "::" in qual else qual


class ClassInfo:
    __slots__ = ("name", "rel", "module", "node", "base_names", "bases",
                 "methods", "attr_types", "lock_attrs")

    def __init__(self, name: str, rel: str, module: "ModuleInfo", node):
        self.name = name
        self.rel = rel
        self.module = module
        self.node = node
        self.base_names: List[str] = []
        self.bases: List["ClassInfo"] = []
        self.methods: Dict[str, FuncInfo] = {}
        self.attr_types: Dict[str, "ClassInfo"] = {}
        self.lock_attrs: Dict[str, LockDecl] = {}

    def mro(self) -> List["ClassInfo"]:
        out, seen, stack = [], set(), [self]
        while stack:
            c = stack.pop(0)
            if id(c) in seen:
                continue
            seen.add(id(c))
            out.append(c)
            stack.extend(c.bases)
        return out

    def find_method(self, name: str) -> Optional[FuncInfo]:
        for c in self.mro():
            if name in c.methods:
                return c.methods[name]
        return None

    def find_lock(self, attr: str) -> Optional[LockDecl]:
        for c in self.mro():
            if attr in c.lock_attrs:
                return c.lock_attrs[attr]
        return None

    def find_attr_type(self, attr: str) -> Optional["ClassInfo"]:
        for c in self.mro():
            if attr in c.attr_types:
                return c.attr_types[attr]
        return None


class ModuleInfo:
    __slots__ = ("rel", "modname", "src", "classes", "functions",
                 "instances", "module_locks", "imports")

    def __init__(self, rel: str, modname: str, src: Source):
        self.rel = rel
        self.modname = modname
        self.src = src
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.instances: Dict[str, ClassInfo] = {}   # NAME = ClassName()
        self.module_locks: Dict[str, LockDecl] = {}
        # alias -> ("module", dotted) | ("symbol", dotted_module, name)
        self.imports: Dict[str, Tuple] = {}


# the witnesses' own plumbing (lockdep._WITNESS_LOCK, ownwit's and
# jitwit's _WITNESS_LOCK — deliberately unwitnessed, held only around
# their record-dict updates) is instrumentation, not part of the
# modeled lattice: keep its locks out of the graph and the committed
# docs/lock_order.dot
_INSTRUMENTATION_MODULES = frozenset({"marian_tpu.common.lockdep",
                                      "marian_tpu.common.ownwit",
                                      "marian_tpu.common.jitwit"})


def _modname(rel: str) -> str:
    parts = rel[:-3].split("/") if rel.endswith(".py") else rel.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclasses.dataclass
class LockEdge:
    src: str
    dst: str
    rel: str                       # file of the acquire site
    lineno: int
    func: str                      # display name of the acquiring function
    chain: str                     # example "A.m -> B.n" holder chain


class CallGraph:
    def __init__(self):
        self.modules: Dict[str, ModuleInfo] = {}       # by dotted name
        self.functions: Dict[str, FuncInfo] = {}       # by qual
        self.locks: Dict[str, LockDecl] = {}           # by lock qual
        # same qual declared by DIFFERENT classes (same class name in two
        # modules): the graph and the runtime witness would silently fuse
        # them into one node — MT-LOCK-NAME reports every extra declarant
        self.lock_collisions: Dict[str, List[LockDecl]] = {}
        self._entry_held: Optional[Dict[str, Set[str]]] = None
        self._origin: Dict[Tuple[str, str], Tuple[str, int]] = {}

    # -- construction -------------------------------------------------------
    @classmethod
    def build(cls, sources: Sequence[Source]) -> "CallGraph":
        g = cls()
        for src in sources:
            g._index_module(src)
        for mod in g.modules.values():
            g._resolve_bases(mod)
        # module-level instances first (they only need the class index),
        # then class attrs (which may reference other modules' instances,
        # e.g. `msm.REGISTRY`), then instances once more for any that
        # needed a return annotation resolved via class attrs
        for mod in g.modules.values():
            g._infer_module_instances(mod)
        for mod in g.modules.values():
            g._infer_class_attrs(mod)
        for mod in g.modules.values():
            g._infer_module_instances(mod)
        for fn in list(g.functions.values()):
            g._extract_facts(fn)
        g._propagate()
        return g

    def _index_module(self, src: Source) -> None:
        mod = ModuleInfo(src.rel, _modname(src.rel), src)
        self.modules[mod.modname] = mod
        for node in src.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._index_import(mod, node)
            elif isinstance(node, ast.ClassDef):
                self._index_class(mod, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FuncInfo(f"{mod.rel}::{node.name}", node, mod.rel,
                              mod, None)
                mod.functions[node.name] = fi
                self.functions[fi.qual] = fi
                self._index_nested(fi)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                kind = _lock_ctor_kind(node.value)
                if kind and mod.modname not in _INSTRUMENTATION_MODULES:
                    decl = LockDecl(qual=f"{mod.modname}.{name}", kind=kind,
                                    rel=mod.rel, lineno=node.lineno,
                                    node=node,
                                    lockdep_name=_lockdep_literal(node.value))
                    mod.module_locks[name] = decl
                    self._register_lock(decl)

    def _register_lock(self, decl: LockDecl) -> None:
        """Claim a lock identity. Module-level quals embed the module
        path and cannot collide; a class-attr qual (`Class.attr`) CAN —
        two same-named classes in different files would merge into one
        node in the order graph and the witness, turning independent
        locks into false cycles (or vacuously whitelisting real ones).
        First declaration wins; every later distinct one is recorded for
        MT-LOCK-NAME."""
        prev = self.locks.get(decl.qual)
        if prev is not None:
            if (prev.rel, prev.lineno) != (decl.rel, decl.lineno):
                self.lock_collisions.setdefault(
                    decl.qual, [prev]).append(decl)
            return
        self.locks[decl.qual] = decl

    def _index_import(self, mod: ModuleInfo, node) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".", 1)[0]
                target = alias.name if alias.asname else \
                    alias.name.split(".", 1)[0]
                mod.imports[name] = ("module", target)
            return
        # ImportFrom: resolve the (possibly relative) base package
        base = node.module or ""
        if node.level:
            pkg = mod.modname.split(".")
            # a module's package is its dotted name minus the leaf;
            # __init__ modules ARE their package
            is_pkg = mod.rel.endswith("__init__.py")
            up = node.level - (1 if is_pkg else 0)
            pkg_parts = pkg if up == 0 else pkg[:-up] if up <= len(pkg) \
                else []
            base = ".".join(pkg_parts + ([base] if base else []))
        for alias in node.names:
            name = alias.asname or alias.name
            dotted = f"{base}.{alias.name}" if base else alias.name
            # `from a.b import c` may bind module a.b.c or symbol c of a.b
            mod.imports[name] = ("from", base, alias.name, dotted)

    def _index_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        ci = ClassInfo(node.name, mod.rel, mod, node)
        for b in node.bases:
            d = dotted_name(b)
            if d:
                ci.base_names.append(d)
        mod.classes[node.name] = ci
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FuncInfo(f"{mod.rel}::{node.name}.{item.name}", item,
                              mod.rel, mod, ci)
                ci.methods[item.name] = fi
                self.functions[fi.qual] = fi
                self._index_nested(fi)

    def _index_nested(self, parent: FuncInfo) -> None:
        for item in ast.walk(parent.node):
            if item is parent.node:
                continue
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _enclosing_function(item) is parent.node:
                fi = FuncInfo(f"{parent.qual}.<{item.name}>", item,
                              parent.rel, parent.module, parent.cls)
                parent.nested[item.name] = fi
                self.functions[fi.qual] = fi
                self._index_nested(fi)

    # -- resolution ---------------------------------------------------------
    def _lookup_class(self, name: str, mod: ModuleInfo
                      ) -> Optional[ClassInfo]:
        """Resolve a dotted class name as seen from `mod`."""
        if not name:
            return None
        head, _, rest = name.partition(".")
        if not rest:
            if head in mod.classes:
                return mod.classes[head]
            imp = mod.imports.get(head)
            if imp and imp[0] == "from":
                _, base, leaf, _dotted = imp
                m = self.modules.get(base)
                if m and leaf in m.classes:
                    return m.classes[leaf]
            return None
        # module-qualified: reg.ModelRegistry, msm.Registry...
        m = self._lookup_module(head, mod)
        if m is not None:
            return self._lookup_class(rest, m) if "." in rest \
                else m.classes.get(rest)
        return None

    def _lookup_module(self, alias: str, mod: ModuleInfo
                       ) -> Optional[ModuleInfo]:
        imp = mod.imports.get(alias)
        if imp is None:
            return None
        if imp[0] == "module":
            return self.modules.get(imp[1])
        _, base, leaf, dotted = imp
        return self.modules.get(dotted)

    def _resolve_bases(self, mod: ModuleInfo) -> None:
        for ci in mod.classes.values():
            for bname in ci.base_names:
                b = self._lookup_class(bname, mod)
                if b is not None:
                    ci.bases.append(b)

    def _resolve_annotation(self, ann, mod: ModuleInfo
                            ) -> Optional[ClassInfo]:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.Subscript):
            # Optional[X] / Union[X, None] / "Optional[reg.ModelVersion]"
            sl = ann.slice
            elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
            for e in elts:
                c = self._resolve_annotation(e, mod)
                if c is not None:
                    return c
            return None
        d = dotted_name(ann)
        return self._lookup_class(d, mod) if d else None

    def _infer_module_instances(self, mod: ModuleInfo) -> None:
        for node in mod.src.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                t = self._expr_type(node.value, mod, None, {}, {})
                if t is not None:
                    mod.instances[node.targets[0].id] = t

    def _infer_class_attrs(self, mod: ModuleInfo) -> None:
        for ci in mod.classes.values():
            for meth in ci.methods.values():
                self._infer_attrs_in(ci, meth, mod)

    def _infer_attrs_in(self, ci: ClassInfo, meth: FuncInfo,
                        mod: ModuleInfo) -> None:
        """Walk one method in statement order, tracking local variable
        types as they bind (the metrics pattern is `r = registry or
        msm.REGISTRY; self.m_x = r.gauge(...)` — `r` must be typed
        before the attr assignment resolves)."""
        params = self._param_types(meth)
        local_types: Dict[str, ClassInfo] = {}

        def handle_assign(node) -> None:
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            rhs = node.value
            for t in targets:
                if isinstance(t, ast.Name) and rhs is not None:
                    ty = self._expr_type(rhs, mod, ci, params, local_types)
                    if ty is not None:
                        local_types[t.id] = ty
                    continue
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                kind = _lock_ctor_kind(rhs) if rhs is not None else None
                if kind and t.attr not in ci.lock_attrs:
                    decl = LockDecl(
                        qual=f"{ci.name}.{t.attr}", kind=kind,
                        rel=ci.rel, lineno=node.lineno, node=node,
                        lockdep_name=_lockdep_literal(rhs),
                        owner_class=ci.name, attr=t.attr)
                    ci.lock_attrs[t.attr] = decl
                    self._register_lock(decl)
                    continue
                ty = None
                if isinstance(node, ast.AnnAssign):
                    ty = self._resolve_annotation(node.annotation, mod)
                if ty is None and rhs is not None:
                    ty = self._expr_type(rhs, mod, ci, params, local_types)
                if ty is not None and t.attr not in ci.attr_types:
                    ci.attr_types[t.attr] = ty

        def visit(node) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.Assign, ast.AnnAssign)):
                    handle_assign(child)
                visit(child)

        visit(meth.node)

    def _param_types(self, fn: FuncInfo) -> Dict[str, ClassInfo]:
        if fn.param_types:
            return fn.param_types
        args = fn.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            t = self._resolve_annotation(a.annotation, fn.module)
            if t is not None:
                fn.param_types[a.arg] = t
        return fn.param_types

    def _expr_type(self, expr, mod: ModuleInfo, cls: Optional[ClassInfo],
                   params: Dict[str, ClassInfo],
                   local_types: Dict[str, ClassInfo],
                   depth: int = 0) -> Optional[ClassInfo]:
        if depth > 6 or expr is None:
            return None
        if isinstance(expr, ast.IfExp):
            return (self._expr_type(expr.body, mod, cls, params,
                                    local_types, depth + 1)
                    or self._expr_type(expr.orelse, mod, cls, params,
                                       local_types, depth + 1))
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                t = self._expr_type(v, mod, cls, params, local_types,
                                    depth + 1)
                if t is not None:
                    return t
            return None
        if isinstance(expr, ast.Name):
            if expr.id == "self" and cls is not None:
                return cls
            return (local_types.get(expr.id) or params.get(expr.id)
                    or mod.instances.get(expr.id)
                    or self._imported_instance(expr.id, mod))
        if isinstance(expr, ast.Attribute):
            base = self._expr_type(expr.value, mod, cls, params,
                                   local_types, depth + 1)
            if base is not None:
                return base.find_attr_type(expr.attr)
            # module attribute: msm.REGISTRY
            if isinstance(expr.value, ast.Name):
                m = self._lookup_module(expr.value.id, mod)
                if m is not None:
                    return m.instances.get(expr.attr)
            return None
        if isinstance(expr, ast.Call):
            callee = dotted_name(expr.func)
            if callee:
                c = self._lookup_class(callee, mod)
                if c is not None:
                    return c               # constructor call
            targets = self._resolve_callable(expr.func, mod, cls, params,
                                             local_types, depth + 1)
            for q in targets:
                f = self.functions.get(q)
                if f is not None:
                    ret = getattr(f.node, "returns", None)
                    t = self._resolve_annotation(ret, f.module)
                    if t is not None:
                        return t
            return None
        return None

    def _imported_instance(self, name: str, mod: ModuleInfo
                           ) -> Optional[ClassInfo]:
        imp = mod.imports.get(name)
        if imp and imp[0] == "from":
            _, base, leaf, dotted = imp
            m = self.modules.get(base)
            if m is not None:
                return m.instances.get(leaf)
        return None

    def _resolve_callable(self, func, mod: ModuleInfo,
                          cls: Optional[ClassInfo],
                          params: Dict[str, ClassInfo],
                          local_types: Dict[str, ClassInfo],
                          depth: int = 0,
                          owner: Optional[FuncInfo] = None
                          ) -> Tuple[str, ...]:
        """Resolve a callee expression to FuncInfo quals (usually 0-1)."""
        if depth > 6:
            return ()
        if isinstance(func, ast.Name):
            if owner is not None and func.id in owner.nested:
                return (owner.nested[func.id].qual,)
            if func.id in mod.functions:
                return (mod.functions[func.id].qual,)
            c = self._lookup_class(func.id, mod)
            if c is not None:
                init = c.find_method("__init__")
                return (init.qual,) if init else ()
            imp = mod.imports.get(func.id)
            if imp and imp[0] == "from":
                _, base, leaf, dotted = imp
                f = self._module_function(base, leaf)
                if f is not None:
                    return (f.qual,)
            return ()
        if isinstance(func, ast.Attribute):
            # super().__init__ / super().m
            if isinstance(func.value, ast.Call) \
                    and dotted_name(func.value.func) == "super" \
                    and cls is not None and cls.bases:
                m = cls.bases[0].find_method(func.attr)
                return (m.qual,) if m else ()
            base_t = self._expr_type(func.value, mod, cls, params,
                                     local_types, depth + 1)
            if base_t is not None:
                m = base_t.find_method(func.attr)
                return (m.qual,) if m else ()
            if isinstance(func.value, ast.Name):
                m = self._lookup_module(func.value.id, mod)
                if m is not None:
                    f = self._module_function(m.modname, func.attr)
                    if f is not None:
                        return (f.qual,)
                    c = m.classes.get(func.attr)
                    if c is not None:
                        init = c.find_method("__init__")
                        return (init.qual,) if init else ()
            return ()
        return ()

    def _module_function(self, modname: str, name: str,
                         depth: int = 0) -> Optional[FuncInfo]:
        """Function ``name`` as exposed by module ``modname``, following
        re-export chains: a package facade (``marian_tpu/obs/__init__.py``
        doing ``from .trace import event``) exposes functions it never
        defines, and calls through it (``obs.event(...)``) must still
        resolve — the lock-order edges those calls create are exactly
        what the lockdep witness cross-checks against this graph."""
        if depth > 4:
            return None
        m = self.modules.get(modname)
        if m is None:
            return None
        if name in m.functions:
            return m.functions[name]
        imp = m.imports.get(name)
        if imp and imp[0] == "from":
            _, base, leaf, _dotted = imp
            return self._module_function(base, leaf, depth + 1)
        return None

    # -- per-function fact extraction --------------------------------------
    def _declared_holds(self, fn: FuncInfo) -> Set[str]:
        from .rules.guarded_by import HOLDS_RE as holds_re
        held: Set[str] = set()
        src = fn.module.src
        for line in (fn.node.lineno, fn.node.lineno - 1):
            m = holds_re.search(src.comments.get(line, ""))
            if m and fn.cls is not None:
                decl = fn.cls.find_lock(m.group(1))
                if decl is not None:
                    held.add(decl.qual)
        return held

    def _lock_of_with_item(self, expr, fn: FuncInfo) -> Optional[str]:
        d = dotted_name(expr)
        if not d:
            return None
        mod, cls = fn.module, fn.cls
        if d.startswith("self.") and cls is not None:
            decl = cls.find_lock(d[len("self."):])
            return decl.qual if decl else None
        head, _, rest = d.partition(".")
        if not rest:
            if head in mod.module_locks:
                return mod.module_locks[head].qual
            imp = mod.imports.get(head)
            if imp and imp[0] == "from":
                _, base, leaf, dotted = imp
                m = self.modules.get(base)
                if m and leaf in m.module_locks:
                    return m.module_locks[leaf].qual
            return None
        # obj.lockattr where obj's type is known (e.g. _STATE.lock), or
        # mod.NAME for an imported module's lock
        base_t = self._expr_type(ast.Name(id=head), mod, cls,
                                 self._param_types(fn), {})
        if base_t is not None and "." not in rest:
            decl = base_t.find_lock(rest)
            return decl.qual if decl else None
        m = self._lookup_module(head, mod)
        if m is not None and "." not in rest and rest in m.module_locks:
            return m.module_locks[rest].qual
        return None

    def _extract_facts(self, fn: FuncInfo) -> None:
        fn.declared_holds = self._declared_holds(fn)
        params = self._param_types(fn)
        mod, cls = fn.module, fn.cls
        local_types: Dict[str, ClassInfo] = {}

        def visit(node, held: frozenset):
            for child in ast.iter_child_nodes(node):
                dispatch(child, held)

        def dispatch(child, held: frozenset):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return              # nested defs are their own FuncInfo
            if isinstance(child, ast.Lambda):
                return
            if isinstance(child, (ast.With, ast.AsyncWith)):
                inner = set(held)
                for item in child.items:
                    lk = self._lock_of_with_item(item.context_expr, fn)
                    if lk is not None:
                        # recorded even when already held: lock_edges
                        # skips re-acquires (edge-free), but
                        # self_deadlocks() needs the site to flag a
                        # plain-Lock re-acquire
                        fn.acquires.append(
                            Acquire(lk, item.context_expr,
                                    frozenset(inner)))
                        inner.add(lk)
                    else:
                        # a non-lock context expression evaluates BEFORE
                        # later items' locks are acquired — only the
                        # locks folded in so far are held around it
                        # (`with open(p) as f, self._lock:` does not
                        # open the file under the lock)
                        dispatch(item.context_expr, frozenset(inner))
                for stmt in child.body:
                    dispatch(stmt, frozenset(inner))
                return
            if isinstance(child, ast.Assign) \
                    and len(child.targets) == 1 \
                    and isinstance(child.targets[0], ast.Name):
                t = self._expr_type(child.value, mod, cls, params,
                                    local_types)
                if t is not None:
                    local_types[child.targets[0].id] = t
            if isinstance(child, ast.Call):
                self._record_call(fn, child, held, params, local_types)
            visit(child, held)

        visit(fn.node, frozenset())

    def _record_call(self, fn: FuncInfo, call: ast.Call, held: frozenset,
                     params, local_types) -> None:
        name = dotted_name(call.func) or ""
        targets = self._resolve_callable(call.func, fn.module, fn.cls,
                                         params, local_types, owner=fn)
        awaited = isinstance(parent(call), ast.Await)
        fn.calls.append(CallSite(node=call, name=name, targets=targets,
                                 held=held, awaited=awaited))
        # callable references passed as arguments (Thread targets, timer
        # callbacks, executor submissions, gauge sample functions...)
        # become spawn edges: reachable, but the caller's held locks do
        # not flow in — the target runs on another thread or later
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, (ast.Name, ast.Attribute)):
                spawned = self._resolve_callable(
                    arg, fn.module, fn.cls, params, local_types, owner=fn)
                if spawned:
                    fn.calls.append(CallSite(
                        node=call, name=dotted_name(arg) or "",
                        targets=spawned, held=held, spawn=True))

    # -- interprocedural held-set propagation -------------------------------
    def _propagate(self) -> None:
        H: Dict[str, Set[str]] = {q: set(f.declared_holds)
                                  for q, f in self.functions.items()}
        origin: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for q, f in self.functions.items():
            for lk in f.declared_holds:
                origin.setdefault((q, lk), (q, f.node.lineno))
        changed = True
        while changed:
            changed = False
            for f in self.functions.values():
                base = H[f.qual]
                for site in f.calls:
                    if site.spawn:
                        continue
                    contributed = base | set(site.held)
                    if not contributed:
                        continue
                    for t in site.targets:
                        tH = H.get(t)
                        if tH is None:
                            continue
                        new = contributed - tH
                        if new:
                            tH.update(new)
                            for lk in new:
                                origin.setdefault(
                                    (t, lk), (f.qual, site.node.lineno))
                            changed = True
        self._entry_held = H
        self._origin = origin

    def entry_held(self, qual: str) -> Set[str]:
        assert self._entry_held is not None
        return self._entry_held.get(qual, set())

    def holder_chain(self, qual: str, lock: str, limit: int = 8) -> str:
        """Example call chain explaining why `lock` may be held at entry
        of `qual` — "A.m -> B.n" (empty when held lexically)."""
        parts: List[str] = []
        cur = qual
        seen = set()
        while limit > 0 and (cur, lock) in self._origin:
            caller, _line = self._origin[(cur, lock)]
            if caller == cur or caller in seen:
                break
            seen.add(caller)
            f = self.functions.get(caller)
            parts.append(f.display if f else caller)
            cur = caller
            limit -= 1
        parts.reverse()
        return " -> ".join(parts)

    # -- the lock-order graph -----------------------------------------------
    def lock_edges(self) -> List[LockEdge]:
        edges: Dict[Tuple[str, str], LockEdge] = {}
        for f in self.functions.values():
            entry = self.entry_held(f.qual)
            for acq in f.acquires:
                held = entry | set(acq.held)
                if acq.lock in held:
                    # reentrant re-acquisition (RLock re-entry): cannot
                    # block, so it orders nothing — mirror the witness
                    continue
                for h in held:
                    if (h, acq.lock) in edges:
                        continue
                    chain = ("" if h in acq.held
                             else self.holder_chain(f.qual, h))
                    edges[(h, acq.lock)] = LockEdge(
                        src=h, dst=acq.lock, rel=f.rel,
                        lineno=acq.node.lineno, func=f.display,
                        chain=chain)
        return sorted(edges.values(), key=lambda e: (e.src, e.dst))

    def self_deadlocks(self) -> List[LockEdge]:
        """Definite self-deadlocks: re-acquiring a NON-reentrant lock
        that may already be held. lock_edges treats every re-acquire as
        edge-free (safe for the RLock re-entry pattern); for a plain
        Lock the inner acquire can never succeed — the most common
        Python self-deadlock. Reported as src==dst pseudo-edges."""
        out: Dict[Tuple[str, str], LockEdge] = {}
        for f in self.functions.values():
            entry = self.entry_held(f.qual)
            for acq in f.acquires:
                if acq.lock not in (entry | set(acq.held)):
                    continue
                decl = self.locks.get(acq.lock)
                if decl is None or decl.kind != "lock":
                    continue
                key = (acq.lock, f.qual)
                if key in out:
                    continue
                chain = ("" if acq.lock in acq.held
                         else self.holder_chain(f.qual, acq.lock))
                out[key] = LockEdge(src=acq.lock, dst=acq.lock, rel=f.rel,
                                    lineno=acq.node.lineno,
                                    func=f.display, chain=chain)
        return sorted(out.values(),
                      key=lambda e: (e.src, e.rel, e.lineno))

    def lock_cycles(self) -> List[List[str]]:
        """Elementary cycles in the lock-order graph (each reported once,
        rotated to start at its smallest node)."""
        adj: Dict[str, List[str]] = {}
        for e in self.lock_edges():
            adj.setdefault(e.src, []).append(e.dst)
        return elementary_cycles(adj)

    def to_dot(self) -> str:
        """The lock-order graph in Graphviz DOT (deterministic order) —
        `python -m marian_tpu.analysis --format dot`; the committed
        snapshot lives at docs/lock_order.dot."""
        edges = self.lock_edges()
        connected = {e.src for e in edges} | {e.dst for e in edges}
        lines = [
            "// mtlint lock-order graph — acquiring B while A is held",
            "// draws A -> B. Regenerate:",
            "//   python -m marian_tpu.analysis --format dot "
            "> docs/lock_order.dot",
            "digraph mtlint_lock_order {",
            '  rankdir=LR;',
            '  node [shape=box, fontname="monospace", fontsize=10];',
        ]
        for q in sorted(self.locks):
            decl = self.locks[q]
            style = ', style=bold' if decl.kind == "rlock" else ""
            free = "" if q in connected else ', color=gray'
            lines.append(f'  "{q}" [label="{q}\\n({decl.kind})"'
                         f'{style}{free}];')
        for e in edges:
            lines.append(f'  "{e.src}" -> "{e.dst}" '
                         f'[label="{e.rel.rsplit("/", 1)[-1]}:{e.lineno}"];')
        lines.append("}")
        return "\n".join(lines) + "\n"


def _enclosing_function(node) -> Optional[ast.AST]:
    for p in ancestors(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p
    return None


def _lock_ctor_kind(expr) -> Optional[str]:
    """'lock'/'rlock' when `expr` constructs a lock — threading.Lock(),
    threading.RLock(), or lockdep.make_lock/make_rlock("name")."""
    if not isinstance(expr, ast.Call):
        return None
    d = dotted_name(expr.func) or ""
    leaf = d.rsplit(".", 1)[-1]
    if d in LOCK_CTORS:
        return LOCK_CTORS[d]
    if leaf in LOCKDEP_CTORS and ("lockdep" in d or leaf == d):
        return LOCKDEP_CTORS[leaf]
    return None


def _lockdep_literal(expr) -> Optional[str]:
    if isinstance(expr, ast.Call) and expr.args \
            and isinstance(expr.args[0], ast.Constant) \
            and isinstance(expr.args[0].value, str):
        d = dotted_name(expr.func) or ""
        if d.rsplit(".", 1)[-1] in LOCKDEP_CTORS:
            return expr.args[0].value
    return None


def elementary_cycles(adj: Dict[str, List[str]]) -> List[List[str]]:
    """Elementary cycles of a directed graph, each reported once and
    rotated to start at its smallest node. Shared by the static
    lock-order graph (:meth:`CallGraph.lock_cycles`) and the runtime
    witness (common/lockdep.py `observed_cycles`), so the two verdicts
    can never diverge on what counts as a cycle."""
    cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str],
            on_path: Set[str]) -> None:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start:
                i = path.index(min(path))
                cycles.add(tuple(path[i:] + path[:i]))
            elif nxt not in on_path and nxt > start:
                # only explore nodes >= start: each cycle is found
                # from its smallest node exactly once
                path.append(nxt)
                on_path.add(nxt)
                dfs(start, nxt, path, on_path)
                on_path.discard(nxt)
                path.pop()

    for n in sorted(adj):
        dfs(n, n, [n], {n})
    return [list(c) for c in sorted(cycles)]


# ---------------------------------------------------------------------------
# memoized build (the three lock rule families + --format dot + the
# runtime witness all want the same graph for the same source set)
# ---------------------------------------------------------------------------

_CACHE: Dict[Tuple, CallGraph] = {}


def build_cached(sources: Sequence[Source]) -> CallGraph:
    key = tuple(sorted((s.rel, hash(s.text)) for s in sources))
    g = _CACHE.get(key)
    if g is None:
        _CACHE.clear()            # keep at most one graph alive
        g = _CACHE[key] = CallGraph.build(sources)
    return g


def static_lock_graph(root) -> Tuple[Set[str], Set[Tuple[str, str]]]:
    """(lock nodes, acquisition-order edges) for the repo at `root` —
    what common/lockdep.py's runtime witness cross-checks observed
    acquisition orders against."""
    from pathlib import Path

    from .core import Config, collect_sources_cached
    root = Path(root)
    config = Config.load(root)
    sources = collect_sources_cached([root / "marian_tpu"], config)
    g = build_cached(sources)
    return (set(g.locks), {(e.src, e.dst) for e in g.lock_edges()})
