"""Resource-ownership model for mtlint (ISSUE 15 tentpole).

PR 16 made page ownership the correctness substrate of the serving
plane: refcounted ``KVPool`` claims flow through ``claim``/
``claim_extra``/``share``/``retable``/``transfer``/``release`` across
the beam engine, the prefix cache, quiesce, and brownout eviction — and
until now the only line of defense was the RUNTIME auditor, which
catches a leak after it happened, on a path traffic happened to
exercise. This module is the static half of the same discipline the
lock analysis already follows (PR 11): enumerate the dynamic behavior
statically, and keep the enumeration honest with a runtime witness
(common/ownwit.py) that fails tier-1 when reality exercises a pairing
the model never derived.

Three things live here, shared by the rule family
(rules/ownership.py), the CLI's ``--format ownership-dot`` render, and
the witness cross-check:

- **The verb registry** (:data:`REGISTRY`): which method calls acquire,
  release, or transfer which RESOURCE CLASS, and where the owner handle
  sits in the argument list. Covered classes: ``kv-pages`` (KVPool
  claims + the prefix cache's holdings — ``adopt`` transfers a row's
  references into the cache), ``span`` (Tracer.start_span/end — the
  static lifetime rules for spans stay with the MT-SPAN family; the
  sites are registered here so the ownership graph knows them),
  ``executor`` (ThreadPoolExecutor construction → ``shutdown``),
  ``worker`` (non-daemon ``threading.Thread`` → ``join``; daemon
  threads are released by process exit and are not a resource),
  ``engine`` (PagedDecodeEngine/PagedBeamEngine construction — an
  engine owns a device page pool; whoever holds one must hand it to the
  lifecycle plane or quiesce it away), and ``file`` (``open`` outside a
  ``with`` → ``close`` — the faultpoint-armed handle discipline).

- **The annotation vocabulary**, mirroring ``# guarded-by:``:
  ``# owns: caller`` on a ``def`` line blesses a function that hands a
  still-held resource to its caller (the ``_claim_pages`` wrapper
  shape); ``# owns: callee`` blesses a function that releases or
  transfers a handle it received from its caller (the ``_evict`` /
  ``adopt`` shape); ``# mtlint: transfers`` on a statement blesses an
  owned handle moving into a longer-lived structure (the engine's
  claims-table holdings). MT-OWN-TRANSFER / MT-OWN-ESCAPE fire at
  unannotated boundaries.

- **The ownership graph**: per resource class, every acquire/release/
  transfer SITE (identified ``<rel>::<function>`` — exactly what a
  runtime stack frame resolves to), with an edge acquire→release for
  every pair the model considers PAIRABLE: the two sites share a common
  ancestor in the project call graph (callgraph.py, spawn edges
  included — a brownout-thread eviction legitimately releases what the
  device worker acquired). The runtime witness asserts observed
  pairings ⊆ this graph; the committed snapshot is
  ``docs/ownership.dot`` (freshness-tested) next to ``lock_order.dot``.

Documented limits (deliberate, witness-kept-honest): calls through
locals bound to callables pair only via spawn edges; owner handles
built from expressions (``self._owner(key, slot)``) are modeled as
sites but not tracked as per-function obligations; exception edges are
modeled for registered acquire verbs (which document ``PoolExhausted``)
and explicit ``raise`` — an arbitrary call that throws mid-hold is the
auditor's and the witness's job to catch.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Source, dotted_name

# -- annotation vocabulary ---------------------------------------------------

OWNS_RE = re.compile(r"owns:\s*(caller|callee)\b")
TRANSFERS_TAG = "transfers"


def owns_annotation(src: Source, fn: ast.AST) -> Optional[str]:
    """'caller' / 'callee' from an ``# owns:`` comment on the def line
    or the line above it (mirrors ``# mtlint: holds``)."""
    for line in (fn.lineno, fn.lineno - 1):
        m = OWNS_RE.search(src.comments.get(line, ""))
        if m:
            return m.group(1)
    return None


def line_transfers(src: Source, lineno: int) -> bool:
    """``# mtlint: transfers [-- reason]`` on the statement's line: the
    owned handle deliberately moves into a longer-lived structure."""
    comment = src.comments.get(lineno, "")
    if not comment.startswith("mtlint:"):
        return False
    body = comment[len("mtlint:"):].split("--", 1)[0].strip()
    return body == TRANSFERS_TAG or body.startswith(TRANSFERS_TAG + " ")


# -- the verb registry -------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Verb:
    cls: str                 # resource class
    kind: str                # "acquire" | "release" | "transfer"
    owner_arg: Optional[int]  # owner-handle arg index; None = the call
    #                          RESULT is the handle (binding style)
    receivers: Tuple[str, ...] = ()   # acceptable receiver leaf names
    #                                   (empty = any receiver / ctor)
    may_raise: bool = False  # documented raiser (PoolExhausted): the
    #                          exception edge the leak rule models


# KVPool's verb surface (ops/pallas/kv_pool.py). The receiver filter is
# the attribute component directly before the verb — `self.pool.claim`,
# `pool.release`, `engine.pool.share` all match; `lock.release` or
# `str.join` never do.
_POOL_RECV = ("pool",)
_PREFIX_RECV = ("prefix", "cache")
_TRACER_RECV = ("TRACER", "tracer", "obs")

REGISTRY: Dict[str, Tuple[Verb, ...]] = {
    "claim": (Verb("kv-pages", "acquire", 0, _POOL_RECV, may_raise=True),),
    "claim_extra": (Verb("kv-pages", "acquire", 0, _POOL_RECV,
                         may_raise=True),),
    "share": (Verb("kv-pages", "acquire", 0, _POOL_RECV, may_raise=True),),
    "release": (Verb("kv-pages", "release", 0, _POOL_RECV),),
    "retable": (Verb("kv-pages", "transfer", 0, _POOL_RECV),),
    "transfer": (Verb("kv-pages", "transfer", 0, _POOL_RECV),),
    # PrefixCache adoption: a finished row's references change hands
    # into the cache (owner handle = the row key, arg 2 of
    # adopt(pool, key, row_key, tokens, text))
    "adopt": (Verb("kv-pages", "transfer", 2, _PREFIX_RECV),),
    # span begin/end — the MT-SPAN family owns the per-function
    # lifetime rules; these entries make the sites part of the
    # ownership site registry (and the witness's span class, were it
    # armed) without double-reporting
    "start_span": (Verb("span", "acquire", None, _TRACER_RECV),),
    "end": (Verb("span", "release", 0, _TRACER_RECV),),
    # executors / workers / engines / files: binding-style handles
    "ThreadPoolExecutor": (Verb("executor", "acquire", None),),
    "shutdown": (Verb("executor", "release", None),),
    "Thread": (Verb("worker", "acquire", None, ("threading",)),),
    "join": (Verb("worker", "release", None),),
    "PagedDecodeEngine": (Verb("engine", "acquire", None),),
    "PagedBeamEngine": (Verb("engine", "acquire", None),),
    "open": (Verb("file", "acquire", None),),
    "close": (Verb("file", "release", None),),
}

# classes whose per-function obligations the MT-OWN rules track (span
# stays with MT-SPAN; worker/file/executor/engine are binding-style)
OWNER_KEYED_CLASSES = frozenset({"kv-pages"})
BINDING_CLASSES = frozenset({"executor", "worker", "engine", "file"})

# classes rendered into the ownership graph / checked by the runtime
# witness (common/ownwit.py instruments exactly these)
GRAPH_CLASSES = ("kv-pages",)

EXTERNAL_SITE = "<external>"


def _tail(name: Optional[str]) -> str:
    return (name or "").rsplit(".", 1)[-1]


def match_verb(call: ast.Call) -> Optional[Verb]:
    """The registry entry a call site matches, or None."""
    name = dotted_name(call.func)
    if not name:
        return None
    parts = name.split(".")
    verbs = REGISTRY.get(parts[-1])
    if not verbs:
        return None
    recv = parts[-2] if len(parts) >= 2 else ""
    for v in verbs:
        if v.receivers and recv not in v.receivers:
            continue
        if parts[-1] == "Thread" and _thread_is_daemon(call):
            # daemon threads are released by process exit — not a
            # resource; non-daemon threads must be joined
            return None
        return v
    return None


def _thread_is_daemon(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def owner_expr(call: ast.Call, verb: Verb) -> Optional[ast.AST]:
    """The owner-handle argument expression of an owner-keyed verb call
    (positional or the conventional keyword), or None."""
    if verb.owner_arg is None:
        return None
    if len(call.args) > verb.owner_arg:
        return call.args[verb.owner_arg]
    names_by_idx = {0: ("owner", "src_owner", "span"), 2: ("row_key",)}
    for kw in call.keywords:
        if kw.arg in names_by_idx.get(verb.owner_arg, ()):
            return kw.value
    return None


# -- static site extraction --------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SiteInfo:
    cls: str
    kind: str                 # acquire | release | transfer
    site: str                 # "<rel>::<function-co_name>"
    rel: str
    lineno: int
    func_qual: Optional[str]  # callgraph qual of the enclosing function


def _enclosing_funcname(node: ast.AST) -> str:
    from .core import ancestors
    for p in ancestors(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p.name
        if isinstance(p, ast.Lambda):
            return "<lambda>"
    return "<module>"


def collect_sites(sources: Sequence[Source],
                  classes: Optional[Sequence[str]] = None
                  ) -> List[SiteInfo]:
    """Every registered verb call site in ``sources`` (deterministic
    order). Site identity ``<rel>::<function name>`` — the same pair a
    runtime frame's ``(co_filename, co_name)`` resolves to, which is
    how the ownership witness matches what it observed back to this
    model."""
    out: List[SiteInfo] = []
    want = set(classes) if classes is not None else None
    for src in sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            v = match_verb(node)
            if v is None or (want is not None and v.cls not in want):
                continue
            fname = _enclosing_funcname(node)
            out.append(SiteInfo(
                cls=v.cls, kind=v.kind,
                site=f"{src.rel}::{fname}", rel=src.rel,
                lineno=node.lineno, func_qual=None))
    out.sort(key=lambda s: (s.cls, s.rel, s.lineno, s.kind))
    return out


# -- the ownership graph -----------------------------------------------------

class OwnershipGraph:
    """Per resource class: acquire/release/transfer sites + the
    pairable acquire→release edges (see module docstring)."""

    def __init__(self):
        # cls -> site -> set of kinds seen at that site
        self.sites: Dict[str, Dict[str, Set[str]]] = {}
        # cls -> {(acquire_site, release_site)}
        self.pairs: Dict[str, Set[Tuple[str, str]]] = {}

    def acquire_sites(self, cls: str) -> Set[str]:
        return {s for s, kinds in self.sites.get(cls, {}).items()
                if kinds & {"acquire", "transfer"}}

    def release_sites(self, cls: str) -> Set[str]:
        return {s for s, kinds in self.sites.get(cls, {}).items()
                if kinds & {"release", "transfer"}}

    @classmethod
    def build(cls, sources: Sequence[Source]) -> "OwnershipGraph":
        from . import callgraph as cg
        g = cls()
        graph = cg.build_cached(sources)
        sites = collect_sites(sources, classes=GRAPH_CLASSES)
        # attach each site to its callgraph function (by rel + name;
        # nested defs and methods both match on the leaf name, which is
        # what a runtime frame's co_name carries)
        by_key: Dict[Tuple[str, str], List[str]] = {}
        for q, f in graph.functions.items():
            by_key.setdefault((f.rel, f.node.name), []).append(q)
        # override dispatch: a call the type inference resolves to
        # Base.m may run Sub.m at runtime (the beam engine overrides
        # the greedy engine's _try_claim/_evict and is driven through
        # the inherited admit_and_step) — every resolved target also
        # reaches its subclass overrides
        overrides: Dict[str, List[str]] = {}
        for mod in graph.modules.values():
            for ci in mod.classes.values():
                for base in ci.mro()[1:]:
                    for name, meth in ci.methods.items():
                        if name in base.methods:
                            overrides.setdefault(
                                base.methods[name].qual,
                                []).append(meth.qual)
        # forward adjacency (spawn edges included: a watcher/brownout
        # thread legitimately releases what the worker acquired)
        adj: Dict[str, Set[str]] = {}
        for q, f in graph.functions.items():
            outs = adj.setdefault(q, set())
            for site in f.calls:
                for t in site.targets:
                    outs.add(t)
                    outs.update(overrides.get(t, ()))
        rev: Dict[str, Set[str]] = {}
        for q, outs in adj.items():
            for t in outs:
                rev.setdefault(t, set()).add(q)

        def ancestors_of(quals: List[str]) -> frozenset:
            seen: Set[str] = set(quals)
            stack = list(quals)
            while stack:
                cur = stack.pop()
                for p in rev.get(cur, ()):
                    if p not in seen:
                        seen.add(p)
                        stack.append(p)
            return frozenset(seen)

        anc_cache: Dict[Tuple[str, str], frozenset] = {}
        site_anc: Dict[str, frozenset] = {}
        for s in sites:
            g.sites.setdefault(s.cls, {}).setdefault(s.site,
                                                     set()).add(s.kind)
            rel, _, fname = s.site.partition("::")
            key = (rel, fname)
            if key not in anc_cache:
                anc_cache[key] = ancestors_of(by_key.get(key, []))
            # a site is always its own ancestor scope, even when the
            # callgraph never indexed its function (module level)
            site_anc[s.site] = anc_cache[key] | {s.site}
        for rcls in g.sites:
            acq = sorted(g.acquire_sites(rcls))
            rel_sites = sorted(g.release_sites(rcls))
            pairs = g.pairs.setdefault(rcls, set())
            for a in acq:
                for r in rel_sites:
                    if a == r or (site_anc[a] & site_anc[r]):
                        pairs.add((a, r))
        return g

    def to_dot(self) -> str:
        """Graphviz render — the committed snapshot is
        ``docs/ownership.dot`` (freshness-tested like lock_order.dot).
        Regenerate:
        ``python -m marian_tpu.analysis --format ownership-dot``."""
        lines = [
            "// mtlint ownership graph — for each resource class, every",
            "// acquire/release/transfer site, with an edge A -> R for",
            "// every pairing the static model derives (the runtime",
            "// ownership witness asserts observed pairings are a subset).",
            "// Regenerate:",
            "//   python -m marian_tpu.analysis --format ownership-dot "
            "> docs/ownership.dot",
            "digraph mtlint_ownership {",
            "  rankdir=LR;",
            '  node [shape=box, fontname="monospace", fontsize=10];',
        ]
        for rcls in sorted(self.sites):
            for site in sorted(self.sites[rcls]):
                kinds = "+".join(sorted(self.sites[rcls][site]))
                style = ""
                if "transfer" in kinds:
                    style = ", style=bold"
                elif kinds == "release":
                    style = ", color=gray40"
                lines.append(f'  "{rcls}:{site}" '
                             f'[label="{site}\\n({rcls}: {kinds})"{style}];')
            for a, r in sorted(self.pairs.get(rcls, ())):
                lines.append(f'  "{rcls}:{a}" -> "{rcls}:{r}";')
        lines.append("}")
        return "\n".join(lines) + "\n"


_GRAPH_CACHE: Dict[tuple, OwnershipGraph] = {}


def _graph_cached(sources) -> OwnershipGraph:
    # same one-entry content-keyed policy as callgraph.build_cached:
    # the witnesses re-check at every module teardown over unchanged
    # sources, so the rebuild would be pure repeated work
    key = tuple(sorted((s.rel, hash(s.text)) for s in sources))
    g = _GRAPH_CACHE.get(key)
    if g is None:
        _GRAPH_CACHE.clear()
        g = _GRAPH_CACHE[key] = OwnershipGraph.build(sources)
    return g


def static_ownership_graph(root) -> OwnershipGraph:
    """The ownership graph for the repo at ``root`` — what the runtime
    witness (common/ownwit.py) cross-checks observed (acquire-site →
    release-site) pairings against. Stdlib-only, never imports the
    analyzed code."""
    from pathlib import Path

    from .core import Config, collect_sources_cached
    root = Path(root)
    config = Config.load(root)
    sources = collect_sources_cached([root / "marian_tpu"], config)
    return _graph_cached(sources)
