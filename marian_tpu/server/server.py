"""marian-server: translation service on a WebSocket port (reference:
src/command/marian_server.cpp + vendored simple-websocket-server).

Protocol kept Marian-compatible: client sends newline-joined source
sentences as a text frame, server replies with newline-joined translations.
Uses the `websockets` package (gated — a clear error if unavailable).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..common import logging as log

try:
    import websockets
    HAVE_WS = True
except ImportError:  # pragma: no cover
    HAVE_WS = False


class TranslationService:
    """Preloaded graphs + jitted search shared across requests (reference:
    TranslationService in marian_server.cpp)."""

    def __init__(self, options):
        from ..translator.translator import Translate
        self.translator = Translate(options)

    def translate(self, text: str) -> str:
        lines = text.split("\n")
        import io as _io
        buf = _io.StringIO()
        self.translator.run(lines=lines, stream=buf)
        return buf.getvalue().rstrip("\n")


async def _serve(options) -> None:
    service = TranslationService(options)
    port = int(options.get("port", 8080))

    async def handler(ws):
        async for message in ws:
            try:
                reply = await asyncio.get_event_loop().run_in_executor(
                    None, service.translate, message)
            except Exception as e:  # keep the server alive on bad input
                log.error("translation error: {}", e)
                reply = ""
            await ws.send(reply)

    log.info("Server is listening on port {}", port)
    async with websockets.serve(handler, "0.0.0.0", port):
        await asyncio.Future()


def serve_main(options) -> None:
    if not HAVE_WS:
        raise RuntimeError(
            "marian-server needs the 'websockets' package (not installed)")
    asyncio.run(_serve(options))
