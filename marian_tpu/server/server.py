"""marian-server: translation service (reference: src/command/marian_server.cpp
+ vendored simple-websocket-server), fronted by the production serving
subsystem (marian_tpu/serving/ — ISSUE 1).

Protocol kept Marian-compatible: client sends newline-joined source
sentences as a text frame, server replies with newline-joined translations.
Transports:

- WebSocket (the Marian protocol) via the ``websockets`` package, gated —
  when unavailable the server falls back to
- a dependency-free length-prefixed TCP framing (``MTPU <nbytes>\\n`` +
  UTF-8 payload, replies framed the same way) that ``scripts/loadgen.py``
  speaks. Both transports share one ServingApp, so admission, scheduling,
  and metrics behave identically.

Beyond the reference (which serves each connection on its own thread
against per-thread graphs): ALL requests flow through ONE continuous
token-budget batching scheduler (serving/scheduler.py) that packs
sentences from concurrent clients into bucketed static-shape device
batches, behind bounded-queue admission control (serving/admission.py),
with Prometheus metrics + health endpoints (serving/metrics.py,
``--metrics-port``). Error replies are explicit: a shed request gets
``!!SERVER-OVERLOADED ...``, an expired one ``!!SERVER-TIMEOUT ...`` —
never a silent hang.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

from .. import obs
from ..common import logging as log
from ..data.batch_generator import bucket_length
from ..obs import slo as mslo
from ..serving import metrics as msm
from ..serving.admission import AdmissionController, Overloaded
from ..serving.scheduler import (ContinuousScheduler, DispatchStalled,
                                 RequestTimeout, RowEvicted)
from ..training import bundle as bdl

try:
    import websockets
    HAVE_WS = True
except ImportError:  # pragma: no cover
    HAVE_WS = False

# graceful-drain budget on shutdown: long enough for a queued maximal batch
# to finish decoding, far below any orchestrator's kill timeout
DRAIN_TIMEOUT_S = 30.0

# Request-tracing protocol extension (ISSUE 8, backwards-compatible): a
# client MAY make the first line of its frame `#trace:<id>` (id: up to 64
# alnum/-/_ chars — scripts/loadgen.py generates 16-hex ones). The server
# strips it, labels the request's span tree with the id, and prepends a
# `#trace:<id> outcome=.. queue_ms=.. service_ms=.. model_version=..`
# metadata line to the reply, so the client can attribute latency to
# queue wait vs device service (swap/canary blips become attributable
# client-side). Clients that send no header see the exact old protocol.
TRACE_PREFIX = "#trace:"
_MAX_TRACE_ID = 64


def split_trace_header(text: str) -> Tuple[Optional[str], str]:
    """(trace_id | None, body) — see TRACE_PREFIX above. A malformed id
    is treated as payload, never an error (the header is advisory)."""
    if not text.startswith(TRACE_PREFIX):
        return None, text
    first, sep, rest = text.partition("\n")
    tid = first[len(TRACE_PREFIX):].strip()
    if not tid or len(tid) > _MAX_TRACE_ID \
            or not all(c.isalnum() or c in "-_" for c in tid):
        return None, text
    return tid, rest if sep else ""


# Tenant-selection protocol extension (ISSUE 20, backwards-compatible
# like #trace): in --fleet mode a client picks its model family by
# making the next line `#model:<tag>`. Headers stack in order #trace,
# #model, #priority, #stream. A MALFORMED tag is payload, never an
# error (the usual header discipline) — but a WELL-FORMED tag naming no
# configured tenant is an explicit !!SERVER-ERROR reply: silently
# translating legal text with the wrong model is the one failure mode a
# fleet must never have. Tags share the trace-id alphabet plus '.', so
# the first '/' in a pool owner label is an unambiguous tenant prefix
# (serving/fleet/accounting.py).
MODEL_PREFIX = "#model:"
_MAX_MODEL_TAG = 64


def split_model_header(text: str) -> Tuple[Optional[str], str]:
    """(tenant tag | None, body) — see MODEL_PREFIX above."""
    if not text.startswith(MODEL_PREFIX):
        return None, text
    first, sep, rest = text.partition("\n")
    tag = first[len(MODEL_PREFIX):].strip()
    if not tag or len(tag) > _MAX_MODEL_TAG \
            or not all(c.isalnum() or c in "-_." for c in tag):
        return None, text
    return tag, rest if sep else ""


# Priority-lane protocol extension (ISSUE 11, backwards-compatible like
# #trace): a client MAY make the first body line `#priority:<int>`; the
# server strips it and admits/schedules the request in that lane. Under
# brownout level 3 the low lanes are shed explicitly while high lanes
# keep serving (serving/brownout.py). Headers stack: #trace first, then
# #priority. A malformed value is payload, never an error. The value is
# CLAMPED to [PRIORITY_MIN, PRIORITY_MAX]: the scheduler keeps one lane
# per distinct priority forever, so an unclamped client-controlled int
# would let any client grow the lane table (and its per-round sort)
# without bound.
PRIORITY_PREFIX = "#priority:"
PRIORITY_MIN, PRIORITY_MAX = -9, 9


def split_priority_header(text: str) -> Tuple[Optional[int], str]:
    """(clamped priority | None, body) — see PRIORITY_PREFIX above."""
    if not text.startswith(PRIORITY_PREFIX):
        return None, text
    first, sep, rest = text.partition("\n")
    raw = first[len(PRIORITY_PREFIX):].strip()
    try:
        prio = int(raw)
    except ValueError:
        return None, text
    return max(PRIORITY_MIN, min(PRIORITY_MAX, prio)), rest if sep else ""


# Streaming protocol extension (ISSUE 16, backwards-compatible like
# #trace / #priority; headers stack in that order, #stream last): a
# client MAY send `#stream:1` — the server then delivers partial target
# text as the decode progresses, one `#partial:<sentence_idx> <text>`
# frame per engine round per still-decoding sentence, followed by the
# normal final reply frame (which for tracing clients carries the
# #trace metadata line, and on retriable eviction is the usual
# !!SERVER-RETRY — i.e. the stream closes retriably). Greedy partials
# are append-only prefixes of the final text; beam partials are the
# CURRENT best hypothesis and may retract earlier text when the beam
# reranks. Only iteration mode produces partials; a request-mode server
# accepts the header and simply never emits any (clients NaN-suppress
# ttft, like loadgen). A malformed value is payload, never an error.
STREAM_PREFIX = "#stream:"
PARTIAL_PREFIX = "#partial:"


def split_stream_header(text: str) -> Tuple[Optional[bool], str]:
    """(stream | None, body) — see STREAM_PREFIX above."""
    if not text.startswith(STREAM_PREFIX):
        return None, text
    first, sep, rest = text.partition("\n")
    raw = first[len(STREAM_PREFIX):].strip()
    if raw not in ("0", "1"):
        return None, text
    return raw == "1", rest if sep else ""
# per-connection cap on bytes the EOF watch may read ahead of the framing
# parser while a reply is pending — bounds what a flooding pipelined
# client can make the server buffer
MAX_READAHEAD = 1 << 20


def _fleet_unrouted(lines: List[str]) -> List[str]:
    """The fleet-mode scheduler's translate_lines: every request must
    resolve through the tenant router, so reaching this is a routing
    bug (handle_frame rejects un-tagged requests without a default
    tenant BEFORE they queue), never a client error."""
    raise RuntimeError(
        "fleet-mode batch reached the un-routed translate path — a "
        "request was queued without a tenant tag")


class TranslationService:
    """Preloaded graphs + jitted search shared across requests (reference:
    TranslationService in marian_server.cpp)."""

    def __init__(self, options):
        from ..translator.translator import Translate
        self.translator = Translate(options)

    def translate_lines(self, lines: List[str]) -> List[str]:
        import io as _io
        buf = _io.StringIO()
        got = self.translator.run(lines=lines, stream=buf)
        if len(got) != len(lines):
            # one entry per input line is what the batched reply slicing
            # relies on — a silent mismatch would route one client's
            # translations to another, so fail loudly instead
            raise RuntimeError(
                f"translator returned {len(got)} lines for {len(lines)} "
                f"inputs — per-request reply slicing would misalign")
        return got

    def translate(self, text: str) -> str:
        return "\n".join(self.translate_lines(text.split("\n")))


def resolve_token_budget(options) -> int:
    """--batch-token-budget, or derived from --mini-batch x the bucketed
    --max-length when unset — the derived value reproduces the sentence-
    count batching the pre-serving server did, so the flagless command
    line keeps its old capacity."""
    budget = int(options.get("batch-token-budget", 0) or 0)
    if budget > 0:
        return budget
    mb = max(1, int(options.get("mini-batch", 1) or 1))
    ml = max(1, int(options.get("max-length", 50) or 50))
    return mb * bucket_length(ml + 1)


class ServingApp:
    """One serving stack: TranslationService (or an injected
    translate_lines — tests, load generators) + continuous scheduler +
    admission control + metrics endpoint + (with ``--model-watch``) the
    zero-downtime model lifecycle (serving/lifecycle/ — ISSUE 5: bundle
    watcher, warmed hot-swap, canary routing, auto-rollback). Shared by
    every transport."""

    def __init__(self, options, translate_lines=None,
                 registry: Optional[msm.Registry] = None,
                 executor_factory=None, engine=None):
        self.options = options
        self.registry = registry if registry is not None else msm.REGISTRY
        # observability (ISSUE 8): --trace enables the span tracer,
        # --trace-dump arms the flight recorder; /tracez rides the
        # metrics port (start() below)
        obs.configure(options)
        budget = resolve_token_budget(options)
        # --batching-mode iteration (ISSUE 10): scheduling moves INSIDE
        # the decode loop over a paged KV pool — sentences join a
        # running decode each step and leave the step they finish
        # (translator/iteration.py; docs/DEPLOYMENT.md "Iteration-level
        # batching"). `engine` injects a prebuilt engine (tests).
        self.batching_mode = str(
            options.get("batching-mode", "request") or "request")
        if self.batching_mode == "iteration":
            self._validate_iteration_options(options)
        # persisted compile cache (ISSUE 20): --compile-cache DIR points
        # jax's persistent compilation cache there at boot, so this
        # process both reuses prior compiles AND has a cache directory
        # to pack into bundles (compile_cache.pack_member)
        cc_dir = str(options.get("compile-cache", "") or "")
        if cc_dir:
            from ..serving.lifecycle import compile_cache as mcc
            mcc.enable(cc_dir)
        # multi-tenant fleet serving (ISSUE 20): --fleet replaces the
        # single boot model with N tenants warmed on demand; requests
        # route by the #model: header. Request mode only — the paged
        # iteration engine drives ONE model's decode loop; iteration
        # tenants belong on dedicated replicas.
        self.fleet = None
        self._fleet_default = str(
            options.get("fleet-default-tenant", "") or "")
        fleet_spec = str(options.get("fleet", "") or "")
        if fleet_spec:
            if self.batching_mode == "iteration":
                raise ValueError(
                    "--fleet serves --batching-mode request only: the "
                    "paged iteration engine is single-model (route "
                    "iteration tenants to dedicated replicas)")
            if float(options.get("model-watch", 0) or 0) > 0:
                raise ValueError(
                    "--fleet and --model-watch are mutually exclusive: "
                    "the fleet already runs one bundle watcher per "
                    "tenant (--fleet-watch)")
            if translate_lines is None:
                # no single boot model to load — every request resolves
                # through the tenant router; align the Translate-internal
                # batcher exactly as the single-model path below does
                options.set("mini-batch-words", budget)
                options.set("mini-batch", budget)
                options.set("maxi-batch", 1)
                translate_lines = _fleet_unrouted
                self.service = None
        if translate_lines is None:
            # align the Translate-internal batcher with the scheduler's
            # groups: one scheduler batch == one device batch, hitting the
            # bucket table's warm jit shapes. All three knobs matter: the
            # token budget governs splitting, and maxi-batch x mini-batch
            # is the maxi-WINDOW cap in sentences (translate-mode
            # mini-batch defaults to 1 — left alone, the window cap of 1
            # would shred every scheduler batch back into single-sentence
            # device batches). Rows per batch can never exceed
            # budget / min-bucket-width, so the budget itself is a safe
            # window cap.
            options.set("mini-batch-words", budget)
            options.set("mini-batch", budget)
            options.set("maxi-batch", 1)
            service = TranslationService(options)
            translate_lines = service.translate_lines
            self.service: Optional[TranslationService] = service
        else:
            self.service = None
        engine_factory = None
        if self.batching_mode == "iteration":
            if engine is None:
                if self.service is None:
                    raise ValueError(
                        "--batching-mode iteration with an injected "
                        "translate_lines needs an injected engine too "
                        "(the paged engine drives the model directly)")
                # rebuild hook resolves THROUGH the lifecycle when one
                # is attached: after a watchdog trip the fresh engine
                # must serve the CURRENT live version, not the boot one
                engine_factory = self._rebuild_live_engine
                engine = self._build_engine()
            # admission prices queue debt in PAGES: default bound is
            # 4x the pool (a full pool of backlog ahead of you is
            # already seconds of queueing; --max-queue-pages overrides)
            self.max_queue_pages = int(
                options.get("max-queue-pages", 0) or 0) \
                or 4 * engine.pool.usable_pages
            # resolved THROUGH the scheduler at call time: a watchdog
            # trip rebuilds scheduler.engine, and a method bound to the
            # dead engine would both misprice admission and keep its
            # whole device-side pool alive (the retention class
            # PERF.set_capacity_inputs's docstring warns about)
            self._pages_for_text = \
                lambda text: self.scheduler.engine.pages_for_text(text)
            self._pool_provider = True
        else:
            self._pages_for_text = None
            self.max_queue_pages = 0
            self._pool_provider = False
        self.scheduler = ContinuousScheduler(
            translate_lines, token_budget=budget, registry=self.registry,
            stall_timeout=float(
                options.get("dispatch-stall-timeout", 0) or 0),
            batching_mode=self.batching_mode, engine=engine,
            engine_factory=engine_factory)
        if self._pool_provider:
            # every flight dump (pool.audit_failed, failed quiesce,
            # brownout escalation, watchdog, poison...) embeds the KV
            # page map at incident time (ISSUE 14). Resolved through
            # the scheduler so swaps/rebuilds dump the live engine.
            from ..obs import poolz as mpoolz
            obs.FLIGHT.add_snapshot_provider(
                "pool", lambda: mpoolz.snapshot(self.scheduler))
        self.admission = AdmissionController(
            int(options.get("max-queue", 512) or 0),
            self.scheduler.queued_units, registry=self.registry,
            max_queue_pages=self.max_queue_pages,
            pages_fn=self.scheduler.queued_pages)
        self.request_timeout = float(options.get("request-timeout", 0) or 0)
        self.metrics_server: Optional[msm.MetricsServer] = None
        self._started = False
        # perf/capacity plane (ISSUE 9, obs/perf.py): wire the headroom
        # gauge's admission-pressure inputs and the MFU geometry; both
        # no-ops when --perf-accounting is off
        self._perf_wired = obs.PERF.enabled
        if obs.PERF.enabled:
            if self.registry is not msm.REGISTRY:
                # configure() enabled the plane on the process-global
                # registry; this app scrapes ITS registry — re-declare
                # the perf series there so /metrics actually shows them
                # (the global copies stay registered but un-emitted)
                obs.PERF.enable(registry=self.registry)
            if self.batching_mode == "iteration":
                # the headroom gauge's queue-pressure units become
                # PAGES (docs/DEPLOYMENT.md): queued page debt against
                # the page bound is what predicts pool saturation
                obs.PERF.set_capacity_inputs(self.scheduler.queued_pages,
                                             self.max_queue_pages)
            else:
                obs.PERF.set_capacity_inputs(
                    self.scheduler.queued_units,
                    self.admission.max_queue_units)
            self._set_perf_geometry()
        # SLO burn-rate engine (obs/slo.py): constructed only when an
        # objective is declared (--slo-availability / --slo-p99-ms);
        # it reads the scheduler's existing counters on its own thread —
        # nothing on the batch path
        self.slo: Optional[mslo.SloEngine] = \
            mslo.maybe_build_engine(options, self.registry)
        if self.slo is not None:
            obs.FLIGHT.add_snapshot_provider("slo", self.slo.state)
        # brownout ladder (--brownout, ISSUE 11; serving/brownout.py):
        # signal-driven degradation levels over the SLO burn-rate and
        # capacity-headroom signals the obs plane already maintains
        self.brownout = None
        self._brownout_cap_factor = float(
            options.get("brownout-cap-factor", 0.5) or 0.5)
        self._brownout_min_priority = int(
            options.get("brownout-min-priority", 1) or 1)
        if options.get("brownout", False):
            from ..serving.brownout import BrownoutController
            burn_thr = float(options.get("brownout-burn", 0) or 0)
            if burn_thr <= 0:
                # default to the SLO engine's fast-burn factor; with no
                # SLO declared the burn signal is off and headroom
                # drives the ladder alone
                burn_thr = self.slo.fast_factor \
                    if self.slo is not None else 0.0
            self.brownout = BrownoutController(
                apply_fn=self._apply_brownout,
                headroom_fn=obs.PERF.headroom if obs.PERF.enabled
                else None,
                burn_fn=self.slo.fast_burn if self.slo is not None
                else None,
                registry=self.registry,
                headroom_floor=float(
                    options.get("brownout-headroom", 0.1) or 0.1),
                burn_threshold=burn_thr,
                hold_s=float(options.get("brownout-hold", 5.0) or 5.0),
                cool_s=float(options.get("brownout-cool", 15.0) or 15.0))
            obs.FLIGHT.add_snapshot_provider("brownout",
                                             self.brownout.state)
            if not obs.PERF.enabled and burn_thr <= 0:
                # both signals dead: headroom_fn is None (reads 1.0,
                # never at the floor) and the burn guard is off — the
                # ladder would tick forever without ever escalating
                # while the operator believes overload protection is on
                log.warn("--brownout is armed but BOTH of its signals "
                         "are disabled (--perf-accounting off and no "
                         "--slo-* objective declared): the ladder will "
                         "never escalate. Enable --perf-accounting or "
                         "declare an SLO (or set --brownout-burn > 0).")
        # zero-downtime lifecycle (--model-watch SECONDS): registry +
        # watcher + warmup + swap controller over <model>.bundles/
        self.lifecycle = None
        self.watcher = None
        watch_s = float(options.get("model-watch", 0) or 0)
        if watch_s > 0:
            self._init_lifecycle(watch_s, translate_lines,
                                 executor_factory)
        if fleet_spec:
            self._init_fleet(fleet_spec, executor_factory)

    # The decode-output-shaping flags iteration mode must take a
    # position on, and that position (ISSUE 16). True = lifted into the
    # paged engines (translator/decode_features.py); a string = why the
    # paged path still refuses it. EVERY flag in DECODE_SURFACE_FLAGS
    # must appear here: a set flag with no entry is refused as
    # UNCLASSIFIED rather than silently decoded without its feature —
    # no flag may fall through to wrong output (the regression test in
    # tests/test_decode_features.py pins exactly that).
    DECODE_SURFACE_FLAGS = ("n-best", "output-sampling", "force-decode",
                            "shortlist", "alignment", "word-scores",
                            "output-approx-knn")
    ITERATION_DECODE_SURFACE = {
        "n-best": True,
        "output-sampling": True,
        "force-decode": True,
        "shortlist": True,
        "alignment": "alignment output — the paged step keeps no "
                     "per-row attention tap",
        "word-scores": "per-word scores — the paged step keeps no "
                       "per-token logp trail",
        "output-approx-knn": "approximate-knn output layers — the LSH "
                             "projection is batch-shaped, not per-row",
    }

    @classmethod
    def _validate_iteration_options(cls, options) -> None:
        """--batching-mode iteration composes with a restricted option
        surface (docs/DEPLOYMENT.md "decode-surface matrix"): the paged
        engines decode a single model (greedily at --beam-size 1,
        copy-on-write beam search above) and — since ISSUE 16 — carry
        the per-row decode-feature plane (shortlist, sampling, n-best,
        force-decode). What remains unsupported fails LOUDLY at boot
        via ITERATION_DECODE_SURFACE above, rather than serving
        something subtly different from what was asked. --model-watch
        DOES compose since ISSUE 11: swaps/canaries/rollbacks re-point
        the engine through the quiesce protocol at a step boundary with
        an empty join set (--quiesce-deadline bounds the drain)."""
        problems = []
        beam = int(options.get("beam-size", 6) or 6)
        if beam < 1:
            problems.append("--beam-size must be >= 1")
        steps = int(options.get("iteration-steps", 1) or 1)
        if steps < 1:
            problems.append("--iteration-steps must be >= 1 (got "
                            f"{steps})")
        merge = str(options.get("iteration-beam-merge", "fused")
                    or "fused")
        if merge not in ("fused", "host"):
            problems.append(f"--iteration-beam-merge {merge!r} "
                            "(choose 'fused' or 'host')")
        elif merge == "host" and steps > 1 \
                and (beam > 1 or bool(options.get("n-best", False))):
            problems.append(
                "--iteration-beam-merge host with --iteration-steps "
                f"{steps}: the host merge needs the host between steps "
                "(rounds run single-step) — drop to --iteration-steps 1 "
                "or keep the default fused merge")
        if beam > int(options.get("iteration-rows", 32) or 32):
            problems.append(
                f"--beam-size {beam} exceeds --iteration-rows "
                f"{options.get('iteration-rows', 32)} (one sentence "
                f"needs beam-size decode slots)")
        models = list(options.get("models", []) or [])
        if len(models) > 1:
            problems.append("--models ensembles are not supported")
        set_flags = []
        for flag in cls.DECODE_SURFACE_FLAGS:
            v = options.get(flag, None)
            if v in (None, False, [], "", 0):
                continue
            set_flags.append(flag)
            verdict = cls.ITERATION_DECODE_SURFACE.get(flag)
            if verdict is True:
                continue
            if not verdict:
                verdict = ("UNCLASSIFIED decode flag — add it to "
                           "ITERATION_DECODE_SURFACE before serving it "
                           "in iteration mode")
            problems.append(f"--{flag} ({verdict})")
        if "shortlist" in set_flags and "force-decode" in set_flags:
            # same refusal the FeaturePlane constructor makes — caught
            # here so the operator sees it at boot, not at first claim
            problems.append(
                "--shortlist together with --force-decode (forced "
                "prefix ids are full-vocab, shortlisted logits are not)")
        if int(options.get("num-devices", 0) or 0) > 1:
            problems.append("--num-devices > 1 (the paged pallas call "
                            "is GSPMD-opaque, like the fused decode "
                            "kernel)")
        if problems:
            raise ValueError(
                "--batching-mode iteration does not support: "
                + "; ".join(problems))

    def _build_engine(self):
        """Fresh PagedDecodeEngine over the boot TranslationService's
        model."""
        return self._engine_for(self.service, self.registry)

    def _engine_for(self, service, registry):
        from ..translator.iteration import PagedDecodeEngine
        tr = service.translator
        opts = self.options
        ml = max(1, int(opts.get("max-length", 50) or 50))
        # per-row decode-feature plane (ISSUE 16): shortlist / sampling
        # / n-best / force-decode, parsed from the SAME flags the dense
        # request-mode path reads; None when no feature is on (engines
        # keep their exact pre-feature compiled step)
        from ..translator.decode_features import FeaturePlane
        plane = FeaturePlane.from_options(opts, tr.src_vocab,
                                          tr.trg_vocab)
        if plane is not None:
            log.info("iteration decode-feature plane: {}",
                     plane.describe())
        prefix = None
        if opts.get("prefix-cache", False):
            from ..translator.prefix_cache import PrefixCache
            # engine-scoped cache, version-stamped with the model path:
            # a hot swap builds a fresh engine + fresh cache, so a
            # stale version's pages/outputs are unreachable
            prefix = PrefixCache(
                max_entries=int(
                    opts.get("prefix-cache-entries", 64) or 64),
                version=str((opts.get("models", None) or ["model"])[0]))
            if plane is not None and plane.n_best:
                # a cached reply would bake in the ORIGINAL request's
                # sentence numbering (the n-best block carries sids) —
                # replaying it to another request mislabels every line
                log.info("--n-best disables the prefix cache: cached "
                         "n-best replies would carry another request's "
                         "sentence ids")
                prefix = None
        kw = dict(
            max_rows=int(opts.get("iteration-rows", 32) or 32),
            page_len=int(opts.get("kv-page-len", 16) or 16),
            pool_bytes=int(opts.get("kv-pool-bytes", 0) or 0),
            src_len_cap=bucket_length(ml + 1),
            max_length_cap=ml,
            max_length_factor=float(
                opts.get("max-length-factor", 3.0) or 3.0),
            registry=registry,
            prefix_cache=prefix,
            features=plane)
        beam = int(opts.get("beam-size", 6) or 6)
        use_beam = beam > 1 or (plane is not None and plane.n_best)
        if use_beam:
            # COW paged beam search (ISSUE 12): same slot engine, one
            # sentence = beam slots, full pages shared by refcount
            from ..translator.beam_iteration import PagedBeamEngine
            norm = opts.get("normalize", 0.0)
            if norm is True:
                norm = 1.0
            # beam rounds scan --iteration-steps like greedy since
            # ISSUE 18: the fused on-device merge keeps EOS freezing
            # and the COW reorder in-graph, one host sync per round.
            # merge='host' (the A/B baseline) clamps itself to
            # single-step inside the engine; the boot validator already
            # rejected the explicit host+steps combo loudly.
            return PagedBeamEngine(
                tr.model, tr.params_list[0], tr.src_vocab, tr.trg_vocab,
                beam_size=beam,
                normalize=float(norm or 0.0),
                word_penalty=float(opts.get("word-penalty", 0.0) or 0.0),
                allow_unk=bool(opts.get("allow-unk", False)),
                merge=str(opts.get("iteration-beam-merge", "fused")
                          or "fused"),
                steps_per_round=int(opts.get("iteration-steps", 1) or 1),
                **kw)
        return PagedDecodeEngine(
            tr.model, tr.params_list[0], tr.src_vocab, tr.trg_vocab,
            steps_per_round=int(opts.get("iteration-steps", 1) or 1),
            **kw)

    def _bundle_engine_factory(self, bundle_dir: str, manifest):
        """executor_factory for iteration mode (ISSUE 11): a warmed
        candidate is a whole PagedDecodeEngine (model + its own device
        page pool) over a fresh TranslationService built against the
        bundle's model member. The EngineExecutor wrapper is callable
        for the golden smoke (warm_executor drives the engine's real
        install/step jits off the serving path) and carries ``.engine``
        for the quiesce re-point. Candidate engines declare no gauges —
        the pool gauges re-point to whichever engine installs
        (scheduler.install_engine)."""
        from ..translator.iteration import EngineExecutor
        member = os.path.basename(self._model_path())
        bopts = self.options.with_(
            models=[os.path.join(bundle_dir, member)])
        return EngineExecutor(
            self._engine_for(TranslationService(bopts), registry=None))

    def _rebuild_live_engine(self):
        """The scheduler's engine_factory (watchdog-trip rebuild — the
        wedged worker thread owns the old engine's device state): a
        fresh engine for the CURRENT live version. With the lifecycle
        attached, rebuild from the live version's bundle and hand the
        controller the replacement executor so round attribution and
        rollbacks track the engine actually serving.

        The bundle case loads a whole model ON THE EVENT LOOP — a
        bounded (seconds) stall of every connection, paid only on a
        watchdog trip / unrecovered round failure. The alternative
        (deferring the build to a thread) would let queued sentences
        join the known-broken engine in the meantime, which is worse
        than a rare bounded stall."""
        from ..translator.iteration import EngineExecutor
        lc = self.lifecycle
        if lc is not None:
            v = lc.live_version()
            if v is not None and getattr(v, "bundle_dir", ""):
                ex = self._bundle_engine_factory(v.bundle_dir,
                                                 v.manifest or {})
                lc.adopt_live_executor(ex)
                return ex.engine
        engine = self._build_engine()
        if lc is not None:
            lc.adopt_live_executor(EngineExecutor(engine))
        return engine

    def _apply_brownout(self, level: int) -> None:
        """BrownoutController's effect hook: push the level into the
        scheduler (cap tightening + row eviction) and admission (lane
        shedding)."""
        self.scheduler.set_brownout_level(
            level, cap_factor=self._brownout_cap_factor)
        self.admission.set_brownout(level, self._brownout_min_priority)

    def _set_perf_geometry(self) -> None:
        """Feed the live-MFU gauges the real model geometry when a real
        TranslationService is behind the scheduler; injected stubs
        (tests, load generators) leave the geometry unset and MFU reads
        0 rather than a guess."""
        if self.service is None:
            return
        try:
            cfg = getattr(self.service.translator.model, "cfg", None)
            if cfg is None or not hasattr(cfg, "dim_ffn"):
                return            # RNN family: no priced decode path
            obs.PERF.set_geometry(
                emb=int(cfg.dim_emb), ffn=int(cfg.dim_ffn),
                enc_depth=int(getattr(cfg, "enc_depth", 6)),
                dec_depth=int(getattr(cfg, "dec_depth", 6)),
                vocab=len(self.service.translator.trg_vocab),
                beam=int(self.options.get("beam-size", 12) or 12))
        except Exception as e:  # noqa: BLE001 — observability is optional
            log.warn("perf accounting: could not derive model geometry "
                     "({}); MFU gauge stays 0", e)

    def _model_path(self) -> str:
        models = self.options.get("models", []) or []
        return str(models[0] if models
                   else self.options.get("model", "") or "")

    @staticmethod
    def _adopt_boot_bundle(model_path: str, valid):
        """Which committed bundle IS the flat (published) model file?
        Same inode in the normal hardlink-publish case; otherwise ONE
        content hash of the flat file compared against each manifest's
        recorded member sha256 (copy-fallback publish). None when it
        matches no bundle (stale publish, hand-copied model)."""
        base = os.path.basename(model_path)
        for b in reversed(valid):
            try:
                if os.path.samefile(model_path,
                                    os.path.join(b.bundle_dir, base)):
                    return b
            except OSError:
                continue
        try:
            flat_sha = bdl.file_sha256(model_path)
        except OSError:
            return None
        for b in reversed(valid):
            rec = (b.manifest or {}).get("members", {}).get(base) or {}
            if rec.get("sha256") == flat_sha:
                return b
        return None

    def _init_lifecycle(self, interval: float, boot_translate,
                        executor_factory) -> None:
        from ..serving.lifecycle import (BundleWatcher, SwapController,
                                         load_golden, scan_bundles)
        model_path = self._model_path()
        if not model_path:
            log.warn("--model-watch: no model path to watch; lifecycle "
                     "disabled")
            return
        iteration = self.batching_mode == "iteration"
        factory = executor_factory or (
            self._bundle_engine_factory if iteration
            else self._bundle_executor_factory)
        self.lifecycle = SwapController(
            executor_factory=factory,
            metrics_registry=self.registry,
            canary_fraction=float(
                self.options.get("canary-fraction", 0) or 0),
            rollback_error_rate=float(
                self.options.get("rollback-error-rate", 0.5) or 0.5),
            rollback_p99_factor=float(
                self.options.get("rollback-p99-factor", 0) or 0),
            canary_min_batches=int(
                self.options.get("canary-min-batches", 8) or 8),
            golden=load_golden(
                self.options.get("warmup-golden", "") or None))
        # seed the boot model as the live version. The flat model file is
        # NORMALLY the published view of the newest valid bundle — but
        # only when it verifiably IS that bundle's member (a crash
        # between bundle commit and flat publish, or a hand-copied
        # model, leaves the flat file older). Adopt the seq of the
        # bundle the flat file actually matches, so the watcher warms +
        # swaps to anything newer instead of silently serving stale
        # weights labeled with the newest bundle's name.
        boot_seq, boot_name, boot_compat = 0, "boot", None
        valid = [b for b in scan_bundles(model_path) if b.ok]
        adopted = self._adopt_boot_bundle(model_path, valid)
        if adopted is not None:
            boot_seq = adopted.seq
            boot_name = os.path.basename(adopted.bundle_dir)
            boot_compat = bdl.manifest_compat(adopted.manifest)
            if adopted is not valid[-1]:
                log.warn("--model-watch: boot model {} matches {} but "
                         "newer committed bundles exist (stale publish?); "
                         "the watcher will hot-swap to the newest",
                         model_path, boot_name)
        elif valid:
            # valid bundles exist but the flat file matches none of them:
            # seed one seq below the newest so the watcher ingests it
            boot_seq = valid[-1].seq - 1
            log.warn("--model-watch: boot model {} matches no committed "
                     "bundle; seeding as '{}' (seq {}) so the newest "
                     "bundle is warmed and swapped in", model_path,
                     boot_name, boot_seq)
        if boot_compat is None and self.service is not None:
            opts = self.service.translator.options
            boot_compat = bdl.compat_block(
                opts, list(opts.get("vocabs", None) or []))
        if iteration:
            # the boot "executor" in iteration mode wraps the engine the
            # scheduler is already running; the quiesce protocol re-
            # points at successors' engines (ISSUE 11)
            from ..translator.iteration import EngineExecutor
            self.lifecycle.seed_live(
                boot_seq, boot_name, EngineExecutor(self.scheduler.engine),
                compat=boot_compat)
            self.lifecycle.attach_iteration(
                self.scheduler,
                float(self.options.get("quiesce-deadline", 2.0) or 2.0))
        else:
            self.lifecycle.seed_live(boot_seq, boot_name, boot_translate,
                                     compat=boot_compat)
            self.scheduler.translate_lines = self.lifecycle.route
        self.scheduler.version_fn = self.lifecycle.live_version_name
        self.watcher = BundleWatcher(bdl.bundle_root(model_path),
                                     self.lifecycle.ingest,
                                     interval=interval,
                                     last_seq=boot_seq)
        # same-process trainer (online learning): commits push the
        # watcher instead of waiting out the poll interval
        bdl.add_commit_hook(self._on_bundle_commit)

    def _on_bundle_commit(self, model_path: str, bundle_dir: str,
                          manifest) -> None:
        if self.watcher is not None \
                and os.path.dirname(os.path.abspath(bundle_dir)) \
                == os.path.abspath(self.watcher.root):
            self.watcher.notify()

    def _bundle_executor_factory(self, bundle_dir: str, manifest):
        """Build a fresh TranslationService against a bundle's model
        member (jit caches and all — warmed off the serving path, then
        swapped in whole)."""
        member = os.path.basename(self._model_path())
        bopts = self.options.with_(
            models=[os.path.join(bundle_dir, member)])
        return TranslationService(bopts).translate_lines

    def _init_fleet(self, spec: str, executor_factory) -> None:
        """--fleet (ISSUE 20): build the FleetManager — per-tenant
        lifecycle stacks under a shared HBM budget — and wire it into
        the scheduler's tenant router + per-tenant version labels and
        the per-tenant SLO engines (docs/DEPLOYMENT.md "Fleet
        serving")."""
        from ..serving import fleet as mfleet
        from ..serving.lifecycle import load_golden
        specs = mfleet.parse_fleet_spec(spec)
        tags = {s.tag for s in specs}
        if self._fleet_default and self._fleet_default not in tags:
            raise ValueError(
                f"--fleet-default-tenant '{self._fleet_default}' is not "
                f"a configured tenant (have: {', '.join(sorted(tags))})")
        opts = self.options
        self.fleet = mfleet.FleetManager(
            specs,
            executor_factory or self._fleet_executor_factory,
            metrics_registry=self.registry,
            hbm_budget_bytes=int(
                float(opts.get("fleet-hbm-budget-mb", 0) or 0) * (1 << 20)),
            watch_interval=float(opts.get("fleet-watch", 0) or 0),
            golden=load_golden(opts.get("warmup-golden", "") or None),
            canary_fraction=float(opts.get("canary-fraction", 0) or 0),
            rollback_error_rate=float(
                opts.get("rollback-error-rate", 0.5) or 0.5),
            rollback_p99_factor=float(
                opts.get("rollback-p99-factor", 0) or 0),
            canary_min_batches=int(
                opts.get("canary-min-batches", 8) or 8),
            brownout_min_priority=self._brownout_min_priority)
        n = self.fleet.build_slos(
            availability=float(opts.get("slo-availability", 0) or 0),
            p99_ms=float(opts.get("slo-p99-ms", 0) or 0))
        if n:
            log.info("fleet: per-tenant SLO engines armed for {} "
                     "tenant(s)", n)
        self.scheduler.tenant_router = self.fleet.executor_for
        self.scheduler.tenant_version_fn = self.fleet.live_version_name
        # every flight dump carries the fleet table (residency, per-
        # tenant burn, page sums) — the CI smoke's failure artifact
        obs.FLIGHT.add_snapshot_provider("fleet", self.fleet.status)

    def _fleet_executor_factory(self, bundle_dir: str, manifest):
        """Default per-tenant executor factory: a fresh
        TranslationService against the bundle's model member — or
        against ``bundle_dir`` itself when a tenant warms from a flat
        model path (no bundles committed yet)."""
        if os.path.isfile(bundle_dir):
            model = bundle_dir
        else:
            members = (manifest or {}).get("members", {}) or {}
            model = next(
                (os.path.join(bundle_dir, rel) for rel in sorted(members)
                 if rel.endswith(".npz") and "optimizer" not in rel),
                None)
            if model is None:
                raise ValueError(
                    f"fleet: bundle {bundle_dir} carries no model "
                    f"member (members: {sorted(members) or 'none'})")
        bopts = self.options.with_(models=[model])
        return TranslationService(bopts).translate_lines

    def _admin_routes(self) -> Dict:
        """Lifecycle endpoints on the metrics port: GET /lifecyclez
        (version table + health), POST /admin/pin | /admin/unpin |
        /admin/rollback (operator verbs; docs/DEPLOYMENT.md)."""
        lc = self.lifecycle

        def _lifecyclez(method: str, query: str):
            body = json.dumps(lc.status(), indent=1).encode() + b"\n"
            return 200, body, "application/json"

        def _verb(fn, name):
            def handler(method: str, query: str):
                if method != "POST":
                    return (405, b"POST only\n", "text/plain")
                ok = fn()
                ok = True if ok is None else bool(ok)
                body = json.dumps({"ok": ok, "verb": name,
                                   "live": lc.live_version_name()}
                                  ).encode() + b"\n"
                return (200 if ok else 409, body, "application/json")
            return handler

        return {
            "/lifecyclez": _lifecyclez,
            "/admin/pin": _verb(lc.pin, "pin"),
            "/admin/unpin": _verb(lc.unpin, "unpin"),
            "/admin/rollback": _verb(lc.rollback, "rollback"),
        }

    def ready(self) -> bool:
        """/readyz: accepting traffic (started, not draining, and — with
        the lifecycle — a warmed live version is routing; a replica
        still warming its first model reads 503 so load balancers hold
        traffic)."""
        if not self._started or self.admission.draining:
            return False
        return self.lifecycle is None or self.lifecycle.has_live()

    async def start(self) -> None:
        self.scheduler.start()
        # /tracez and /sloz are always routed (they report "disabled"
        # rather than 404 — operators should not have to guess); admin
        # verbs only exist with the lifecycle
        routes = obs.trace_routes()
        routes.update(mslo.slo_routes(lambda: self.slo,
                                      lambda: self.brownout))
        # /poolz rides the metrics port like /tracez and /sloz: always
        # routed, request-mode servers answer enabled:false (ISSUE 14)
        routes.update(obs.pool_routes(lambda: self.scheduler))
        if self.lifecycle is not None:
            routes.update(self._admin_routes())
        if self.fleet is not None:
            # /fleetz: the fleet table — per-tenant residency, live
            # version, in-flight batches, cold starts, SLO burn, page
            # sums — same JSON the flight dump embeds
            routes["/fleetz"] = lambda method, query: (
                200, json.dumps(self.fleet.status(), indent=1).encode()
                + b"\n", "application/json")
        self.metrics_server = msm.maybe_start_metrics_server(
            self.options, ready_fn=self.ready, routes=routes)
        if self.slo is not None:
            self.slo.start()
        if self.brownout is not None:
            self.brownout.start()
        if self.options.get("warmup-on-boot", False):
            # not gated on the perf plane: the user asked for warm
            # buckets either way — without --perf-accounting only the
            # compile TELEMETRY is skipped (warm_bucket no-ops)
            self._boot_warmup()
        if self.watcher is not None:
            self.watcher.start()
        if self.fleet is not None:
            # pre-warm every tenant the budget allows (spec order; the
            # earliest-warmed become the LRU victims under pressure) and
            # start the per-tenant SLO evaluator + bundle watchers
            self.fleet.start()
        self._started = True
        log.info("Serving: token budget {} padded tokens/batch, queue "
                 "limit {} sentences, request timeout {}",
                 self.scheduler.token_budget,
                 self.admission.max_queue_units or "unbounded",
                 f"{self.request_timeout}s" if self.request_timeout
                 else "none")

    def _boot_warmup(self) -> None:
        """--warmup-on-boot: per-bucket golden warmup of the boot
        executor BEFORE the first client lands, reported as
        trigger=boot-warmup compile telemetry (ISSUE 9) — without it the
        first request of every width bucket pays the jit inline and
        shows up as a steady-state recompile incident. Failure degrades
        to a warning: a cold-but-correct server beats no server."""
        from ..serving.lifecycle.warmup import (DEFAULT_GOLDEN,
                                                load_golden, smoke_buckets)
        try:
            golden = load_golden(
                self.options.get("warmup-golden", "") or None) \
                or list(DEFAULT_GOLDEN)
            # warm under the EXACT label the scheduler will stamp on
            # batches (its version_fn — "unversioned" without a
            # lifecycle): a mismatched label would leave every warmed
            # bucket reading as a steady-state recompile incident
            version = self.scheduler._version_label()
            smoke_buckets(self.scheduler.translate_lines, golden,
                          version, "boot-warmup", "boot model")
        except Exception as e:  # noqa: BLE001
            log.warn("--warmup-on-boot failed ({}); first requests pay "
                     "the jit compile inline", e)

    async def handle_text(self, text: str, priority: int = 0) -> str:
        """One protocol frame in, one reply frame out — the transport-
        agnostic request path (admission -> scheduler -> reply).
        Convenience over :meth:`handle_frame` for callers that don't
        report the reply-write moment (or stream partials)."""
        reply, done = await self.handle_frame(text, priority)
        done(len(reply.encode("utf-8")))   # nbytes means BYTES everywhere
        return reply

    async def handle_frame(self, text: str, priority: int = 0,
                           send_partial: Optional[
                               Callable[[str], None]] = None
                           ) -> Tuple[str, Callable[[int], None]]:
        """(reply, done) — the transports call ``done(nbytes)`` after
        the reply bytes hit the socket, which closes the request's root
        span with a ``reply.write`` child covering the write (ISSUE 8:
        the span tree spans ingest → … → reply write). ``done`` is a
        no-op when tracing is off.

        ``send_partial`` is the transport's partial-frame writer for
        #stream: clients (called on the event-loop thread, in order,
        strictly before this coroutine returns the final reply); None
        means the transport cannot stream — the header is then ignored,
        which is also the request-mode behavior."""
        t0 = time.perf_counter()
        trace_id, body = split_trace_header(text)
        model_tag, body = split_model_header(body)
        hdr_priority, body = split_priority_header(body)
        if hdr_priority is not None:
            priority = hdr_priority
        stream, body = split_stream_header(body)
        on_partial = None
        if stream and send_partial is not None:
            def on_partial(idx: int, partial: str, _ntok: int) -> None:
                send_partial(f"{PARTIAL_PREFIX}{idx} {partial}")
        lines = body.split("\n")
        # fleet mode (ISSUE 20): the #model: tag picks the tenant (or
        # --fleet-default-tenant); without a fleet the header is payload
        tenant = ""
        if self.fleet is not None:
            tenant = model_tag or self._fleet_default

        def finish(outcome: str, reply: str):
            if self.fleet is not None and tenant:
                # the tenant-labeled series the per-tenant SLO engines
                # burn against (end-to-end latency, this coroutine)
                self.fleet.note_outcome(tenant, outcome,
                                        time.perf_counter() - t0)
            return self._finish_frame(trace_id, meta, span, outcome,
                                      reply)

        span = None
        if obs.enabled():
            span = obs.start_span("request", trace_id=trace_id or None,
                                  n_sentences=len(lines),
                                  priority=priority, tenant=tenant)
        # reply metadata (queue vs service breakdown) is collected iff
        # the client asked for it by sending a trace header
        meta: Optional[Dict] = {} if trace_id is not None else None
        if self.fleet is not None and not self.fleet.has_tenant(tenant):
            # a WELL-FORMED but unconfigured tag (or no tag and no
            # default) is an explicit error — translating legal text
            # with the wrong model is the one thing a fleet must never
            # do. Shed label "?" — tags are client-controlled, and an
            # unbounded label value would be a cardinality bomb.
            self.fleet.note_shed("?", "unknown_tenant")
            tenant = ""     # don't bill outcomes to the unknown tag
            return finish(
                "failure",
                f"!!SERVER-ERROR unknown model tag "
                f"'{model_tag or self._fleet_default or '(none)'}' — "
                f"send #model:<tag> "
                f"(configured: {', '.join(self.fleet.tags())})")
        n_pages = (sum(self._pages_for_text(l) for l in lines)
                   if self._pages_for_text is not None else 0)
        try:
            # admit inside the span context so a shed's timeline event
            # inherits the trace id (flight dumps tie it to the victim);
            # the per-tenant gate runs first — a tenant burning its own
            # error budget sheds before it costs global queue space
            with obs.TRACER.use(span):
                if self.fleet is not None:
                    self.fleet.gate(tenant, priority)
                self.admission.admit(len(lines), n_pages=n_pages,
                                     priority=priority)
        except Overloaded as e:
            return finish("shed", f"!!SERVER-OVERLOADED {e}")
        with obs.TRACER.use(span):
            fut = self.scheduler.submit(
                lines, priority=priority,
                timeout=self.request_timeout or None,
                meta=meta, trace_id=trace_id, on_partial=on_partial,
                tenant=tenant)
        try:
            out = await fut
        except RequestTimeout as e:
            return finish("timeout", f"!!SERVER-TIMEOUT {e}")
        except DispatchStalled as e:
            # watchdog liveness trip: explicitly retriable — the replica
            # is healthy again (fresh device worker), resend the request
            return finish("stalled", f"!!SERVER-RETRY {e}")
        except RowEvicted as e:
            # quiesce-deadline / brownout / recoverable-engine-failure
            # eviction (ISSUE 11): pages freed, replica healthy or about
            # to be — explicitly retriable, counted, never silent
            return finish("evicted", f"!!SERVER-RETRY {e}")
        except asyncio.CancelledError:
            # client abort: record the root span before unwinding — an
            # aborted request is exactly what an operator inspects later,
            # and an un-ended span never reaches the ring
            if self.fleet is not None and tenant:
                self.fleet.note_outcome(tenant, "cancelled",
                                        time.perf_counter() - t0)
            obs.end(span, outcome="cancelled")
            raise
        except Exception:  # error already logged by the scheduler
            return finish("failure", "")
        return finish("ok", "\n".join(out))

    @staticmethod
    def _finish_frame(trace_id: Optional[str], meta: Optional[Dict],
                      span, outcome: str, reply: str
                      ) -> Tuple[str, Callable[[int], None]]:
        """Prepend the reply-metadata header for tracing clients and
        build the ``done`` callback that records the write + ends the
        root span."""
        if trace_id is not None:
            m = meta or {}
            line = (f"{TRACE_PREFIX}{trace_id} "
                    f"outcome={m.get('outcome', outcome)} "
                    f"queue_ms={m.get('queue_s', 0.0) * 1e3:.1f} "
                    f"service_ms={m.get('service_s', 0.0) * 1e3:.1f} "
                    f"model_version={m.get('model_version', '-')}")
            if "rounds" in m:
                # iteration-mode row breakdown (ISSUE 14): decode
                # rounds participated, time-to-first-join (-1 = never
                # joined), prefix-cache hit flag, retriable evictions
                line += (f" rounds={m['rounds']} "
                         f"ttfj_ms={m.get('ttfj_ms', -1.0):.1f} "
                         f"prefix_hit={m.get('prefix_hit', 0)} "
                         f"evictions={m.get('evictions', 0)}")
            reply = line + "\n" + reply
        if span is None:
            return reply, lambda nbytes=0: None
        t_reply = time.perf_counter()

        def done(nbytes: int = 0) -> None:
            obs.TRACER.record("reply.write", t_reply, time.perf_counter(),
                              parent=span, nbytes=nbytes)
            obs.end(span, outcome=outcome)
        return reply, done

    async def shutdown(self, drain_timeout: float = DRAIN_TIMEOUT_S) -> bool:
        """Drain-on-shutdown: stop admitting (readyz flips to 503 so load
        balancers stop routing here), finish queued work, then stop."""
        self.admission.begin_drain()
        queued = self.scheduler.queued_units()
        if queued:
            log.info("Draining {} queued sentences (up to {}s)", queued,
                     drain_timeout)
        ok = await self.scheduler.drain(drain_timeout)
        if not ok:
            log.warn("Drain timed out after {}s — queued requests failed",
                     drain_timeout)
        # the scheduler resolving the last futures and the per-connection
        # handler tasks WRITING those replies are separate loop steps — a
        # short grace lets the handlers flush before the transport (and
        # then the loop) tears down, else drained work still resets
        # client connections
        await asyncio.sleep(0.2)
        self.close_nowait()
        return ok

    def close_nowait(self) -> None:
        """Synchronous hard cleanup (cancelled contexts, test teardown)."""
        self._started = False
        if self._perf_wired:
            # unwire the process-global headroom gauge from this app's
            # scheduler: a scrape after close must not sample a dead
            # scheduler (or keep its model graph alive via the bound
            # method)
            obs.PERF.set_capacity_inputs(None, 0)
            self._perf_wired = False
        if self._pool_provider:
            obs.FLIGHT.remove_snapshot_provider("pool")
            self._pool_provider = False
        if self.slo is not None:
            self.slo.stop()
            obs.FLIGHT.remove_snapshot_provider("slo")
        if self.brownout is not None:
            self.brownout.stop()
            obs.FLIGHT.remove_snapshot_provider("brownout")
            self.brownout = None
        if self.watcher is not None:
            bdl.remove_commit_hook(self._on_bundle_commit)
            self.watcher.stop()
            self.watcher = None
        if self.fleet is not None:
            obs.FLIGHT.remove_snapshot_provider("fleet")
            self.fleet.stop()
            self.fleet = None
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None


def _make_ws_handler(app: ServingApp):
    """The per-connection WebSocket protocol, shared by _serve and the
    tests (so the real wiring is what gets exercised). A dropped
    connection cancels the handler task mid-await, which cancels the
    request future — the scheduler then discards its queued sentences
    before they cost device time (cancellation propagation).

    Streaming (#stream:, ISSUE 16): partial frames are enqueued by the
    scheduler's round loop while ``handle_frame`` is awaited; a per-
    connection drainer task sends them in order, and the final reply
    rides the SAME queue, so a client can never see it before (or
    interleaved with) its partials."""
    async def handler(ws):
        q: "asyncio.Queue[Optional[str]]" = asyncio.Queue()

        async def _drain():
            while True:
                frame = await q.get()
                try:
                    await ws.send(frame)
                finally:
                    q.task_done()

        drainer = asyncio.ensure_future(_drain())
        try:
            async for message in ws:
                reply, done = await app.handle_frame(
                    message, send_partial=q.put_nowait)
                nbytes = 0
                try:
                    q.put_nowait(reply)
                    flushed = asyncio.ensure_future(q.join())
                    # a dead drainer (send failed: client gone) leaves
                    # queue items un-acked forever — never await join
                    # unguarded
                    await asyncio.wait({flushed, drainer},
                                       return_when=asyncio.FIRST_COMPLETED)
                    if not flushed.done():
                        flushed.cancel()
                        drainer.result()     # surface the send error
                    # UTF-8 byte count, matching the TCP path — the trace
                    # attribute must mean the same thing on both
                    # transports
                    nbytes = len(reply.encode("utf-8"))
                finally:
                    # root span must close even when the send fails
                    # (client abort is exactly the case an operator
                    # inspects later)
                    done(nbytes)
        finally:
            drainer.cancel()
    return handler


def _make_tcp_handler(app: ServingApp):
    """Length-prefixed TCP framing: ``MTPU <nbytes>\\n`` + payload, both
    directions. Dependency-free stand-in for the ws transport (same
    ServingApp path) — used by scripts/loadgen.py and the serving tests.

    Cancellation parity with the ws transport: while a reply is pending,
    the connection is watched for EOF — a client that disconnects cancels
    its request, so the scheduler drops the queued sentences before they
    cost device time (same guarantee the ws path gets from the handler
    task being cancelled on close). The watch is RE-ARMED after every
    pipelined chunk (PR 8 review fix: it previously stopped at the first
    byte, so a pipelining client's disconnect was only noticed at
    reply-write time — its queued sentences still cost device work);
    read-ahead lands in a buffer the framing reads drain first."""
    async def on_connection(reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter):
        # bytes read ahead by the EOF watch of a pipelining client —
        # drained by _readline/_readexactly before touching the socket
        buf = b""

        async def _readline() -> bytes:
            nonlocal buf
            if b"\n" in buf:
                line, _, rest = buf.partition(b"\n")
                buf = rest
                return line + b"\n"
            line, buf = buf, b""
            return line + await reader.readline()

        async def _readexactly(n: int) -> bytes:
            nonlocal buf
            take, buf = buf[:n], buf[n:]
            if len(take) < n:
                take += await reader.readexactly(n - len(take))
            return take

        try:
            while True:
                header = await _readline()
                if not header:
                    break
                parts = header.split()
                # the length must be a NON-NEGATIVE integer before it
                # reaches _readexactly: python slicing with a negative
                # count would silently mis-slice buffered read-ahead
                # bytes (the raw StreamReader used to raise for us), and
                # a non-numeric length deserves the explicit bad-frame
                # reply, not a silent close
                nbytes = (int(parts[1])
                          if len(parts) == 2 and parts[0] == b"MTPU"
                          and parts[1].isdigit() else -1)
                if nbytes < 0:
                    writer.write(b"MTPU 24\n!!SERVER-ERROR bad frame")
                    await writer.drain()
                    break
                payload = await _readexactly(nbytes)

                def _send_partial(frame: str) -> None:
                    # one MTPU frame per partial (#stream:, ISSUE 16),
                    # written on the event-loop thread in delivery
                    # order, always before the final reply frame below;
                    # TCP backpressure is absorbed by the writer buffer
                    # and drained with the final reply
                    b = frame.encode("utf-8")
                    writer.write(b"MTPU %d\n" % len(b) + b)

                reply_t = asyncio.ensure_future(
                    app.handle_frame(payload.decode("utf-8"),
                                     send_partial=_send_partial))
                eof = False
                while not reply_t.done():
                    if len(buf) >= MAX_READAHEAD:
                        # bounded read-ahead: past the cap, stop reading
                        # and let TCP backpressure throttle the client
                        # (a flooding pipeliner must not grow server
                        # memory while a reply is in flight; EOF in this
                        # state is noticed at reply-write time, like the
                        # pre-watch behavior)
                        await asyncio.wait({reply_t})
                        break
                    watch = asyncio.ensure_future(reader.read(65536))
                    await asyncio.wait({reply_t, watch},
                                       return_when=asyncio.FIRST_COMPLETED)
                    if watch.done():
                        data = watch.result()
                        if not data:    # EOF: client gone mid-request
                            eof = True
                            break
                        buf += data     # pipelined bytes: keep, re-watch
                    else:
                        # cancelling an un-fired read() consumes nothing
                        watch.cancel()
                        try:
                            await watch
                        except asyncio.CancelledError:
                            pass
                if eof and not reply_t.done():
                    reply_t.cancel()
                    try:
                        await reply_t
                    except (asyncio.CancelledError, Exception):  # noqa: BLE001
                        pass
                    break
                reply, reply_done = await reply_t
                out = reply.encode("utf-8")
                nbytes = 0
                try:
                    writer.write(b"MTPU %d\n" % len(out) + out)
                    await writer.drain()
                    nbytes = len(out)
                finally:
                    # close the root span even when the write fails —
                    # a mid-write disconnect must not drop the request's
                    # span tree from /tracez and flight dumps
                    reply_done(nbytes)
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass                     # client went away / malformed frame
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass
    return on_connection


async def _serve(options, ready: Optional[asyncio.Future] = None) -> None:
    """Serve forever. `ready` (tests): resolved with the bound port once
    listening — pass --port 0 to bind an ephemeral port."""
    app = ServingApp(options)
    await app.start()
    port = int(options.get("port", 8080))

    def _announce(bound: int, transport: str) -> None:
        log.info("Server is listening on port {} ({})", bound, transport)
        if ready is not None and not ready.cancelled():
            ready.set_result(bound)

    async def _serve_until_cancelled() -> None:
        """Runs INSIDE the transport's serve context so the graceful
        drain completes while client connections are still open — in-
        flight clients get their replies before the listener (and with
        it every connection) is torn down on context exit."""
        try:
            await asyncio.Future()
        except asyncio.CancelledError:
            # shielded from the cancellation already delivered to this
            # task: finish queued work before going down
            await asyncio.shield(app.shutdown())
            raise

    try:
        if HAVE_WS:
            async with websockets.serve(_make_ws_handler(app), "0.0.0.0",
                                        port) as server:
                _announce(next(iter(server.sockets)).getsockname()[1],
                          "websocket")
                await _serve_until_cancelled()
        else:
            log.warn("the 'websockets' package is unavailable — serving "
                     "the length-prefixed TCP framing instead (Marian ws "
                     "clients cannot connect; scripts/loadgen.py "
                     "--transport tcp speaks it)")
            server = await asyncio.start_server(
                _make_tcp_handler(app), "0.0.0.0", port)
            async with server:
                _announce(server.sockets[0].getsockname()[1], "tcp")
                await _serve_until_cancelled()
    finally:
        app.close_nowait()


def serve_main(options) -> None:
    async def _main():
        import signal
        loop = asyncio.get_event_loop()
        task = asyncio.ensure_future(_serve(options))
        # SIGTERM (orchestrator shutdown) and SIGINT both route through
        # _serve's cancellation path: drain, then exit
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, task.cancel)
            except (NotImplementedError, RuntimeError):  # pragma: no cover
                pass                         # non-Unix / nested loop
        try:
            await task
        except asyncio.CancelledError:
            pass

    try:
        asyncio.run(_main())
    except KeyboardInterrupt:  # signal handler could not be installed
        pass
