"""marian-server: translation service on a WebSocket port (reference:
src/command/marian_server.cpp + vendored simple-websocket-server).

Protocol kept Marian-compatible: client sends newline-joined source
sentences as a text frame, server replies with newline-joined translations.
Uses the `websockets` package (gated — a clear error if unavailable).

Beyond the reference: concurrent requests are funneled through ONE
worker with a short dynamic-batching window — sentences from requests
arriving within ~5 ms translate as one device batch (better MXU
utilization than per-request batches), and the single worker also
serializes access to the shared Translate driver (whose jit caches and
prefix state are not re-entrant). The reference serves each connection
on its own thread against per-thread graphs; one TPU program shared by
all clients replaces that design.
"""

from __future__ import annotations

import asyncio
from typing import List, Optional, Tuple

from ..common import logging as log

try:
    import websockets
    HAVE_WS = True
except ImportError:  # pragma: no cover
    HAVE_WS = False

# dynamic-batching window: long enough to coalesce a burst of concurrent
# clients, far below human-visible latency
BATCH_WINDOW_S = 0.005


class TranslationService:
    """Preloaded graphs + jitted search shared across requests (reference:
    TranslationService in marian_server.cpp)."""

    def __init__(self, options):
        from ..translator.translator import Translate
        self.translator = Translate(options)

    def translate_lines(self, lines: List[str]) -> List[str]:
        import io as _io
        buf = _io.StringIO()
        got = self.translator.run(lines=lines, stream=buf)
        if len(got) != len(lines):
            # one entry per input line is what the batched reply slicing
            # relies on — a silent mismatch would route one client's
            # translations to another, so fail loudly instead
            raise RuntimeError(
                f"translator returned {len(got)} lines for {len(lines)} "
                f"inputs — per-request reply slicing would misalign")
        return got

    def translate(self, text: str) -> str:
        return "\n".join(self.translate_lines(text.split("\n")))


async def _batching_worker(queue: "asyncio.Queue[Tuple[str, asyncio.Future]]",
                           translate_lines) -> None:
    """Drain the request queue into dynamic batches: block for the first
    request, then coalesce everything arriving inside the window; one
    translate_lines call per batch (in an executor — the device work
    must not block the event loop); per-request replies by line count.

    Failure isolation: a failing BATCH is retried per request, so one
    client's bad input fails only that client (the per-request error
    domain of the unbatched design). The worker itself survives any
    exception short of cancellation — a dead worker would hang every
    future request on an unresolved future."""
    loop = asyncio.get_event_loop()

    async def _reply(batch):
        lines: List[str] = []
        counts: List[int] = []
        for t, _f in batch:
            ls = t.split("\n")
            counts.append(len(ls))
            lines.extend(ls)
        out = await loop.run_in_executor(None, translate_lines, lines)
        i = 0
        for (_t, f), c in zip(batch, counts):
            if not f.cancelled():
                f.set_result("\n".join(out[i:i + c]))
            i += c

    while True:
        try:
            text, fut = await queue.get()
            batch = [(text, fut)]
            # Coalesce the burst with sleep + get_nowait, NOT
            # wait_for(queue.get()): cancelling a waiting get() (what
            # wait_for does on timeout, Python < 3.12) can consume a
            # just-enqueued item and drop it — the client would await an
            # unresolved future forever (ADVICE r3).
            await asyncio.sleep(BATCH_WINDOW_S)
            while True:
                try:
                    batch.append(queue.get_nowait())
                except asyncio.QueueEmpty:
                    break
            try:
                await _reply(batch)
            except Exception as e:  # noqa: BLE001
                if len(batch) == 1:
                    log.error("translation error: {}", e)
                    if not batch[0][1].cancelled():
                        batch[0][1].set_exception(RuntimeError(str(e)))
                else:
                    # isolate the failure: one bad request must not fail
                    # the whole coalesced batch
                    log.error("batch translation error ({} requests — "
                              "retrying individually): {}", len(batch), e)
                    for entry in batch:
                        try:
                            await _reply([entry])
                        except Exception as e1:  # noqa: BLE001
                            log.error("translation error: {}", e1)
                            if not entry[1].cancelled():
                                entry[1].set_exception(
                                    RuntimeError(str(e1)))
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — supervision: never die
            log.error("server worker error (recovered): {}", e)


def _make_handler(queue: "asyncio.Queue[Tuple[str, asyncio.Future]]"):
    """The per-connection protocol, shared by _serve and the tests (so
    the real wiring is what gets exercised)."""
    async def handler(ws):
        async for message in ws:
            fut = asyncio.get_event_loop().create_future()
            await queue.put((message, fut))
            try:
                reply = await fut
            except Exception:  # error already logged by the worker
                reply = ""
            await ws.send(reply)
    return handler


async def _serve(options, ready: Optional[asyncio.Future] = None) -> None:
    """Serve forever. `ready` (tests): resolved with the bound port once
    listening — pass --port 0 to bind an ephemeral port."""
    service = TranslationService(options)
    port = int(options.get("port", 8080))
    queue: "asyncio.Queue[Tuple[str, asyncio.Future]]" = asyncio.Queue()
    worker = asyncio.ensure_future(
        _batching_worker(queue, service.translate_lines))

    try:
        async with websockets.serve(_make_handler(queue), "0.0.0.0",
                                    port) as server:
            bound = next(iter(server.sockets)).getsockname()[1]
            log.info("Server is listening on port {}", bound)
            if ready is not None and not ready.cancelled():
                ready.set_result(bound)
            await asyncio.Future()
    finally:
        worker.cancel()


def serve_main(options) -> None:
    if not HAVE_WS:
        raise RuntimeError(
            "marian-server needs the 'websockets' package (not installed)")
    asyncio.run(_serve(options))
