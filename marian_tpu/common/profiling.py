"""Tracing / profiling subsystem (SURVEY §5 row 1 — the reference ships
nvtx ranges + nvprof hooks in src/common/profiler.h; the TPU-native
equivalents are jax.profiler device traces and HLO dumps).

Three surfaces:

- ``--profile [dir]``: capture a jax.profiler trace (TensorBoard / xprof
  format) around a window of training updates. The trace records every XLA
  op's device time — the tool the round-1 verdict flagged as missing for
  locating the throughput gap.
- ``--dump-hlo path``: write the jaxpr and the optimized HLO of the jitted
  train step (the ExpressionGraph::graphviz debugging equivalent).
- ``StepTimer``: lightweight host-side wall-clock histogram of the train
  loop phases (data, step dispatch, host bookkeeping) — finds host-bound
  gaps a device trace doesn't show.

``StepTimer`` and ``TraceWindow`` were folded onto the span-tracer API
(ISSUE 8) and now live in ``marian_tpu/obs/profiling.py`` — the names
below are re-export shims so existing call sites keep importing from
here. StepTimer additionally gained the ``sync_fn`` device-sync honesty
fix (see its module docstring / docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from ..obs.profiling import StepTimer, TraceWindow  # noqa: F401 — shims
from . import logging as log


def default_cache_dir() -> str:
    """The one place the persistent-cache location is decided: the
    manifest check MUST look at the same directory the cache writes to,
    or a drifted manifest silently re-enables cold-compile surprises."""
    return os.environ.get(
        "MARIAN_XLA_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), ".cache", "xla"))


def enable_compilation_cache(path: Optional[str] = None) -> None:
    """Point JAX's persistent compilation cache at a repo-local directory so
    repeated invocations (bench reruns, CLI restarts, the driver's
    end-of-round bench) skip the 20-40s XLA compile per train-step shape.
    Safe to call more than once; a cache miss behaves exactly like no cache.
    """
    import jax
    path = path or default_cache_dir()
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        log.warn("persistent compilation cache unavailable: {}", e)


def _cache_fingerprint() -> Dict[str, str]:
    """Identity of the compiler stack the persistent cache was warmed
    against. A libtpu/jax version bump (the round-2 outage cause) or a
    different chip generation invalidates every entry silently — XLA just
    misses and recompiles, turning a warm 30s bench into a cold 20-40min
    one over the tunnel."""
    import jax
    fp = {"jax": jax.version.__version__}
    try:
        import jaxlib.version
        fp["jaxlib"] = jaxlib.version.__version__
    except Exception:  # noqa: BLE001
        fp["jaxlib"] = "?"
    try:
        import jax.extend.backend as eb
        backend = eb.get_backend()
        fp["platform"] = backend.platform
        fp["platform_version"] = str(
            getattr(backend, "platform_version", "?"))
        devs = jax.devices()
        fp["device_kind"] = devs[0].device_kind if devs else "?"
    except Exception as e:  # noqa: BLE001
        fp["platform"] = f"unavailable: {e}"
    return fp


def check_cache_manifest(write: bool = False,
                         path: Optional[str] = None) -> bool:
    """Compare the live compiler-stack fingerprint against
    ``.cache/xla/MANIFEST.json``. Returns True when the warmed cache is
    trustworthy (fingerprints match, or ``write=True`` just stamped a
    fresh manifest). On mismatch: logs loudly and returns False so
    callers can drop optional double-compile work (bench.py skips the
    fused-CE A/B — VERDICT r2 next-step #6). Requires backends to be
    initialized (call after watchdog_devices)."""
    import json

    cache_dir = path or default_cache_dir()
    manifest_p = os.path.join(cache_dir, "MANIFEST.json")
    fp = _cache_fingerprint()
    if write:
        try:
            os.makedirs(cache_dir, exist_ok=True)
            with open(manifest_p, "w") as fh:
                json.dump(fp, fh, indent=1)
        except OSError as e:
            log.warn("cache manifest write failed: {}", e)
        return True
    try:
        with open(manifest_p) as fh:
            stamped = json.load(fh)
    except (OSError, ValueError):
        log.warn("no cache manifest at {} — treating the {} -entry cache "
                 "as cold (compiles may take minutes over the tunnel)",
                 manifest_p,
                 len(os.listdir(cache_dir)) if os.path.isdir(cache_dir)
                 else 0)
        return False
    drift = {k: (stamped.get(k), v) for k, v in fp.items()
             if stamped.get(k) != v}
    if drift:
        log.warn("XLA cache manifest MISMATCH (cache warmed on a "
                 "different stack — every entry will silently miss): {}",
                 "; ".join(f"{k}: cached={a!r} live={b!r}"
                           for k, (a, b) in drift.items()))
        return False
    return True


def maybe_start_profile_server(options) -> bool:
    """--profile-server PORT: live profiler endpoint on a RUNNING job —
    TensorBoard's profile tab / xprof connect and capture on demand,
    with no pre-planned trace window (the TPU-era answer to attaching
    nvprof to a running trainer; SURVEY §5 tracing row). Returns whether
    a server was started."""
    port = int(options.get("profile-server", 0) or 0)
    if port <= 0:
        return False
    import jax
    try:
        jax.profiler.start_server(port)
    except Exception as e:  # noqa: BLE001 — diagnostics must not kill train
        log.warn("--profile-server {}: failed to start ({})", port, e)
        return False
    log.info("Profiler server listening on port {} (attach with "
             "TensorBoard's profile tab or xprof)", port)
    return True


def dump_lowered(path: str, lowered) -> None:
    """Write <path>.hlo.txt (stable HLO) and <path>.hlo_opt.txt (post-
    fusion — what actually runs on the chip) for a lowered jitted call
    (reference: ExpressionGraph::graphviz / --dump-graph debugging)."""
    base = path[:-4] if path.endswith(".txt") else path
    with open(base + ".hlo.txt", "w") as fh:
        fh.write(lowered.as_text())
    try:
        with open(base + ".hlo_opt.txt", "w") as fh:
            fh.write(lowered.compile().as_text())
    except Exception as e:  # noqa: BLE001
        log.warn("optimized-HLO dump failed: {}", e)
    log.info("Dumped train-step HLO to {}.hlo*.txt", base)
