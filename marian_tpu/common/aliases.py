"""Task aliases: one flag expands to a canonical hyperparameter bundle.

Rebuild of reference src/common/aliases.cpp (``--task transformer-base`` etc.).
Values follow the well-known transformer-base/big recipes that Marian's alias
table encodes; on TPU we additionally set bfloat16 compute precision (the
reference's fp16 path) since that is the MXU-native dtype.
"""

from __future__ import annotations

from typing import Any, Dict

_TRANSFORMER_BASE: Dict[str, Any] = {
    "type": "transformer",
    "enc-depth": 6,
    "dec-depth": 6,
    "dim-emb": 512,
    "transformer-dim-ffn": 2048,
    "transformer-heads": 8,
    "transformer-postprocess": "dan",
    "transformer-preprocess": "",
    "transformer-ffn-activation": "relu",
    "transformer-dropout": 0.1,
    "transformer-dropout-attention": 0.0,
    "transformer-dropout-ffn": 0.0,
    "label-smoothing": 0.1,
    "clip-norm": 0.0,
    "learn-rate": 0.0003,
    "lr-warmup": "16000",
    "lr-decay-inv-sqrt": ["16000"],
    "lr-report": True,
    "optimizer-params": [0.9, 0.98, 1e-09],
    "cost-type": "ce-mean-words",
    "tied-embeddings-all": True,
    "sync-sgd": True,
    "exponential-smoothing": 0.0001,
    "max-length": 100,
    "mini-batch-fit": True,
    "mini-batch": 1000,
    "maxi-batch": 1000,
    "beam-size": 8,
    "valid-mini-batch": 16,
    "normalize": 1.0,
}

_TRANSFORMER_BIG: Dict[str, Any] = dict(
    _TRANSFORMER_BASE,
    **{
        "dim-emb": 1024,
        "transformer-dim-ffn": 4096,
        "transformer-heads": 16,
        "transformer-dropout": 0.1,
        "learn-rate": 0.0002,
        "lr-warmup": "8000",
        "lr-decay-inv-sqrt": ["8000"],
    },
)


def _prenorm(base: Dict[str, Any]) -> Dict[str, Any]:
    return dict(base, **{
        "transformer-preprocess": "n",
        "transformer-postprocess": "da",
        "transformer-postprocess-top": "n",
    })


ALIASES: Dict[str, Dict[str, Any]] = {
    "transformer-base": _TRANSFORMER_BASE,
    "transformer-big": _TRANSFORMER_BIG,
    "transformer-base-prenorm": _prenorm(_TRANSFORMER_BASE),
    "transformer-big-prenorm": _prenorm(_TRANSFORMER_BIG),
}


def expand_aliases(task: str, merged: Dict[str, Any]) -> Dict[str, Any]:
    """Apply alias bundle under current values: alias keys override defaults,
    but anything the user set in a config file stays only if it differs from
    the parser default at a later merge stage (Marian applies aliases before
    explicit user options; we mirror that in ConfigParser.parse)."""
    if task not in ALIASES:
        raise SystemExit(
            f"Unknown --task '{task}'; known: {', '.join(sorted(ALIASES))}")
    out = dict(merged)
    out.update(ALIASES[task])
    return out
