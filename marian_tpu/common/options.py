"""Type-flexible options store — the TPU-native equivalent of Marian's
``Options`` (reference: src/common/options.h :: Options::get<T>/has/with).

Marian passes a YAML-node-backed, type-erased dictionary through every layer of
the stack. We keep the same UX (one object, dotted flag names with dashes,
``get``/``has``/``with`` API) but back it with a plain dict — idiomatic Python,
trivially picklable into checkpoints (Marian embeds the config as the
``special:model.yml`` tensor; we do the same in io.py).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Iterator, Optional

import yaml


class Options:
    """Immutable-by-convention key-value store for all configuration.

    Keys are Marian-style flag names with dashes (``mini-batch-words``).
    Values are plain Python scalars / lists / dicts.
    """

    __slots__ = ("_data",)

    def __init__(self, data: Optional[Dict[str, Any]] = None, **kwargs: Any):
        self._data: Dict[str, Any] = dict(data or {})
        if kwargs:
            # allow Options(foo_bar=1) → "foo-bar"
            for k, v in kwargs.items():
                self._data[k.replace("_", "-")] = v

    # -- core API (mirrors Options::get<T>, Options::has) ------------------
    def get(self, key: str, default: Any = ...) -> Any:
        key = key.replace("_", "-")
        if key in self._data:
            return self._data[key]
        if default is ...:
            raise KeyError(f"Required option '{key}' is not set")
        return default

    def has(self, key: str) -> bool:
        return key.replace("_", "-") in self._data

    def nonempty(self, key: str) -> bool:
        """True if set and truthy (Marian: has() && !get().empty())."""
        key = key.replace("_", "-")
        v = self._data.get(key)
        return bool(v)

    def set(self, key: str, value: Any) -> None:
        self._data[key.replace("_", "-")] = value

    def with_(self, *updates: Dict[str, Any], **kwargs: Any) -> "Options":
        """Return a copy with updates applied (Marian: options->with(...))."""
        new = copy.deepcopy(self._data)
        for upd in updates:
            for k, v in upd.items():
                new[k.replace("_", "-")] = v
        for k, v in kwargs.items():
            new[k.replace("_", "-")] = v
        return Options(new)

    def clone(self) -> "Options":
        return Options(copy.deepcopy(self._data))

    # -- dict-ish conveniences ---------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self.get(key)

    def __contains__(self, key: str) -> bool:
        return self.has(key)

    def __iter__(self) -> Iterator[str]:
        return iter(self._data)

    def items(self):
        return self._data.items()

    def as_dict(self) -> Dict[str, Any]:
        return copy.deepcopy(self._data)

    # -- YAML round-trip (Marian: options->asYamlString, cloneFromYaml) ----
    def as_yaml(self) -> str:
        return yaml.safe_dump(self._data, default_flow_style=False, sort_keys=True)

    @classmethod
    def from_yaml(cls, text: str) -> "Options":
        data = yaml.safe_load(text) or {}
        if not isinstance(data, dict):
            raise ValueError("Top-level YAML config must be a mapping")
        return cls(data)

    def __repr__(self) -> str:
        return f"Options({len(self._data)} keys)"
