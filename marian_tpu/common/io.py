"""Model IO: ``.npz`` checkpoints (Marian-compatible) and a fast mmap-able
``.bin`` format.

Rebuild of reference src/common/io.cpp :: io::loadItems/saveItems and
src/common/binary.cpp. Conventions kept for checkpoint compatibility with
upstream Marian models:

- a checkpoint is a set of named tensors ("items");
- the model config travels inside the checkpoint as a special int8 tensor
  named ``special:model.yml`` holding the YAML text (NUL-terminated);
- optimizer state is a sibling file ``<model>.optimizer.npz``;
- training progress is a sibling YAML ``<model>.progress.yml``.

The ``.bin`` format here is little-endian: magic ``MTPUBIN1``, u64 item count,
then per item (u32 name_len, name bytes, u32 dtype_len, dtype str, u32 ndim,
u64 dims..., u64 byte_len, padding to 64B, raw data). Data offsets are 64-byte
aligned so tensors can be used directly from an mmap.
"""

from __future__ import annotations

import dataclasses
import io as _pyio
import mmap
import os
import struct
import zipfile
from typing import Any, Dict, List, Optional

import numpy as np
import yaml

SPECIAL_CONFIG_KEY = "special:model.yml"
_BIN_MAGIC = b"MTPUBIN1"
_ALIGN = 64


@dataclasses.dataclass
class Item:
    """One named tensor (reference: src/common/io/item.h :: io::Item)."""
    name: str
    array: np.ndarray


def config_to_item(config_yaml: str) -> Item:
    """Marian stores the config as int8 bytes incl. trailing NUL."""
    raw = config_yaml.encode("utf-8") + b"\x00"
    return Item(SPECIAL_CONFIG_KEY, np.frombuffer(raw, dtype=np.int8).copy())


def item_to_config(item: Item) -> str:
    raw = item.array.astype(np.int8).tobytes()
    return raw.rstrip(b"\x00").decode("utf-8")


# ---------------------------------------------------------------------------
# npz
# ---------------------------------------------------------------------------

def load_items(path: str) -> List[Item]:
    """Load npz or bin by extension (reference: io::loadItems)."""
    if path.endswith(".bin"):
        return _load_bin(path)
    out: List[Item] = []
    with np.load(path, allow_pickle=False) as npz:
        for name in npz.files:
            out.append(Item(name, npz[name]))
    return out


def save_items(path: str, items: List[Item]) -> None:
    """Save as npz or bin by extension (reference: io::saveItems).

    Writes atomically via a temp file + rename so SIGTERM/preemption during
    save never corrupts the previous checkpoint.
    """
    tmp = path + ".tmp"
    if path.endswith(".bin"):
        _save_bin(tmp, items)
    else:
        arrays = {it.name: np.asarray(it.array) for it in items}
        # np.savez_compressed writes a zip; build in-memory then flush once.
        with open(tmp, "wb") as fh:
            np.savez(fh, **arrays)
    os.replace(tmp, path)


def load_model(path: str):
    """Returns (params: dict name->ndarray, config_yaml: Optional[str])."""
    items = load_items(path)
    params: Dict[str, np.ndarray] = {}
    config: Optional[str] = None
    for it in items:
        if it.name == SPECIAL_CONFIG_KEY:
            config = item_to_config(it)
        else:
            params[it.name] = it.array
    return params, config


def save_model(path: str, params: Dict[str, np.ndarray],
               config_yaml: Optional[str] = None) -> None:
    items = [Item(k, np.asarray(v)) for k, v in sorted(params.items())]
    if config_yaml is not None:
        items.append(config_to_item(config_yaml))
    save_items(path, items)


# ---------------------------------------------------------------------------
# bin (mmap-able)
# ---------------------------------------------------------------------------

def _pad(n: int) -> int:
    return (-n) % _ALIGN


def _save_bin(path: str, items: List[Item]) -> None:
    with open(path, "wb") as fh:
        fh.write(_BIN_MAGIC)
        fh.write(struct.pack("<Q", len(items)))
        for it in items:
            arr = np.ascontiguousarray(it.array)
            name_b = it.name.encode("utf-8")
            dtype_b = arr.dtype.str.encode("ascii")
            fh.write(struct.pack("<I", len(name_b)))
            fh.write(name_b)
            fh.write(struct.pack("<I", len(dtype_b)))
            fh.write(dtype_b)
            fh.write(struct.pack("<I", arr.ndim))
            for d in arr.shape:
                fh.write(struct.pack("<Q", d))
            data = arr.tobytes()
            fh.write(struct.pack("<Q", len(data)))
            fh.write(b"\x00" * _pad(fh.tell()))
            fh.write(data)


def _load_bin(path: str) -> List[Item]:
    out: List[Item] = []
    with open(path, "rb") as fh:
        mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
        pos = 0
        if mm[pos:pos + 8] != _BIN_MAGIC:
            raise ValueError(f"{path}: not a marian-tpu .bin file")
        pos += 8
        (count,) = struct.unpack_from("<Q", mm, pos); pos += 8
        for _ in range(count):
            (nlen,) = struct.unpack_from("<I", mm, pos); pos += 4
            name = mm[pos:pos + nlen].decode("utf-8"); pos += nlen
            (dlen,) = struct.unpack_from("<I", mm, pos); pos += 4
            dtype = np.dtype(mm[pos:pos + dlen].decode("ascii")); pos += dlen
            (ndim,) = struct.unpack_from("<I", mm, pos); pos += 4
            shape = struct.unpack_from(f"<{ndim}Q", mm, pos); pos += 8 * ndim
            (blen,) = struct.unpack_from("<Q", mm, pos); pos += 8
            pos += _pad(pos)
            arr = np.frombuffer(mm, dtype=dtype, count=blen // dtype.itemsize,
                                offset=pos).reshape(shape)
            pos += blen
            out.append(Item(name, arr))
    return out


# ---------------------------------------------------------------------------
# progress yaml (TrainingState serialization lives in training/training_state)
# ---------------------------------------------------------------------------

def load_yaml(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        return yaml.safe_load(fh) or {}


def save_yaml(path: str, data: Dict[str, Any]) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        yaml.safe_dump(data, fh, default_flow_style=False, sort_keys=False)
    os.replace(tmp, path)
