"""Logging with Marian's look-and-feel (reference: src/common/logging.cpp ::
createLoggers, LOG macro; spdlog pattern "[%Y-%m-%d %T] %v").

Two named loggers, like Marian: ``general`` (training/runtime messages, goes
to stderr + optional --log file) and ``valid`` (validation messages, prefixed
``[valid]``, goes to stderr + optional --valid-log file). stdout stays clean
for translations.
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
    "off": logging.CRITICAL + 10,
}


class _MarianFormatter(logging.Formatter):
    def __init__(self, prefix: str = ""):
        super().__init__(fmt="[%(asctime)s] " + prefix + "%(message)s",
                         datefmt="%Y-%m-%d %H:%M:%S")


_initialized = False


def create_loggers(options=None) -> None:
    """Set up 'general' and 'valid' loggers from Options (or defaults)."""
    global _initialized
    quiet = bool(options and options.get("quiet", False))
    # --quiet-translation: only the translation drivers pass mode hints;
    # suppress stderr info chatter while still honoring --log files
    if options and options.get("quiet-translation", False) \
            and options.get("_translation_task", False):
        quiet = True
    level = _LEVELS.get((options.get("log-level", "info") if options else "info"), logging.INFO)
    log_file: Optional[str] = options.get("log", None) if options else None
    valid_file: Optional[str] = options.get("valid-log", None) if options else None

    for name, prefix, fpath in (("general", "", log_file),
                                ("valid", "[valid] ", valid_file)):
        lg = logging.getLogger(f"marian.{name}")
        lg.setLevel(level)
        lg.propagate = False
        lg.handlers.clear()
        if not quiet:
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(_MarianFormatter(prefix))
            lg.addHandler(h)
        if fpath:
            fh = logging.FileHandler(fpath)
            fh.setFormatter(_MarianFormatter(prefix))
            lg.addHandler(fh)
        if quiet and not fpath:
            lg.addHandler(logging.NullHandler())
    _initialized = True


def _get(name: str) -> logging.Logger:
    if not _initialized:
        create_loggers(None)
    return logging.getLogger(f"marian.{name}")


def log(level: str, msg: str, *args) -> None:
    """LOG(info, "...") equivalent; {} placeholders like spdlog."""
    if args:
        try:
            msg = msg.format(*args)
        except (IndexError, KeyError, ValueError):
            msg = f"{msg} {args}"
    _get("general").log(_LEVELS.get(level, logging.INFO), msg)


def log_valid(level: str, msg: str, *args) -> None:
    if args:
        try:
            msg = msg.format(*args)
        except (IndexError, KeyError, ValueError):
            msg = f"{msg} {args}"
    _get("valid").log(_LEVELS.get(level, logging.INFO), msg)


def info(msg: str, *args) -> None:
    log("info", msg, *args)


def warn(msg: str, *args) -> None:
    log("warn", msg, *args)


def error(msg: str, *args) -> None:
    log("error", msg, *args)
