"""Deterministic fault injection for crash-safety testing (ISSUE 4).

A FAULT POINT is a named site in production code where a test (or the
chaos harness, scripts/chaos.py) can inject a failure on demand:

    from ..common import faultpoints as fp
    ...
    fp.fault_point("ckpt.commit")      # no-op unless armed

Arming is by environment variable (crosses process boundaries — the
crash-resume tests kill real trainer subprocesses) or programmatically
(in-process tests):

    MARIAN_FAULTS="ckpt.commit=kill@2" marian-train ...
    with fp.active("serving.translate=hang:0.5"): ...

Spec grammar (comma-separated list):

    name=mode[:arg][@hit]

    mode  fail        raise InjectedFault           (simulated IO error)
          kill        os._exit(FAULT_EXIT_CODE)     (simulated SIGKILL /
                                                     TPU preemption — no
                                                     cleanup, no finally)
          hang:SECS   time.sleep(SECS), then pass   (stall — watchdog food)
          prob:P      raise with probability P, deterministic from
                      (seed, name, hit index)
    @hit  @N   trigger on the Nth hit only (1-based; default @1 —
               except prob, which defaults to @* so P applies per hit)
          @N+  trigger on every hit from the Nth on
          @*   trigger on every hit

Determinism: a given (spec, MARIAN_FAULTS_SEED, call sequence) always
fires at the same sites — reproducing a chaos-harness failure is
re-running with the printed spec and seed. Hit counters are per-name and
process-wide (thread-safe: the AsyncSaver worker, the serving executor
thread, and the training thread all cross fault points).

Every fault point must be declared in CATALOG below; mtlint's
fault-hygiene rule (MT-FAULT-UNKNOWN / MT-FAULT-UNTESTED) checks that
call sites use declared names and that every declared point is exercised
by at least one test (docs/ROBUSTNESS.md carries the operator-facing
catalog). Stdlib-only on purpose: importable from any layer, including
the analysis tooling and subprocess drivers with no jax.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, Optional, Tuple

from . import lockdep

ENV_SPEC = "MARIAN_FAULTS"
ENV_SEED = "MARIAN_FAULTS_SEED"
# distinctive exit code so tests can tell an injected kill from a real crash
FAULT_EXIT_CODE = 117

# The fault-point catalog: every fault_point() call site must use one of
# these names (mtlint MT-FAULT-UNKNOWN), and every name must be exercised
# by at least one test (MT-FAULT-UNTESTED). Keep descriptions in sync with
# docs/ROBUSTNESS.md.
CATALOG: Dict[str, str] = {
    "ckpt.write.model": "before the model member is written into staging",
    "ckpt.write.optimizer": "before the optimizer member is written",
    "ckpt.write.progress": "before the progress member is written",
    "ckpt.write.manifest": "before the bundle manifest is written",
    "ckpt.commit": "after staging is complete, before the atomic "
                   "staging->bundle rename (the commit point)",
    "ckpt.publish": "after commit, before the legacy top-level view "
                    "(model.npz etc.) is republished",
    "ckpt.async.worker": "at the start of the AsyncSaver background job",
    "data.batch.next": "in the batch pipeline, before a batch is yielded",
    "serving.dispatch": "on the event loop, before a device batch is "
                        "handed to the executor",
    "serving.translate": "on the device worker thread, before "
                         "translate_lines runs (hang mode feeds the "
                         "dispatch watchdog)",
    "lifecycle.watch": "on the bundle-watcher thread, after a new "
                       "committed bundle is discovered, before it is "
                       "handed to the lifecycle controller",
    "lifecycle.warmup": "before the candidate executor is built and "
                        "golden-smoked (model load + jit compile happen "
                        "past this point)",
    "lifecycle.swap": "after a successful warmup, before dispatch is "
                      "re-pointed at the warmed executor (the hot-swap "
                      "commit point)",
    "lifecycle.rollback": "before a canary/live rollback re-points "
                          "dispatch at the previous live version",
    "serving.quiesce": "on the event loop, at the quiesce boundary — "
                       "active rows drained/evicted, before the paged "
                       "engine is re-pointed at the new executor (kill "
                       "= the kill-mid-quiesce chaos schedule)",
    "pool.double_free": "detection drill: an armed 'fail' makes the KV "
                        "pool re-free a still-claimed row's pages (the "
                        "double-free bug class) so the pool auditor is "
                        "proven against REAL corrupted state, not a "
                        "mocked report",
    "pool.table_corrupt": "detection drill: an armed 'fail' scribbles a "
                          "wrong physical page id into one active row's "
                          "page table so the auditor's table/claim "
                          "cross-check is proven against real corruption",
    "pool.refcount_corrupt": "detection drill: an armed 'fail' bumps one "
                             "live page's refcount without a table "
                             "reference (the lost-decref/phantom-incref "
                             "bug class of the COW fork/reorder paths) "
                             "so the auditor's references-vs-refcount "
                             "cross-check is proven against real "
                             "corruption",
    "pool.release_drop": "detection drill (ISSUE 15): an armed 'fail' "
                         "makes KVPool.release silently do nothing — "
                         "the suppressed-release leak bug class — so "
                         "the runtime ownership witness "
                         "(common/ownwit.py) and the pool auditors are "
                         "proven to catch a REAL seeded leak, never a "
                         "mocked report",
    "jit.closure_vary": "detection drill (ISSUE 17): an armed 'fail' "
                        "makes the paged engine's next step jit capture "
                        "a varied closure constant — the silent-retrace "
                        "bug class (same compile key, different traced "
                        "program) — so the jit retrace witness "
                        "(common/jitwit.py) is proven to catch a REAL "
                        "recompile, never a mocked report",
    "beam.diff_corrupt": "detection drill (ISSUE 18): an armed 'fail' "
                         "truncates one live slot's device-computed "
                         "retable diff before the host refcount plane "
                         "applies it — the bad-device-diff bug class of "
                         "the fused beam merge — so the pool auditor's "
                         "table/claim cross-check is proven to catch a "
                         "REAL divergence between the device page table "
                         "and the host mirror, never a mocked report",
    "train.nan_grad": "divergence drill (ISSUE 19): an armed 'fail' "
                      "poisons one training batch's target mask with NaN "
                      "before dispatch — the transient bad-batch bug "
                      "class — so --check-gradient-nan's skip/revert, the "
                      "live skip counter, and the --on-divergence "
                      "rollback ladder are proven against a REAL "
                      "non-finite gradient, never a mocked loss",
    "train.hang": "on the training loop, once per batch iteration before "
                  "dispatch (hang mode wedges the step so it never "
                  "fences — food for the --train-stall-timeout watchdog; "
                  "kill mode is the mid-step preemption drill)",
    "train.diverge_cost": "divergence drill (ISSUE 19): an armed 'fail' "
                          "replaces one applied update's lazy loss sum "
                          "with NaN before the scheduler accumulates it — "
                          "the cost-blowup bug class that only surfaces "
                          "at the display-boundary sync — proving the "
                          "display-path detection and rollback without "
                          "touching parameters",
    "tenant.page_leak": "detection drill (ISSUE 20): an armed 'fail' "
                        "moves one page reference between the claim "
                        "lists of owners in DIFFERENT tenants — a page "
                        "charged to the wrong tenant. Refcounts are "
                        "unchanged, so KVPool.audit() stays green by "
                        "construction; only the tenant-level auditor "
                        "(serving/fleet/accounting.py::audit_tenants) "
                        "catches it, proving per-tenant isolation is "
                        "checked against REAL mischarged state, never "
                        "a mocked report",
}


class InjectedFault(RuntimeError):
    """Raised by an armed 'fail'/'prob' fault point."""


class FaultSpecError(ValueError):
    """Malformed MARIAN_FAULTS spec or undeclared fault-point name."""


class _Spec:
    __slots__ = ("name", "mode", "arg", "hit", "every_from")

    def __init__(self, name: str, mode: str, arg: Optional[float],
                 hit: Optional[int], every_from: Optional[int]):
        self.name = name
        self.mode = mode
        self.arg = arg
        self.hit = hit              # exact hit index (1-based) or None
        self.every_from = every_from  # fire on every hit >= this, or None

    def matches(self, n: int) -> bool:
        if self.every_from is not None:
            return n >= self.every_from
        return n == (self.hit if self.hit is not None else 1)


def _parse_one(piece: str) -> _Spec:
    if "=" not in piece:
        raise FaultSpecError(f"fault spec {piece!r}: expected name=mode")
    name, _, rhs = piece.partition("=")
    name = name.strip()
    if name not in CATALOG:
        raise FaultSpecError(
            f"unknown fault point {name!r} (catalog: "
            f"{', '.join(sorted(CATALOG))})")
    hit: Optional[int] = None
    every_from: Optional[int] = None
    if "@" in rhs:
        rhs, _, hs = rhs.partition("@")
        hs = hs.strip()
        try:
            if hs == "*":
                every_from = 1
            elif hs.endswith("+"):
                every_from = int(hs[:-1])
            else:
                hit = int(hs)
        except ValueError:
            raise FaultSpecError(
                f"fault point {name!r}: bad hit selector @{hs!r} "
                f"(expected @N, @N+, or @*)") from None
        # hit counters are 1-based: @0 would never match and the drill
        # would silently inject nothing
        if (hit is not None and hit < 1) \
                or (every_from is not None and every_from < 1):
            raise FaultSpecError(
                f"fault point {name!r}: hit selector @{hs} must be >= 1")
    mode, _, argtext = rhs.strip().partition(":")
    arg: Optional[float] = float(argtext) if argtext else None
    if mode not in ("fail", "kill", "hang", "prob"):
        raise FaultSpecError(f"fault point {name!r}: unknown mode {mode!r}")
    if mode == "prob" and arg is None:
        raise FaultSpecError(f"fault point {name!r}: prob needs :P")
    if mode == "prob" and hit is None and every_from is None:
        # per-hit probability is the whole point of prob — an implicit
        # @1 would roll the dice exactly once and report a clean drill
        every_from = 1
    return _Spec(name, mode, arg, hit, every_from)


def parse_spec(text: str) -> Dict[str, _Spec]:
    specs: Dict[str, _Spec] = {}
    for piece in text.split(","):
        piece = piece.strip()
        if not piece:
            continue
        s = _parse_one(piece)
        specs[s.name] = s
    return specs


class _State:
    """Process-wide arming state + per-name hit counters."""

    def __init__(self):
        self.lock = lockdep.make_lock("_State.lock")
        self.specs: Dict[str, _Spec] = {}
        self.seed = 0
        self.hits: Dict[str, int] = {}
        self.env_loaded = False


_STATE = _State()


def _load_env_locked() -> None:
    if _STATE.env_loaded:
        return
    text = os.environ.get(ENV_SPEC, "")
    if text:
        # parse BEFORE marking loaded: a malformed spec must raise at
        # EVERY crossing, not raise once and silently disarm the drill
        # (a chaos run with a typo'd spec reporting success would be
        # worse than no drill at all)
        try:
            specs = parse_spec(text)
        except FaultSpecError as e:
            _log(f"FAULTPOINT SPEC ERROR in {ENV_SPEC}: {e}")
            raise
        _STATE.specs.update(specs)
        _STATE.seed = int(os.environ.get(ENV_SEED, "0") or "0")
    _STATE.env_loaded = True


def activate(spec: str, seed: int = 0) -> None:
    """Arm fault points programmatically (replaces any previous arming,
    including the environment's); resets hit counters."""
    parsed = parse_spec(spec)
    with _STATE.lock:
        _STATE.env_loaded = True        # programmatic arming wins over env
        _STATE.specs = parsed
        _STATE.seed = int(seed)
        _STATE.hits = {}


def deactivate() -> None:
    """Disarm everything and reset hit counters (env spec stays ignored
    until reset_for_tests)."""
    with _STATE.lock:
        _STATE.env_loaded = True
        _STATE.specs = {}
        _STATE.hits = {}


def reset_for_tests() -> None:
    """Full reset: disarm AND re-read MARIAN_FAULTS on next hit."""
    with _STATE.lock:
        _STATE.specs = {}
        _STATE.hits = {}
        _STATE.env_loaded = False


class active:
    """Context manager: arm `spec` inside the block, disarm after."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = seed

    def __enter__(self) -> "active":
        activate(self.spec, seed=self.seed)
        return self

    def __exit__(self, *exc) -> None:
        deactivate()


def hits(name: str) -> int:
    """How many times `name` was crossed since the last (re)arming."""
    with _STATE.lock:
        return _STATE.hits.get(name, 0)


def hit_counts() -> Dict[str, int]:
    """Copy of every per-name hit counter (flight-recorder dumps)."""
    with _STATE.lock:
        return dict(_STATE.hits)


# Observer hooks (ISSUE 8): the obs layer records firings onto its event
# timeline and dumps the flight recorder before an injected kill. Plain
# lists mutated only at registration time (startup / arm time); firing
# iterates a snapshot, outside _STATE.lock, and swallows hook errors —
# instrumentation must never change whether the drill fires.
_FIRE_HOOKS: list = []     # fn(name, mode, hit) — any armed spec matched
_KILL_HOOKS: list = []     # fn(name, hit) — about to os._exit


def add_fire_hook(fn) -> None:
    if fn not in _FIRE_HOOKS:
        _FIRE_HOOKS.append(fn)


def add_kill_hook(fn) -> None:
    if fn not in _KILL_HOOKS:
        _KILL_HOOKS.append(fn)


def remove_fire_hook(fn) -> None:
    if fn in _FIRE_HOOKS:
        _FIRE_HOOKS.remove(fn)


def remove_kill_hook(fn) -> None:
    if fn in _KILL_HOOKS:
        _KILL_HOOKS.remove(fn)


def _run_hooks(hooks, *args) -> None:
    for fn in list(hooks):
        try:
            fn(*args)
        except Exception:  # noqa: BLE001 — observers must not alter drills
            pass


def _log(msg: str) -> None:
    # plain stderr, not the marian logger: fault points fire in subprocesses
    # before create_loggers, and the kill path must not depend on handler
    # state mid-teardown
    import sys
    sys.stderr.write(msg + "\n")
    sys.stderr.flush()


def fault_point(name: str) -> None:
    """Cross the named fault point. No-op (one dict lookup under a lock)
    unless armed; raises InjectedFault / sleeps / kills the process when
    the armed spec matches this hit."""
    with _STATE.lock:
        _load_env_locked()
        if name not in CATALOG:
            raise FaultSpecError(f"fault_point({name!r}) is not in the "
                                 f"faultpoints.CATALOG")
        n = _STATE.hits.get(name, 0) + 1
        _STATE.hits[name] = n
        spec = _STATE.specs.get(name)
        if spec is None or not spec.matches(n):
            return
        seed = _STATE.seed
    # act OUTSIDE the lock: hang must not serialize every other fault
    # point behind a sleeping thread, and kill flushes stderr first
    if spec.mode == "prob":
        r = random.Random(f"{seed}:{name}:{n}").random()
        if r >= float(spec.arg or 0.0):
            return
        _run_hooks(_FIRE_HOOKS, name, "prob", n)
        _log(f"FAULTPOINT {name} hit {n}: injected failure (prob)")
        raise InjectedFault(f"injected fault at {name} (hit {n}, prob)")
    _run_hooks(_FIRE_HOOKS, name, spec.mode, n)
    if spec.mode == "fail":
        _log(f"FAULTPOINT {name} hit {n}: injected failure")
        raise InjectedFault(f"injected fault at {name} (hit {n})")
    if spec.mode == "hang":
        secs = float(spec.arg if spec.arg is not None else 3600.0)
        _log(f"FAULTPOINT {name} hit {n}: hanging {secs}s")
        time.sleep(secs)  # mtlint: ok -- hang mode IS the deliberate stall being drilled (watchdog food)
        return
    if spec.mode == "kill":
        _log(f"FAULTPOINT {name} hit {n}: killing process "
             f"(exit {FAULT_EXIT_CODE})")
        # last words: let the flight recorder (obs/flight.py) snapshot
        # the span ring before the simulated SIGKILL erases it
        _run_hooks(_KILL_HOOKS, name, n)
        os._exit(FAULT_EXIT_CODE)


def describe() -> Tuple[Tuple[str, str], ...]:
    """(name, description) rows of the catalog — chaos harness / docs."""
    return tuple(sorted(CATALOG.items()))
