"""Hermetic virtual-CPU platform setup, shared by tests/conftest.py and
__graft_entry__.dryrun_multichip.

The deployment environment's sitecustomize pre-imports jax with
JAX_PLATFORMS=axon (a single-chip TPU tunnel whose health must not affect
CPU-only code paths), so "run on N virtual CPU devices" takes more than env
vars: the platform must be forced through jax.config (the env var was read
at import time), the axon/tpu backend factories dropped, and — if any client
was already created in this process — the backends and dispatch caches
cleared so the CPU client is rebuilt with the requested device count.
"""

from __future__ import annotations

import os
import re


def force_cpu_devices(n_devices: int):
    """Force jax onto a CPU platform with at least ``n_devices`` devices.

    Safe to call whether or not jax backends were already initialized.
    Returns the jax module. Raises RuntimeError if the platform cannot be
    provisioned (never silently under-provisions — a 1-device run must not
    report success for an 8-device request).
    """
    # Honor a larger preexisting override (e.g. a developer running the
    # suite at 16 devices) — only ever grow the count.
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    want = max(n_devices, int(m.group(1)) if m else 0)
    flag = f"--xla_force_host_platform_device_count={want}"
    if m:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_ENABLE_X64", "0")

    import jax

    # Pallas registers MLIR lowerings for the "tpu" platform at import time,
    # which needs the tpu backend factory still registered — import BEFORE
    # dropping the factories (kernels then run in interpret mode on CPU).
    # Broad except: an experimental plugin's registration failure must not
    # take down CPU-only runs — pallas is simply unavailable then.
    try:
        import jax.experimental.pallas  # noqa: F401
        import jax.experimental.pallas.tpu  # noqa: F401
    except Exception:
        pass

    import jax._src.xla_bridge as xb

    if xb.backends_are_initialized():
        devs = jax.devices()
        if devs and devs[0].platform == "cpu" and len(devs) >= n_devices:
            return jax  # already satisfied — don't discard jit caches
        # Public API: clears backend clients AND the dispatch/pjit caches
        # that hold references to the old client (the private
        # xb._clear_backends alone leaves get_backend's memo populated).
        import jax.extend.backend as eb
        eb.clear_backends()

    jax.config.update("jax_platforms", "cpu")
    for _plugin in ("axon", "tpu"):
        xb._backend_factories.pop(_plugin, None)
    # XLA_FLAGS may already have been parsed by an earlier client creation;
    # the config state is the reliable knob (its validator only rejects
    # changes while backends are initialized, and we just cleared them).
    # Older jax (< 0.5) has no jax_num_cpu_devices config — there the
    # XLA_FLAGS value set above is re-read at client creation, and the
    # device-count check below still catches under-provisioning.
    if getattr(jax.config, "jax_num_cpu_devices", want) < want:
        jax.config.update("jax_num_cpu_devices", want)

    devs = jax.devices()
    if len(devs) < n_devices or devs[0].platform != "cpu":
        raise RuntimeError(
            f"hermetic CPU setup failed: got {len(devs)} "
            f"{devs[0].platform if devs else '?'} devices, "
            f"need {n_devices} cpu devices")
    return jax


def watchdog_devices(timeout_s: int = 120, label: str = "bench",
                     on_timeout=None):
    """jax.devices() with a hard watchdog: the axon TPU tunnel can hang
    device enumeration forever during outages, in a native RPC wait that
    starves signal handlers — only a timer thread + os._exit gets out.
    Returns the device list or exits the process with code 3.
    `on_timeout` (optional) runs just before the exit and may return an
    exit code to use instead (bench uses this to emit a last-known-good
    stale row so the driver's artifact is never null during an outage)."""
    import os
    import sys
    import threading

    def _die():
        print(f"{label}: TPU device enumeration hung >{timeout_s}s "
              f"(tunnel outage?) — aborting", file=sys.stderr, flush=True)
        code = 3
        if on_timeout is not None:
            try:
                rc = on_timeout()
                if isinstance(rc, int):
                    code = rc
            except Exception as e:  # the watchdog must still exit
                print(f"{label}: on_timeout hook failed: {e}",
                      file=sys.stderr, flush=True)
        os._exit(code)

    timer = threading.Timer(timeout_s, _die)
    timer.daemon = True
    timer.start()
    import jax
    devs = jax.devices()
    timer.cancel()
    return devs
