"""Runtime lockdep witness: the dynamic half of mtlint's lock analysis.

The static side (marian_tpu/analysis/callgraph.py + the lock-order /
lock-blocking rule families) reasons about ``with self._lock:`` blocks it
can SEE. Its documented blind spots — calls through locals bound to
callables, ``lock.acquire()`` outside a ``with``, dynamic dispatch the
type inference cannot resolve — are exactly where a real deadlock would
hide from it. This module keeps the static model honest the same way
``MARIAN_FAULTS`` keeps the crash-safety story honest (PR 9): measure the
real thing and cross-check.

Every lock in the threaded layers is created through :func:`make_lock` /
:func:`make_rlock` with its STATIC identity as the name — the
``<OwningClass>.<attr>`` (or ``<module>.<NAME>``) string the call-graph
builder derives for the same declaration site; the MT-LOCK-NAME rule
fails the build if the two ever disagree. With ``MARIAN_LOCKDEP=1`` in
the environment (read at lock-construction time; the tier-1 serving +
lifecycle suites set it) each returned lock is a thin instrumented
wrapper that records, per thread, the order in which named locks are
acquired: holding A while acquiring B records the edge A→B, exactly the
relation the static lock-order graph models. Reentrant re-acquisition of
the same NAME records nothing — class-level identity is what the static
graph uses, so instance-vs-instance distinctions are out of scope on
both sides, symmetrically.

The witness verdict (:func:`check_against_static`, asserted at the end
of the tier-1 serving and lifecycle suites, and printed loudly at
process exit for manual runs):

- an observed acquisition edge absent from the static graph → the static
  model has a blind spot; FAIL (extend callgraph.py, do not baseline);
- an observed lock name the static graph never discovered → same;
- a cycle in the observed edges → an actually-interleavable deadlock;
  FAIL regardless of what the static graph thinks.

Without ``MARIAN_LOCKDEP=1`` the factories return plain
``threading.Lock``/``RLock`` objects — zero overhead, nothing recorded.
Stdlib-only, imports nothing from the analyzed layers (common/ is below
everything that locks), so arming it can never change import order.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Dict, List, Optional, Set, Tuple

ENV_VAR = "MARIAN_LOCKDEP"


def enabled() -> bool:
    return os.environ.get(ENV_VAR, "") == "1"


# -- the observed model ------------------------------------------------------
# Guarded by _WITNESS_LOCK (a plain lock, deliberately NOT witnessed:
# it is acquired while arbitrary witnessed locks are held and would
# otherwise show up as a spurious *→witness edge on every first
# acquisition). Per-thread held stacks live in TLS and need no lock.

_WITNESS_LOCK = threading.Lock()
_EDGES: Dict[Tuple[str, str], str] = {}     # (held, acquired) -> thread name
_NODES: Set[str] = set()
_TLS = threading.local()
_EXIT_HOOKED = False


def _stack() -> List[Tuple[str, int]]:
    """Per-thread held stack of (static name, id(inner lock)). The name
    feeds the edge graph (one node per static identity, like the static
    model); the instance id keys behavior-changing checks — two
    INSTANCES of the same class's lock may legally nest."""
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def _record_acquire(name: str, inner_id: int) -> None:
    st = _stack()
    if any(n == name for n, _ in st):
        # same static identity already held (true reentrant re-acquire,
        # or a sibling instance of the same class): the static
        # name-graph has ONE node per identity, where this is
        # edge-free — recording held->name would invent a reverse edge
        # (and a false cycle) for the documented-legal RLock re-entry
        st.append((name, inner_id))
        return
    fresh = [(held, name) for held, _ in st
             if held != name and (held, name) not in _EDGES]
    if fresh or name not in _NODES:
        thread = threading.current_thread().name
        with _WITNESS_LOCK:
            _NODES.add(name)
            for e in fresh:
                _EDGES.setdefault(e, thread)
    st.append((name, inner_id))


def _record_release(name: str, inner_id: int) -> None:
    st = _stack()
    for i in range(len(st) - 1, -1, -1):    # innermost reentrant hold first
        if st[i] == (name, inner_id):
            del st[i]
            return
    # plain threading.Lock PERMITS releasing on a thread that never
    # acquired — but that breaks the per-thread held-stack model (the
    # acquirer's stack would keep the lock forever and every later
    # acquisition there records phantom edges). The witness's job is to
    # keep models honest: fail loudly instead of silently corrupting.
    raise RuntimeError(
        f"lockdep: {name!r} released on thread "
        f"{threading.current_thread().name!r}, which does not hold it — "
        f"cross-thread release breaks the per-thread acquisition-order "
        f"model; release on the acquiring thread (or don't use this lock "
        f"as a signal)")


class _WitnessedLock:
    """threading.Lock/RLock wrapper recording acquisition-order edges.

    Supports the full surface this tree uses: ``with``, explicit
    ``acquire``/``release`` (timeouts included — an edge is recorded only
    on a SUCCESSFUL acquire), and ``locked()`` where the inner lock has
    it. Releasing on a thread that never acquired (legal for a plain
    Lock, poison to the per-thread held-stack model) raises — after the
    inner lock is actually released."""

    __slots__ = ("_name", "_inner", "_reentrant")

    def __init__(self, name: str, inner, reentrant: bool = False):
        self._name = name
        self._inner = inner
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking and timeout < 0 and not self._reentrant \
                and any(i == id(self._inner) for _, i in _stack()):
            # an INDEFINITELY-blocking re-acquire of THIS plain Lock
            # (instance-keyed: a sibling instance of the same class may
            # legally nest) by the thread that already holds it can
            # NEVER succeed — fail loudly instead of hanging the
            # process (static analogue: callgraph.self_deadlocks /
            # MT-LOCK-ORDER). A timed acquire is recoverable (False
            # after the timeout) and passes through unchanged — the
            # witness must not alter program behavior beyond
            # observation.
            raise RuntimeError(
                f"lockdep: blocking re-acquire of non-reentrant lock "
                f"{self._name!r} on thread "
                f"{threading.current_thread().name!r}, which already "
                f"holds it — guaranteed self-deadlock")
        got = self._inner.acquire(blocking, timeout)
        if got:
            _record_acquire(self._name, id(self._inner))
        return got

    def release(self) -> None:
        self._inner.release()     # first: a witness refusal (cross-thread
        _record_release(self._name, id(self._inner))
        # ^ after the real release: a witness refusal must not leave the
        #   inner lock held

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "_WitnessedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover — debugging nicety
        return f"<lockdep {self._name} wrapping {self._inner!r}>"


def make_lock(name: str):
    """A ``threading.Lock`` named with its static lock-graph identity
    (``Class.attr`` / ``module.NAME``); witnessed under MARIAN_LOCKDEP=1."""
    if not enabled():
        return threading.Lock()
    _hook_exit_report()
    return _WitnessedLock(name, threading.Lock())


def make_rlock(name: str):
    """Reentrant variant of :func:`make_lock` (same-name re-acquisition
    records no edge, matching the static graph's reentrancy rule)."""
    if not enabled():
        return threading.RLock()
    _hook_exit_report()
    return _WitnessedLock(name, threading.RLock(), reentrant=True)


# -- inspection / verdict ----------------------------------------------------

def observed_edges() -> Dict[Tuple[str, str], str]:
    with _WITNESS_LOCK:
        return dict(_EDGES)


def observed_nodes() -> Set[str]:
    with _WITNESS_LOCK:
        return set(_NODES)


def reset() -> None:
    """Forget everything observed so far (tests)."""
    with _WITNESS_LOCK:
        _EDGES.clear()
        _NODES.clear()


def observed_cycles() -> List[List[str]]:
    """Elementary cycles among the observed edges (normally none — a
    cycle here is a deadlock two threads can actually interleave into).
    Uses the SAME cycle finder as the static graph (lazy import keeps
    the runtime layer free of analysis imports unless asked)."""
    from ..analysis.callgraph import elementary_cycles
    adj: Dict[str, List[str]] = {}
    for a, b in observed_edges():
        adj.setdefault(a, []).append(b)
    return elementary_cycles(adj)


def check(static_nodes: Set[str],
          static_edges: Set[Tuple[str, str]]) -> List[str]:
    """Violations of the static model by what actually ran. Empty list =
    the static lock-order graph covered every observed behavior."""
    violations: List[str] = []
    for name in sorted(observed_nodes()):
        if name not in static_nodes:
            violations.append(
                f"observed lock {name!r} is unknown to the static graph — "
                f"callgraph.py did not discover its declaration (or its "
                f"lockdep name is stale; MT-LOCK-NAME should have caught "
                f"that)")
    for (a, b), thread in sorted(observed_edges().items()):
        if (a, b) not in static_edges:
            violations.append(
                f"observed acquisition edge {a} -> {b} (first seen on "
                f"thread {thread!r}) is absent from the static lock-order "
                f"graph — a blind spot in callgraph.py's model; extend the "
                f"analysis, do not baseline this")
    for cyc in observed_cycles():
        ring = " -> ".join(cyc + [cyc[0]])
        violations.append(
            f"observed lock-order CYCLE {ring}: two threads can deadlock "
            f"by interleaving these acquisition orders")
    return violations


def check_against_static(root) -> List[str]:
    """:func:`check` against the static graph built from the repo at
    ``root`` (the cross-check the tier-1 serving/lifecycle suites assert
    on). The analysis layer is stdlib-only, so this never imports jax."""
    from ..analysis.callgraph import static_lock_graph
    nodes, edges = static_lock_graph(root)
    return check(nodes, edges)


def _find_root() -> Optional[str]:
    cur = os.path.dirname(os.path.abspath(__file__))
    for _ in range(6):
        if os.path.exists(os.path.join(cur, "pyproject.toml")):
            return cur
        cur = os.path.dirname(cur)
    return None


def _exit_report() -> None:  # pragma: no cover — exercised via subprocess
    """Loud stderr report at process exit for manual MARIAN_LOCKDEP=1
    runs. The enforcing check is the in-suite assertion (tier-1 serving +
    lifecycle); at exit it is too late to fail anything politely, so this
    prints and leaves the exit code alone."""
    if not observed_nodes():
        return
    root = _find_root()
    if root is None:
        return
    try:
        violations = check_against_static(root)
    except Exception as e:  # noqa: BLE001 — a report must not mask the exit
        import sys
        sys.stderr.write(f"MARIAN-LOCKDEP: exit cross-check failed to "
                         f"run: {e}\n")
        return
    if violations:
        import sys
        sys.stderr.write("MARIAN-LOCKDEP: the runtime witness observed "
                         "behavior the static lock-order graph does not "
                         "model:\n")
        for v in violations:
            sys.stderr.write(f"MARIAN-LOCKDEP:   {v}\n")


def _hook_exit_report() -> None:
    global _EXIT_HOOKED
    if not _EXIT_HOOKED:
        _EXIT_HOOKED = True
        atexit.register(_exit_report)
