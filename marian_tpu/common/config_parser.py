"""Marian-compatible configuration surface: YAML config files + CLI overrides.

TPU-native rebuild of reference src/common/config_parser.cpp ::
ConfigParser::parseOptions and src/common/cli_wrapper.cpp. Flag NAMES and
semantics follow Marian so existing Marian command lines / config.yml files run
unmodified (north-star requirement); the implementation is plain argparse+yaml.

Precedence (same as Marian): defaults < config file(s) < CLI flags.
``--dump-config [minimal|expand]`` prints the effective config and exits.
Aliases (``--task transformer-big``) expand to canonical hyperparameter sets
(reference: src/common/aliases.cpp) before user overrides are applied.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import re
import sys
from typing import Any, Dict, List, Optional, Sequence

import yaml

from .options import Options
from .aliases import ALIASES, expand_aliases

# ---------------------------------------------------------------------------
# Flag table. Each entry: (name, type, default, help, group)
# type: bool flags are implicit-true switches with optional value, like CLI11.
# A default of None means "unset" (Options.has() is False) unless the mode
# defaults below fill it in.
# ---------------------------------------------------------------------------

F = dataclasses.make_dataclass("F", ["name", "type", "default", "help", "group", "nargs"])


def _f(name, type_, default, help_, group, nargs=None):
    return F(name, type_, default, help_, group, nargs)


_COMMON = [
    _f("config", str, None, "Paths to YAML config file(s); later files override earlier", "general", "+"),
    _f("workspace", int, -1, "Device workspace hint in MB (XLA manages memory; kept for CLI compat)", "general"),
    _f("log", str, None, "Log to file in addition to stderr", "general"),
    _f("log-level", str, "info", "trace/debug/info/warn/error/critical/off", "general"),
    _f("log-time-zone", str, "", "Time zone for log timestamps", "general"),
    _f("quiet", bool, False, "Suppress all logging to stderr", "general"),
    _f("quiet-translation", bool, False, "Suppress logging for translation", "general"),
    _f("seed", int, 0, "RNG seed; 0 means use wall-clock", "general"),
    _f("check-nan", bool, False, "Check gradients for NaN/inf (jax_debug_nans)", "general"),
    _f("interpolate-env-vars", bool, False, "Interpolate ${ENV_VAR} in config/paths", "general"),
    _f("relative-paths", bool, False, "Paths in configs are relative to the config file", "general"),
    _f("dump-config", str, None, "Dump effective config and exit: full/minimal/expand", "general"),
    _f("sigterm", str, "save-and-exit", "SIGTERM behavior: save-and-exit or exit-immediately", "general"),
    _f("profile", str, None, "Capture a jax.profiler device trace to this directory around a training-update window (TPU extension; view with tensorboard)", "general", "?"),
    _f("profile-server", int, 0, "Start a live jax.profiler server on this port (0 = off): attach TensorBoard's profile tab or xprof to a RUNNING training job and capture on demand (TPU extension; SURVEY tracing row)", "general"),
    _f("profile-start", int, 10, "First update of the profiler trace window", "general"),
    _f("profile-updates", int, 5, "Number of updates to trace", "general"),
    _f("dump-hlo", str, None, "Write jaxpr + optimized HLO of the compiled train step to this path prefix and continue (graph-dump debugging equivalent)", "general"),
    _f("authors", bool, False, "Print list of authors and exit", "general"),
    _f("cite", bool, False, "Print citation and exit", "general"),
    _f("build-info", str, None, "Print build info and exit", "general"),
    _f("version", bool, False, "Print version and exit", "general"),
]

_MODEL = [
    _f("model", str, "model.npz", "Path prefix for model to be saved/resumed", "model"),
    _f("pretrained-model", str, None, "Initialize weights from this model", "model"),
    _f("ignore-model-config", bool, False, "Ignore the config embedded in the model file", "model"),
    _f("type", str, "amun", "Model type: transformer, s2s, nematus, amun, multi-s2s, char-s2s, multi-transformer, bert, bert-classifier, transformer-lm", "model"),
    _f("dim-vocabs", int, [0, 0], "Maximum vocabulary sizes (0 = from vocab file)", "model", "+"),
    _f("dim-emb", int, 512, "Embedding vector size", "model"),
    _f("factors-dim-emb", int, 0, "Embedding size of factors (0 = sum combine)", "model"),
    _f("factors-combine", str, "sum", "How to combine factor embeddings: sum or concat", "model"),
    _f("lemma-dim-emb", int, 0, "Re-embedding dimension of lemma in factors", "model"),
    _f("lemma-dependency", str, "", "Factor-prediction dependency mechanism (collapsed into --lemma-dim-emb re-embedding; see flag audit)", "model"),
    _f("output-omit-bias", bool, False, "Output (logits) projection without a bias term", "model"),
    _f("dim-rnn", int, 1024, "RNN state size", "model"),
    _f("char-stride", int, 5, "Width of max-pooling layer after convolution layer in char-s2s model", "model"),
    _f("char-highway", int, 4, "Number of highway network layers after max-pooling in char-s2s model", "model"),
    _f("enc-type", str, "bidirectional", "Encoder type: bidirectional, bi-unidirectional, alternating", "model"),
    _f("enc-cell", str, "gru", "Encoder cell: gru, lstm, ssru, gru-nematus", "model"),
    _f("enc-cell-depth", int, 1, "Cells per encoder transition (deep transition)", "model"),
    _f("enc-depth", int, 1, "Encoder layers", "model"),
    _f("dec-cell", str, "gru", "Decoder cell: gru, lstm, ssru, gru-nematus", "model"),
    _f("dec-cell-base-depth", int, 2, "Cells in first decoder transition (incl. attention cell)", "model"),
    _f("dec-cell-high-depth", int, 1, "Cells in higher decoder transitions", "model"),
    _f("dec-depth", int, 1, "Decoder layers", "model"),
    _f("skip", bool, False, "Residual/skip connections in RNN layers", "model"),
    _f("layer-normalization", bool, False, "Layer normalization in RNN cells", "model"),
    _f("right-left", bool, False, "Train right-to-left model", "model"),
    _f("input-types", str, [], "Input types per stream: sequence, class, alignment, weight", "model", "*"),
    _f("tied-embeddings", bool, False, "Tie target embeddings and output layer", "model"),
    _f("tied-embeddings-src", bool, False, "Tie source and target embeddings", "model"),
    _f("tied-embeddings-all", bool, False, "Tie all embeddings and output layer", "model"),
    # transformer
    _f("transformer-heads", int, 8, "Number of attention heads", "model"),
    _f("transformer-dim-ffn", int, 2048, "FFN hidden size", "model"),
    _f("transformer-decoder-dim-ffn", int, 0, "Decoder FFN hidden size (0 = transformer-dim-ffn)", "model"),
    _f("transformer-ffn-depth", int, 2, "FFN depth (number of linear layers)", "model"),
    _f("transformer-decoder-ffn-depth", int, 0, "Decoder FFN depth (0 = transformer-ffn-depth)", "model"),
    _f("transformer-ffn-activation", str, "swish", "relu, swish, gelu", "model"),
    _f("transformer-no-projection", bool, False, "Omit output projection in MHA", "model"),
    _f("transformer-pool", bool, False, "Pooler instead of self-attention (experimental)", "model"),
    _f("transformer-dim-aan", int, 2048, "AAN FFN hidden size", "model"),
    _f("transformer-aan-depth", int, 2, "Depth of the AAN position-wise FFN", "model"),
    _f("transformer-aan-activation", str, "swish", "Activation of the AAN FFN: swish | relu | gelu", "model"),
    _f("transformer-aan-nogate", bool, False, "Disable the AAN input/forget gate", "model"),
    _f("transformer-decoder-autoreg", str, "self-attention", "self-attention, average-attention, rnn", "model"),
    _f("transformer-flash-attention", str, "auto", "Pallas blockwise attention kernel: auto, on, off (TPU extension)", "model"),
    _f("transformer-packed-attention", str, "auto", "Pallas head-packed short-sequence attention kernel, fills the 128x128 MXU tile with 128//dim-head heads per pass: auto (TPU only), on, off (TPU extension)", "model"),
    _f("transformer-fused-decode-attention", str, "auto", "Pallas fused beam-gather + cache-update + attention decode step: auto (TPU only), on, off (TPU extension)", "model"),
    _f("fused-ce", str, "auto", "Streaming fused softmax cross-entropy kernel (logit blocks stay in VMEM): auto (TPU only), on, off (TPU extension)", "model"),
    _f("transformer-tied-layers", int, [], "Tie decoder layers to these encoder layers", "model", "*"),
    _f("transformer-guided-alignment-layer", str, "last", "Decoder layer for guided alignment", "model"),
    _f("transformer-preprocess", str, "", "Per-sublayer preprocess ops: d=dropout, a=add(residual), n=layernorm", "model"),
    _f("transformer-postprocess", str, "dan", "Per-sublayer postprocess ops", "model"),
    _f("transformer-postprocess-emb", str, "d", "Embedding postprocess ops", "model"),
    _f("transformer-postprocess-top", str, "", "Final decoder-top postprocess ops", "model"),
    _f("transformer-train-position-embeddings", bool, False, "Learned positional embeddings", "model"),
    _f("transformer-depth-scaling", bool, False, "Depth-scaled parameter initialization", "model"),
    _f("transformer-rnn-projection", bool, False, "Projection after decoder RNN (autoreg=rnn)", "model"),
    _f("max-length", int, 50, "Maximum sentence length (training crop/skip; decode cap)", "model"),
    _f("max-length-crop", bool, False, "Crop instead of skipping over-long sentences", "model"),
    _f("bert-mask-symbol", str, "[MASK]", "BERT masking symbol", "model"),
    _f("bert-sep-symbol", str, "[SEP]", "BERT separator symbol", "model"),
    _f("bert-class-symbol", str, "[CLS]", "BERT class symbol", "model"),
    _f("bert-masking-fraction", float, 0.15, "BERT masking fraction", "model"),
    _f("bert-train-type-embeddings", bool, True, "Train sentence-type embeddings", "model"),
    _f("bert-type-vocab-size", int, 2, "Type vocab size", "model"),
    # precision
    _f("precision", str, ["float32", "float32"], "Training precisions: compute, optimizer accumulation (float16 is mapped to bfloat16 on TPU)", "model", "+"),
    _f("cost-scaling", str, [], "Dynamic loss scaling (mostly unneeded in bf16; kept for parity)", "model", "*"),
    _f("gradient-checkpointing", bool, False, "Rematerialization (jax.checkpoint) to save memory", "model"),
    # tpu-specific (new, no Marian equivalent)
    _f("attention-kernel", str, "auto", "Attention impl: auto, dense, flash (Pallas)", "model"),
    _f("auto-tune", bool, False, "Time implementation alternatives (dense vs Pallas flash attention crossover) on the current backend and bind the fastest, like the reference's AutoTuner (TPU extension)", "model"),
    _f("sequence-parallel", str, "none", "Sequence/context parallelism over the 'seq' mesh axis: none, ring (K/V blocks rotate via ppermute), ulysses (all-to-all head<->seq swap) (TPU extension)", "model"),
    _f("scan-layers", bool, False, "lax.scan over layer stack: compile time O(1) in depth, but measured 25-33% slower per step than unrolled on TPU v5e (r4 bench scan A/B — XLA schedules/fuses across unrolled layers, not across a while-loop boundary). Default off; turn on for very deep stacks or compile-time-bound jobs. Auto-falls back for tied layers/alignment/int8; implied ON by --stacked-params and pipe-sharded meshes (they consume the stacked layout)", "model"),
    _f("stacked-params", bool, False, "Store transformer layer weights depth-stacked [L,...] during training: the --scan-layers forward consumes the stack directly, removing its per-step restack (one full HBM read+write of every layer weight per micro-batch). Implied by meshes with pipe>1; checkpoints stay Marian-flat", "model"),
    _f("transformer-moe-experts", int, 0, "Mixture-of-Experts FFN: number of experts (0 = dense FFN; TPU extension, shards over the 'expert' mesh axis)", "model"),
    _f("transformer-moe-top-k", int, 2, "MoE router top-k (1 = Switch, 2 = GShard)", "model"),
    _f("moe-capacity-factor", float, 1.25, "MoE expert capacity factor (tokens beyond capacity fall through the residual)", "model"),
    _f("moe-aux-weight", float, 0.01, "Weight of the MoE load-balancing auxiliary loss", "training"),
]

_TRAINING = [
    _f("task", str, None, "Shortcut for a predefined hyperparameter bundle: transformer-base, transformer-big, transformer-base-prenorm, transformer-big-prenorm", "training", "?"),
    _f("cost-type", str, "ce-sum", "ce-mean, ce-mean-words, ce-sum, perplexity", "training"),
    _f("multi-loss-type", str, "sum", "sum, scaled, mean", "training"),
    _f("unlikelihood-loss", bool, False, "Use word-level weights as indicators for unlikelihood loss", "training"),
    _f("overwrite", bool, False, "Do not create checkpoints per save, overwrite model file", "training"),
    _f("no-reload", bool, False, "Do not load existing model file before training", "training"),
    _f("train-sets", str, [], "Paths to training corpora (source target ...)", "training", "*"),
    _f("vocabs", str, [], "Paths to vocabulary files; created if missing", "training", "*"),
    _f("sentencepiece-alphas", float, [], "Subword-regularization sampling alphas per stream", "training", "*"),
    _f("sentencepiece-options", str, "", "Options passed to on-the-fly SentencePiece training", "training"),
    _f("sentencepiece-max-lines", int, 2000000, "Max lines for SentencePiece vocab training", "training"),
    _f("after-epochs", int, 0, "Stop after this many epochs (0 = no limit); same as --after Ne", "training"),
    _f("after-batches", int, 0, "Stop after this many updates (0 = no limit)", "training"),
    _f("after", str, "0e", "Stop after: e.g. 10e (epochs), 100Ku (updates), 1Gt (labels)", "training"),
    _f("disp-freq", str, "1000u", "Display information every N updates/epochs/labels", "training"),
    _f("disp-first", int, 0, "Display information for the first N updates", "training"),
    _f("disp-label-counts", bool, True, "Display label counts in progress", "training"),
    _f("save-freq", str, "10000u", "Save model every N", "training"),
    _f("normalize-gradient", bool, False, "Additionally divide the gradient by the batch's target-word count", "training"),
    _f("check-gradient-nan", bool, False, "Skip the whole update (params + optimizer state unchanged) when the gradient norm is non-finite", "training"),
    _f("dynamic-gradient-scaling", str, [], "FACTOR ['log']: scale outlier gradients down to FACTOR x the windowed average (log-)norm", "training", "*"),
    _f("gradient-norm-average-window", int, 100, "Window for the running gradient-norm average used by --dynamic-gradient-scaling", "training"),
    _f("optimizer-state-dtype", str, "float32", "Storage dtype for Adam's first moment: float32 | bfloat16 (halves m's HBM footprint and per-step traffic; math stays f32, v stays f32; beyond the reference)", "training"),
    _f("gradient-dtype", str, "float32", "Dtype gradients are produced, reduce-scattered, and stored in until the optimizer's in-register f32 upcast: float32 | bfloat16 (halves backward gradient HBM writes and ZeRO-1 collective bytes — the analogue of Marian's fp16 gradient communication; requires matching bfloat16 compute --precision, otherwise ignored with a warning). Note: the logits backward always rounds its cotangent through the COMPUTE dtype (ops/ops.py logits_matmul — the bf16 MXU-rate fix), so float32 here does NOT make bf16-compute backward passes fully f32; see docs/PERFORMANCE.md", "training"),
    _f("async-save", bool, False, "Overlap checkpoint writes with training: device snapshots on the train thread, numpy+disk IO on a background worker (beyond the reference, whose Train::save blocks the update loop). Needs transient HBM headroom for one device copy of params+EMA+optimizer state at save time", "training"),
    _f("keep-checkpoint-bundles", int, 3, "Crash-safe checkpointing: keep the last N committed checkpoint bundles under <model>.bundles/ (each bundle is the atomic, checksummed model+optimizer+progress unit restore validates and falls back across; see docs/ROBUSTNESS.md). Disk cost is ~N x checkpoint size; minimum 1 (TPU extension)", "training"),
    _f("compact-transfer", bool, True, "Ship training batches as uint16 tokens + per-row lengths instead of int32 ids + float masks (~4x less host-to-device traffic per step; ids/masks are rebuilt inside the jitted step — beyond the reference)", "training"),
    _f("tensorboard", str, None, "Write train/valid scalars (cost, words/s, learn rate, validation metrics) as TensorBoard events to this directory (beyond the reference, which logs text only)", "training", "?"),
    _f("logical-epoch", str, ["1e"], "Logical epoch spec, e.g. 1Gt", "training", "+"),
    _f("max-length-factor", float, 3.0, "Max target length factor of source length while decoding", "training"),
    _f("shuffle", str, "data", "data, batches, none", "training"),
    _f("no-shuffle", bool, False, "Disable shuffling (= --shuffle none)", "training"),
    _f("no-restore-corpus", bool, False, "Do not restore corpus position on resume", "training"),
    _f("tempdir", str, "/tmp", "Temporary directory for shuffling", "training"),
    _f("sqlite", str, None, "Keep corpus in an on-disk database for O(1) mid-epoch resume", "training", "?"),
    _f("sqlite-drop", bool, False, "Drop the SQLite corpus database (no-op; see flag audit)", "training"),
    _f("mini-batch-track-optimum", bool, False, "Track the optimal batch size (no-op; see flag audit)", "training"),
    _f("train-embedder-rank", str, [], "Margin-based embedder-rank training (refused; see flag audit)", "training", "*"),
    _f("tsv", bool, False, "Train sets are tab-separated files (one line carries all streams)", "training"),
    _f("tsv-fields", int, 0, "Number of TSV columns (0 = infer from --vocabs count)", "training"),
    _f("no-spm-encode", bool, False, "Input is already SentencePiece-encoded: skip encoding, split on whitespace", "training"),
    _f("input-reorder", int, [], "Permutation applied to TSV columns before they become streams, e.g. 1 0", "training", "*"),
    _f("throw-on-divergence", bool, False, "Raise (instead of logging) when the training cost goes non-finite, so orchestration restarts from the last checkpoint", "training"),
    _f("on-divergence", str, "", "Divergence policy: throw | warn | rollback. 'rollback' self-heals in-process: restore the last good checkpoint bundle, rewind the data pipeline to the bundle's corpus snapshot (past the poison window), apply --divergence-lr-backoff per retry, and give up loudly (raise) after --divergence-retries attempts. Empty derives from --throw-on-divergence: throw when set, else warn (TPU extension; see docs/ROBUSTNESS.md)", "training"),
    _f("divergence-retries", int, 3, "With --on-divergence rollback: in-process rollback attempts before giving up and raising like throw (TPU extension)", "training"),
    _f("divergence-lr-backoff", float, 0.5, "With --on-divergence rollback: multiply the learning-rate decay factor by this on each retry (compounds across retries and persists in the saved training state; 1.0 = no backoff) (TPU extension)", "training"),
    _f("divergence-skip-window", int, 10, "With --check-gradient-nan: treat this many CONSECUTIVE NaN-skipped updates as divergence, feeding --on-divergence without waiting for the display-boundary cost sync (0 = never; detection lags the hot loop by ~2 updates, not a display window) (TPU extension)", "training"),
    _f("train-stall-timeout", float, 0.0, "Training-step watchdog: when the update loop makes no progress for this many seconds (a step that never fences — wedged collective, hung data feed), dump a flight recording naming the stalled step, save a host-side diagnostic progress file, and exit with the distinct retriable code 75 so a supervisor restarts into the checkpoint-resume path (0 = off) (TPU extension)", "training"),
    _f("diverged-after", str, None, "fp16 divergence-recovery horizon (no-op; see flag audit)", "training", "?"),
    _f("custom-fallbacks", str, [], "fp16 fallback config list (no-op; see flag audit)", "training", "*"),
    _f("fp16-fallback-to-fp32", bool, False, "fp16 fallback (no-op; see flag audit)", "training"),
    _f("recover-from-fallback-after", str, None, "fp16 fallback recovery (no-op; see flag audit)", "training", "?"),
    _f("overwrite-checkpoint", bool, True, "Overwrite the single rolling checkpoint (no-op; see flag audit)", "training"),
    _f("clip-gemm", float, 0.0, "Legacy GEMM clipping (no-op; see flag audit)", "training"),
    _f("mini-batch", int, 64, "Minibatch size (sentences)", "training"),
    _f("mini-batch-words", int, 0, "Minibatch size in target labels (token budget)", "training"),
    _f("mini-batch-fit", bool, False, "Determine minibatch automatically from workspace (TPU: bucket table)", "training"),
    _f("mini-batch-fit-step", int, 10, "Step for mini-batch-fit search", "training"),
    _f("maxi-batch", int, 100, "Number of minibatches to preload and sort", "training"),
    _f("maxi-batch-sort", str, "trg", "Sorting within maxi-batch: trg, src, none", "training"),
    _f("shuffle-in-ram", bool, False, "Shuffle corpus in RAM instead of temp files", "training"),
    _f("data-threads", int, 8, "Host threads for data pipeline", "training"),
    _f("all-caps-every", int, 0, "Upper-case every Nth batch (data augmentation)", "training"),
    _f("english-title-case-every", int, 0, "Title-case every Nth batch", "training"),
    _f("mini-batch-words-ref", int, 0, "Reference batch size in words for LR auto-adjustment", "training"),
    _f("mini-batch-warmup", str, "0", "Linear batch-size warmup period", "training"),
    _f("mini-batch-track-lr", bool, False, "Adjust LR for tracked batch-size ramp", "training"),
    _f("mini-batch-round-up", bool, True, "Round up batch size for warmup", "training"),
    _f("optimizer", str, "adam", "adam, adagrad, sgd", "training"),
    _f("optimizer-params", float, [], "Optimizer hyperparameters (Adam: beta1 beta2 eps)", "training", "*"),
    _f("optimizer-delay", float, 1.0, "SGD update delay (gradient accumulation): N updates or fractional", "training"),
    _f("dispatch-window", int, 1, "Run N full optimizer updates inside one jitted dispatch (lax.scan over same-shape batches; amortizes host dispatch latency — beyond the reference, whose host loop runs per update). Requires --optimizer-delay 1", "training"),
    _f("sync-sgd", bool, False, "Synchronous SGD (the only mode on TPU; async maps to it with a warning)", "training"),
    _f("learn-rate", float, 0.0001, "Learning rate", "training"),
    _f("lr-report", bool, False, "Report learning rate in progress lines", "training"),
    _f("lr-decay", float, 0.0, "Decay factor: lr = lr * decay", "training"),
    _f("lr-decay-strategy", str, "epoch+stalled", "epoch, batches, stalled, epoch+batches, epoch+stalled", "training"),
    _f("lr-decay-start", int, [10, 1], "Decay start: [epoch, batches/stalled]", "training", "+"),
    _f("lr-decay-freq", int, 50000, "Decay frequency (strategy: batches)", "training"),
    _f("lr-decay-reset-optimizer", bool, False, "Reset optimizer state at LR decay", "training"),
    _f("lr-decay-repeat-warmup", bool, False, "Repeat warmup after decay", "training"),
    _f("lr-decay-inv-sqrt", str, ["0"], "Inverse-sqrt decay with this warmup, e.g. 16000u", "training", "+"),
    _f("lr-warmup", str, "0", "Linear LR warmup period", "training"),
    _f("lr-warmup-start-rate", float, 0.0, "Warmup start LR", "training"),
    _f("lr-warmup-cycle", bool, False, "Cyclic warmup", "training"),
    _f("lr-warmup-at-reload", bool, False, "Repeat warmup after checkpoint reload", "training"),
    _f("label-smoothing", float, 0.0, "Label smoothing epsilon", "training"),
    _f("factor-weight", float, 1.0, "Weight for loss of factors vs lemma", "training"),
    _f("clip-norm", float, 1.0, "Global gradient-norm clipping (0 = off)", "training"),
    _f("exponential-smoothing", float, 0.0, "EMA decay of parameters, e.g. 1e-4 (0 = off)", "training", "?"),
    _f("guided-alignment", str, "none", "Path to alignments or 'none'", "training"),
    _f("guided-alignment-cost", str, "ce", "ce, mse, mult", "training"),
    _f("guided-alignment-weight", float, 0.1, "Weight for guided-alignment cost", "training"),
    _f("data-weighting", str, None, "Path to per-sentence/word weight file", "training"),
    _f("data-weighting-type", str, "sentence", "sentence or word", "training"),
    _f("embedding-vectors", str, [], "Paths to pretrained embedding vectors", "training", "*"),
    _f("embedding-normalization", bool, False, "Normalize pretrained embedding vectors", "training"),
    _f("embedding-fix-src", bool, False, "Fix source embeddings", "training"),
    _f("embedding-fix-trg", bool, False, "Fix target embeddings", "training"),
    _f("quantize-bits", int, 0, "Train-time model quantization bits (0 = off)", "training"),
    _f("gradient-dropping-rate", float, 0.0, "Drop this fraction of each gradient tensor (DGC-style, with error feedback); 0 = off", "training"),
    _f("quantize-optimization-steps", int, 0, "Scale-optimization steps for quantization", "training"),
    _f("quantize-log-based", bool, False, "Log-based quantization", "training"),
    _f("quantize-biases", bool, False, "Quantize biases too", "training"),
    _f("ulr", bool, False, "Universal language representation", "training"),
    _f("ulr-query-vectors", str, "", "Path to ULR query vectors", "training"),
    _f("ulr-keys-vectors", str, "", "Path to ULR key vectors", "training"),
    _f("ulr-trainable-transformation", bool, False, "Trainable ULR transformation", "training"),
    _f("ulr-dim-emb", int, 0, "ULR embedding dim", "training"),
    _f("ulr-dropout", float, 0.0, "ULR dropout", "training"),
    _f("ulr-softmax-temperature", float, 1.0, "ULR softmax temperature", "training"),
    # dropout group
    _f("dropout-rnn", float, 0.0, "RNN state dropout", "training"),
    _f("dropout-src", float, 0.0, "Source word dropout", "training"),
    _f("dropout-trg", float, 0.0, "Target word dropout", "training"),
    _f("transformer-dropout", float, 0.0, "Dropout between transformer layers", "training"),
    _f("transformer-dropout-attention", float, 0.0, "Attention-weight dropout", "training"),
    _f("transformer-dropout-ffn", float, 0.0, "FFN dropout", "training"),
    # devices
    _f("devices", str, ["0"], "Device ids (GPU compat) or tpu:N..M mesh spec", "training", "+"),
    _f("num-devices", int, 0, "Number of devices (0 = all visible)", "training"),
    _f("data-backend", str, "python", "Batch pipeline: python, or native (C++ tokenizer+batcher, marian_tpu/native) (TPU extension)", "training"),
    _f("no-nccl", bool, False, "(GPU compat; ignored — ICI collectives are always used)", "training"),
    _f("sharding", str, "global", "Optimizer sharding domain: global (ZeRO-1 over all devices) or local", "training"),
    _f("sync-freq", str, "200u", "Param sync frequency for local sharding", "training"),
    _f("cpu-threads", int, 0, "Use CPU with this many threads (inference)", "training", "?"),
    # multi-node
    _f("multi-node", bool, False, "Multi-host training (jax.distributed)", "training"),
    _f("multi-node-overlap", bool, True, "(compat; XLA overlaps automatically)", "training"),
    _f("coordinator-address", str, None, "jax.distributed coordinator ip:port", "training"),
    _f("num-processes", int, 1, "Number of hosts (jax.distributed)", "training"),
    _f("process-id", int, 0, "This host's rank", "training"),
    # mesh axes (TPU-native extension; absent in reference)
    _f("mesh", str, [], "Mesh axes as name:size pairs, e.g. data:8 model:4 seq:2 (default: all devices on data)", "training", "*"),
]

_VALIDATION = [
    _f("valid-sets", str, [], "Paths to validation corpora", "valid", "*"),
    _f("valid-freq", str, "10000u", "Validate every N", "valid"),
    _f("valid-metrics", str, ["cross-entropy"], "cross-entropy, ce-mean-words, perplexity, bleu, bleu-detok, bleu-segmented, chrf, valid-script, translation", "valid", "+"),
    _f("valid-reset-stalled", bool, False, "Reset stalled counts on training restart", "valid"),
    _f("valid-reset-all", bool, False, "Reset all validation state on restart", "valid"),
    _f("early-stopping", int, 10, "Stop after N consecutive non-improving validations", "valid"),
    _f("early-stopping-epsilon", float, [0.0], "Minimum required improvement per metric", "valid", "+"),
    _f("early-stopping-on", str, "first", "first, all, any of valid-metrics", "valid"),
    _f("keep-best", bool, False, "Keep best model per metric", "valid"),
    _f("valid-log", str, None, "Validation log file", "valid"),
    _f("valid-max-length", int, 1000, "Max length for validation sentences", "valid"),
    _f("valid-mini-batch", int, 32, "Validation minibatch size", "valid"),
    _f("valid-script-path", str, None, "External validation script", "valid"),
    _f("valid-script-args", str, [], "Args for external validation script", "valid", "*"),
    _f("valid-translation-output", str, None, "Print validation translations to file", "valid"),
]

_TRANSLATION = [
    _f("vocabs", str, [], "Paths to vocabulary files", "translate", "*"),
    _f("mini-batch", int, 1, "Minibatch size (sentences)", "translate"),
    _f("mini-batch-words", int, 0, "Minibatch size in words", "translate"),
    _f("maxi-batch", int, 1, "Number of minibatches to preload and sort", "translate"),
    _f("maxi-batch-sort", str, "src", "Sorting within maxi-batch: src, none", "translate"),
    _f("data-threads", int, 8, "Host threads for data pipeline", "translate"),
    _f("input", str, ["stdin"], "Input file(s) or stdin", "translate", "+"),
    _f("output", str, "stdout", "Output file or stdout", "translate"),
    _f("models", str, [], "Model file(s) to ensemble", "translate", "*"),
    _f("weights", float, [], "Ensemble scorer weights", "translate", "*"),
    _f("beam-size", int, 12, "Beam size", "translate"),
    _f("normalize", float, 0.0, "Divide score by length^alpha", "translate", "?"),
    _f("word-penalty", float, 0.0, "Subtract penalty*length from score", "translate"),
    _f("allow-unk", bool, False, "Allow <unk> in output", "translate"),
    _f("allow-special", bool, False, "Allow special symbols in output", "translate"),
    _f("n-best", bool, False, "Produce n-best lists", "translate"),
    _f("word-scores", bool, False, "Print per-word scores in n-best lists", "translate"),
    _f("n-best-feature", str, "Score", "Feature name for the n-best score column", "translate"),
    _f("alignment", str, None, "Return word alignments: 0.x threshold, soft, hard", "translate", "?"),
    _f("force-decode", bool, False, "Force-decode given prefixes", "translate"),
    _f("best-deep", bool, False, "(compat)", "translate"),
    _f("output-sampling", str, [], "Sampling instead of argmax: full [temp] / topk k [temp]", "translate", "*"),
    _f("output-approx-knn", int, [], "LSH-approximated output layer: nodes, hashes", "translate", "*"),
    _f("max-length-factor-translate", float, 3.0, "(see max-length-factor)", "translate"),
    _f("skip-cost", bool, False, "Skip costly final scoring", "translate"),
    _f("shortlist", str, [], "Lexical shortlist: path [first] [best] [prune]", "translate", "*"),
    _f("port", int, 8080, "marian-server port", "translate"),
    # serving subsystem (marian_tpu/serving/ — TPU extension, no Marian
    # equivalent): continuous batching, admission control, observability
    _f("max-queue", int, 512, "marian-server admission control: maximum queued sentences before new requests are shed with an explicit !!SERVER-OVERLOADED reply (0 = unbounded, the reference's behavior) (TPU extension)", "translate"),
    _f("request-timeout", float, 0.0, "marian-server per-request deadline in seconds: expired requests get an explicit !!SERVER-TIMEOUT reply (even while queued) instead of waiting forever (0 = no deadline) (TPU extension)", "translate"),
    _f("batch-token-budget", int, 0, "marian-server continuous batching: token budget per device batch against the bucketed static-shape table (data/batch_generator buckets, so serve-time batches hit warm jit-cache shapes). Counted as real rows x bucketed width — the same --mini-batch-words semantics training uses; the realized device batch can exceed it by the row snap-up to the batch multiple. 0 = derive from mini-batch x bucketed max-length (TPU extension)", "translate"),
    _f("batching-mode", str, "request", "marian-server batching discipline: 'request' packs whole requests into device batches between decodes (the default continuous token-budget scheduler); 'iteration' moves scheduling INSIDE the decode loop over a paged KV-cache pool — sentences join a RUNNING decode at any step and leave the step they finish, admission prices queue debt in pool pages, and the headroom gauge's queue-pressure units become pages. --beam-size 1 decodes greedily; beam > 1 decodes with copy-on-write page sharing across hypotheses (full pages alias via refcounts, only partial pages copy on fork — translator/beam_iteration.py; a sentence occupies beam-size slots). Single model only; composes with a restricted option surface (validated loudly at boot; docs/DEPLOYMENT.md) (TPU extension)", "translate"),
    _f("iteration-rows", int, 32, "With --batching-mode iteration: decode slot count — the maximum concurrently decoding sentences; the per-step compiled shape rounds the OCCUPIED slot prefix up through the row-bucket table, so idle slots cost nothing compiled (TPU extension)", "translate"),
    _f("iteration-steps", int, 1, "With --batching-mode iteration: decode steps per scheduling round, run as one jitted scan. 1 = joins possible at EVERY step (pure iteration-level); >1 amortizes per-step host dispatch on host-bound backends at the cost of up to N-1 steps of join latency and a few self-fed row-steps past each EOS. Applies at ANY beam size: beam > 1 scans too under the default fused on-device merge (EOS freezing is an in-scan mask; the COW reorder is in-graph table math), while --iteration-beam-merge host pins beam rounds to single-step (the numpy merge needs the host between steps) (TPU extension)", "translate"),
    _f("iteration-beam-merge", str, "fused", "With --batching-mode iteration and beam > 1: where the k*k candidate merge runs. 'fused' (default) merges on-device — one jitted flat top-k over every live sentence plus in-graph COW page bookkeeping, one host sync per round, composes with --iteration-steps > 1; 'host' keeps the per-step numpy merge (the pre-fused A/B baseline — single-step rounds, one sync per token). Sampling and the cow=False replication baseline always run the host path (TPU extension)", "translate"),
    _f("kv-page-len", int, 16, "With --batching-mode iteration: tokens per KV-cache page. Smaller pages waste less pool on short sentences (internal fragmentation <= page_len-1 tokens/row) but grow the page table; see docs/DECODE_ROOFLINE.md r7 for the HBM-line-size trade (TPU extension)", "translate"),
    _f("kv-pool-bytes", int, 0, "With --batching-mode iteration: byte budget for the paged KV pool across all decoder layers (K+V). 0 = size the pool so every slot can hold a full --max-length row (the pool is then never the admission constraint) (TPU extension)", "translate"),
    _f("max-queue-pages", int, 0, "With --batching-mode iteration: admission bound on queued KV-pool PAGE debt — requests are shed with !!SERVER-OVERLOADED when the queue already owes this many pages (0 = 4x the pool's allocatable pages). Beam-k requests are priced at the shared-trunk steady-state holding (one trunk + k-1 extra partial pages) — an optimistic estimate, never k-times full replication; fully divergent lineages can transiently hold more, which lazy claims cover with retriable mid-decode eviction when the pool runs dry (TPU extension)", "translate"),
    _f("prefix-cache", bool, False, "With --batching-mode iteration: cross-request prefix sharing over the paged KV pool. An exact repeat of a source decoding RIGHT NOW joins as a copy-on-write follower (aliases the leader's full KV pages via refcounts, copies only the partial page, skips the encoder); a repeat of a COMPLETED decode replays it instantly, with the finished rows' pages retained by the cache and LRU-evicted under pool pressure. Deterministic decode makes warm output bitwise-identical to cold; marian_prefix_* metrics count hits/tokens saved/pages reused (docs/DEPLOYMENT.md) (TPU extension)", "translate"),
    _f("prefix-cache-entries", int, 64, "With --prefix-cache: maximum completed decodes retained (LRU); pool pressure can evict below this (TPU extension)", "translate"),
    _f("metrics-port", int, 0, "Serve Prometheus /metrics + /healthz + /readyz on this port (0 = off): queue depth, batch fill ratio, padding waste, time-to-first-batch, end-to-end latency, shed/timeout counts; train/translate emit into the same registry (TPU extension)", "translate"),
    _f("dispatch-stall-timeout", float, 0.0, "marian-server liveness watchdog: if one device batch (translate_lines call) runs longer than this many seconds, fail its requests with an explicit retriable !!SERVER-RETRY reply and move the scheduler onto a fresh device worker instead of wedging the whole serving path behind the stuck call (0 = off; set comfortably above the worst legitimate batch decode time; see docs/ROBUSTNESS.md) (TPU extension)", "translate"),
    _f("quiesce-deadline", float, 2.0, "With --batching-mode iteration and --model-watch: drain budget in seconds for a lifecycle quiesce (swap/canary/rollback). Joins pause and active decode rows drain naturally; rows still decoding at the deadline are evicted with a retriable !!SERVER-RETRY (pages freed, counted in marian_serving_quiesce_evictions_total) so a swap is never held hostage by one long sentence; the engine is re-pointed at a step boundary with an empty join set (docs/ROBUSTNESS.md) (TPU extension)", "translate"),
    _f("brownout", bool, False, "marian-server brownout ladder: under sustained overload (capacity headroom at/below --brownout-headroom, or the SLO fast-burn threshold) step through explicit degradation levels — 1 tighten per-row decode caps, 2 evict lowest-priority/longest-remaining rows with retriable !!SERVER-RETRY, 3 shed admissions below --brownout-min-priority — so high-priority traffic keeps a bounded p99 while low lanes degrade predictably; every transition is a timeline event + marian_brownout_level move (docs/ROBUSTNESS.md) (TPU extension)", "translate"),
    _f("brownout-headroom", float, 0.1, "Brownout overload signal: escalate while marian_capacity_headroom_ratio stays at or below this floor (TPU extension)", "translate"),
    _f("brownout-burn", float, 0.0, "Brownout overload signal: escalate while the SLO engine's fast-window burn rate stays at or above this (0 = use the SLO fast-burn factor when an SLO is declared, else the burn signal is off and headroom drives the ladder alone) (TPU extension)", "translate"),
    _f("brownout-hold", float, 5.0, "Seconds the overload signal must persist before the ladder escalates one level (each rung needs its own sustained hold) (TPU extension)", "translate"),
    _f("brownout-cool", float, 15.0, "Seconds of continuous health before the ladder de-escalates one level (TPU extension)", "translate"),
    _f("brownout-cap-factor", float, 0.5, "Brownout level 1: scale factor applied to NEW rows' decode caps (shorter rows claim fewer KV pages and leave sooner; possible truncation of the longest outputs is the explicit trade) (TPU extension)", "translate"),
    _f("brownout-min-priority", int, 1, "Brownout level 3: admission sheds requests whose priority lane is below this (clients set a lane with the '#priority:N' protocol header; default lane is 0) (TPU extension)", "translate"),
    _f("model-watch", float, 0.0, "marian-server zero-downtime lifecycle: poll <model>.bundles/ every N seconds for newly committed checkpoint bundles and hot-swap to them after an off-path warmup (compat check, load, jit compile, golden smoke) with no dropped requests; in-flight batches finish on the old model (0 = off; see docs/DEPLOYMENT.md) (TPU extension)", "translate"),
    _f("canary-fraction", float, 0.0, "With --model-watch: route this fraction of device batches to a freshly warmed candidate (state 'canary') before promoting it to live; per-version error/latency metrics (marian_model_*) record both sides, and a canary whose failure rate or p99 regresses is auto-rolled-back (0 = swap immediately after warmup) (TPU extension)", "translate"),
    _f("rollback-error-rate", float, 0.5, "With --model-watch: auto-rollback threshold on the windowed device-batch failure rate — a canary (or a freshly swapped live version with a retained rollback target) exceeding this rate is rolled back to the previous live version (docs/DEPLOYMENT.md) (TPU extension)", "translate"),
    _f("rollback-p99-factor", float, 0.0, "With --model-watch: auto-rollback a canary whose p99 batch latency exceeds this factor x the live version's p99 (both over a recent-sample window; 0 = latency check off) (TPU extension)", "translate"),
    _f("canary-min-batches", int, 8, "With --model-watch and --canary-fraction > 0: promote the canary to live after this many canary batches without tripping a rollback threshold (TPU extension)", "translate"),
    _f("warmup-golden", str, "", "With --model-watch: file of golden source sentences (one per line) each candidate model must translate during off-path warmup before it can serve — forces jit compilation of the serving shapes and proves the checkpoint decodes (empty = a built-in probe set) (TPU extension)", "translate"),
    # observability (marian_tpu/obs/ — docs/OBSERVABILITY.md)
    _f("trace", bool, False, "Enable the request-scoped span tracer: every request's path (ingest, admission, queue wait, batch formation, dispatch, translate, reply write — and train-loop phases) is recorded into a bounded in-memory ring, exported as Chrome trace JSON at /tracez on the metrics port (open in Perfetto). Off = zero overhead: no ring allocation, no lock on the hot path (TPU extension)", "translate"),
    _f("trace-ring", int, 4096, "With --trace: span ring capacity — how many most-recent spans /tracez and flight-recorder dumps can see (TPU extension)", "translate"),
    _f("trace-dump", str, "", "Arm the crash flight recorder (implies --trace): on a dispatch-watchdog trip, a canary/live auto-rollback, a poison-request isolation, or an injected MARIAN_FAULTS kill, snapshot the span ring + event timeline + /metrics to a timestamped JSON file in this directory (docs/OBSERVABILITY.md runbook) (TPU extension)", "translate"),
    _f("trace-sync-phases", bool, False, "Honest train-loop phase timing: drain the device (block_until_ready) at every StepTimer phase boundary so async dispatch cannot shift device seconds into whichever later phase blocks first. Serializes host and device — a diagnosis mode, not a throughput config (TPU extension)", "translate"),
    _f("perf-accounting", bool, True, "Live performance & capacity plane (obs/perf.py): per-batch chip-seconds/token, tokens/s, MFU-vs-analytic-roofline and capacity-headroom gauges on /metrics, plus per-shape-bucket jit-compile telemetry (boot/swap warmup vs steady-state recompiles — a steady-state recompile is a latency incident and lands on the event timeline). One counter update per device batch; `--perf-accounting false` restores the strictly lock-free batch path (TPU extension)", "translate"),
    _f("warmup-on-boot", bool, False, "marian-server: golden-warm every serving width bucket BEFORE accepting the first request (one jit compile per bucket off the serving path, reported as trigger=boot-warmup compile telemetry) instead of letting the first request of each bucket pay the compile inline (TPU extension)", "translate"),
    _f("fleet", str, "", "marian-server multi-tenant fleet serving: comma-separated <tag>=<model-path> tenants (e.g. 'en-de=/m/ende.npz,en-fr=/m/enfr.npz') served concurrently by ONE process — per-tenant lifecycle stacks (bundle watcher, canary, rollback) under the shared --fleet-hbm-budget-mb with evict-coldest + warm-on-demand; clients pick a tenant with the '#model:<tag>' protocol header. Request batching mode only; mutually exclusive with --model-watch (docs/DEPLOYMENT.md 'Fleet serving') (TPU extension)", "translate"),
    _f("fleet-hbm-budget-mb", float, 0.0, "With --fleet: shared HBM budget in MB for resident tenant executors (estimated as bundle member bytes x an overhead factor); warming a tenant past the budget evicts the coldest idle tenant's executors first (never one with in-flight batches). 0 = unbudgeted — every tenant stays resident (TPU extension)", "translate"),
    _f("fleet-default-tenant", str, "", "With --fleet: tenant tag for requests that send no '#model:' header (must name a configured tenant); empty = un-tagged requests are rejected with !!SERVER-ERROR (TPU extension)", "translate"),
    _f("fleet-watch", float, 0.0, "With --fleet: poll each RESIDENT tenant's <model>.bundles/ every N seconds and hot-swap new committed bundles through that tenant's own canary/rollback lifecycle (the per-tenant --model-watch; 0 = off, tenants still warm-on-demand) (TPU extension)", "translate"),
    _f("compile-cache", str, "", "Persistent XLA compilation cache directory (jax_compilation_cache_dir with the persistence thresholds zeroed): compiled serving/training programs are reused across process restarts, and the directory is what checkpoint bundles pack as their xla_cache.zip member so a fleet cold start (or --model-watch swap) is load+verify instead of full jit (docs/PERFORMANCE.md compile-telemetry ledger; empty = off) (TPU extension)", "translate"),
    _f("slo-availability", float, 0.0, "Declare an availability SLO (e.g. 0.999): the in-process burn-rate engine (obs/slo.py) evaluates ok-vs-(failure|timeout|stalled) outcomes over fast/slow windows, exports marian_slo_* gauges and GET /sloz, emits timeline events on threshold crossings and fires a flight dump on fast burn (0 = off) (TPU extension)", "translate"),
    _f("slo-p99-ms", float, 0.0, "Declare a latency SLO: 99% of requests must resolve under this many milliseconds (evaluated against the request-latency histogram buckets, conservatively rounded DOWN to a bucket edge). Same burn-rate machinery and exports as --slo-availability (0 = off) (TPU extension)", "translate"),
    _f("slo-window", float, 60.0, "SLO engine short (fast-burn) window in seconds; the slow window is 10x this (TPU extension)", "translate"),
    _f("slo-eval-interval", float, 2.0, "SLO engine evaluation cadence in seconds (its own daemon thread; nothing on the batch path) (TPU extension)", "translate"),
    _f("fuse", bool, False, "(compat; XLA always fuses)", "translate"),
    _f("gemm-type", str, "float32", "float32, bfloat16, int8 (TPU AQT path), intgemm8/packed* map to int8", "translate"),
    _f("quantize-range", float, 0.0, "Quantization clip range in stddevs (0 = absmax)", "translate"),
    _f("mini-batch-words-translate", int, 0, "(see mini-batch-words)", "translate"),
    # Decoder-compat shims live here, not in _TRAINING: translation /
    # embedding / server modes parse _COMMON+_MODEL+_TRANSLATION only and
    # SystemExit on unknown options, so Marian decoder command lines that
    # carry these must still parse in those modes (ADVICE r3). Training
    # mode also includes this list, so they remain accepted everywhere.
    _f("devices", str, ["0"], "Device ids (GPU compat; the data-parallel decode mesh uses all visible devices)", "translate", "+"),
    _f("num-devices", int, 0, "Cap the data-parallel decode mesh (0 = all visible devices; the batch dim shards over a 'data' mesh — the SPMD equivalent of per-device translator workers)", "translate"),
    _f("optimize", bool, False, "Legacy optimized int16 GEMM switch (no-op; see flag audit)", "translate"),
    _f("model-mmap", bool, False, "Memory-map model loading (no-op; .bin checkpoints are always mmap-loaded)", "translate"),
    _f("fp16", bool, False, "Half-precision shortcut: maps to bfloat16 compute on TPU (fp16's narrow exponent needs loss scaling; bf16 keeps the f32 range)", "translate"),
]

_SCORER = [
    _f("train-sets-scorer", str, [], "(scorer) corpora to score", "scorer", "*"),
    _f("n-best-feature", str, "Score", "Feature name for n-best rescoring", "scorer"),
    _f("summary", str, None, "Summary score: cross-entropy, ce-mean-words, perplexity", "scorer", "?"),
    _f("normalize-scorer", float, 0.0, "(see normalize)", "scorer"),
]

_EMBEDDER = [
    _f("train-sets", str, [], "(embedder) input text stream(s) to embed", "embedder", "*"),
    _f("compute-similarity", bool, False, "(embedder) cosine similarity of two parallel text streams' sentence embeddings instead of printing vectors", "embedder"),
]


MODE_FLAGS: Dict[str, List[Any]] = {
    # training includes the translation group: the translation validator
    # runs beam search with --beam-size/--normalize etc. (reference:
    # config_parser.cpp addOptionsTranslation in training mode)
    "training": _COMMON + _MODEL + _TRAINING + _VALIDATION + _TRANSLATION,
    "translation": _COMMON + _MODEL + _TRANSLATION,
    "scoring": _COMMON + _MODEL + _TRAINING + _SCORER + _TRANSLATION,
    "embedding": _COMMON + _MODEL + _EMBEDDER + _TRANSLATION,
    "vocab": _COMMON,
    "server": _COMMON + _MODEL + _TRANSLATION,
}


def _flag_table(mode: str) -> Dict[str, Any]:
    seen: Dict[str, Any] = {}
    for f in MODE_FLAGS[mode]:
        if f.name not in seen:
            seen[f.name] = f
    return seen


class ConfigParser:
    """parseOptions equivalent. Returns a fully-populated Options."""

    def __init__(self, mode: str = "training"):
        if mode not in MODE_FLAGS:
            raise ValueError(f"Unknown mode '{mode}'")
        self.mode = mode
        self.flags = _flag_table(mode)

    def _build_argparser(self) -> argparse.ArgumentParser:
        p = argparse.ArgumentParser(
            prog=f"marian-tpu ({self.mode})", add_help=True, allow_abbrev=False
        )
        for f in self.flags.values():
            arg = f"--{f.name}"
            kwargs: Dict[str, Any] = {"dest": f.name.replace("-", "_"), "default": None}
            if f.type is bool:
                # CLI11-style: bare flag = true, or explicit --flag true/false
                kwargs.update(nargs="?", const=True, type=_parse_bool)
            else:
                kwargs["type"] = f.type
                if f.nargs:
                    kwargs["nargs"] = f.nargs
                    if f.nargs == "?":
                        kwargs["const"] = True if f.type is bool else ""
            p.add_argument(arg, help=f.help, **kwargs)
        return p

    def defaults(self) -> Dict[str, Any]:
        return {f.name: f.default for f in self.flags.values() if f.default is not None}

    def parse(self, argv: Optional[Sequence[str]] = None) -> Options:
        argv = list(sys.argv[1:] if argv is None else argv)
        parser = self._build_argparser()
        ns, unknown = parser.parse_known_args(argv)
        if unknown:
            raise SystemExit(f"Unknown option(s): {' '.join(unknown)}")
        cli: Dict[str, Any] = {
            k.replace("_", "-"): v for k, v in vars(ns).items() if v is not None
        }

        # layer 1: defaults
        merged = self.defaults()

        # layer 2: config file(s)
        explicit = set(cli.keys())       # keys the user actually provided
        for path in _as_list(cli.get("config")):
            with open(path, "r", encoding="utf-8") as fh:
                loaded = yaml.safe_load(fh) or {}
            interp = loaded.get("interpolate-env-vars",
                                cli.get("interpolate-env-vars", False))
            if interp:
                loaded = _interpolate_env_vars(loaded)
            if loaded.get("relative-paths", cli.get("relative-paths", False)):
                loaded = _make_paths_absolute(loaded, os.path.dirname(
                    os.path.abspath(path)))
            for k, v in loaded.items():
                merged[str(k)] = v
                explicit.add(str(k))

        # layer 3: alias expansion (--task / from config), before CLI overrides
        task = cli.get("task", merged.get("task"))
        if task:
            merged = expand_aliases(task, merged)
            merged["task"] = task

        # layer 4: CLI overrides
        for k, v in cli.items():
            if k == "config":
                continue
            merged[k] = v

        if merged.get("no-shuffle"):
            merged["shuffle"] = "none"
        if merged.get("fp16"):
            # --fp16 shortcut (reference: precision float16 float32 +
            # cost-scaling defaults). On TPU fp16's 5-bit exponent would
            # need the whole loss-scaling apparatus; bf16 keeps the f32
            # range, so the shortcut maps there — same memory/matmul
            # savings, no scaling machinery. An explicit --precision wins.
            if "precision" not in explicit:
                merged["precision"] = ["bfloat16", "float32"]
        if str((merged.get("precision") or ["float32"])[0]) in (
                "float16", "fp16", "half"):
            from . import logging as _log
            _log.warn("precision float16 is mapped to bfloat16 on TPU "
                      "(same width, f32 exponent range — no loss scaling "
                      "needed)")
            merged["precision"] = ["bfloat16"] + \
                list(merged["precision"][1:])
        # bare `--output-sampling` (Marian shorthand) = full sampling, temp 1
        if cli.get("output-sampling") == []:
            merged["output-sampling"] = ["full"]
        # bare `--dynamic-gradient-scaling` = factor 2 (same default the
        # YAML `true` spelling gets)
        if cli.get("dynamic-gradient-scaling") == [] \
                or merged.get("dynamic-gradient-scaling") is True:
            merged["dynamic-gradient-scaling"] = ["2"]
        if cli.get("interpolate-env-vars") or merged.get("interpolate-env-vars"):
            merged = _interpolate_env_vars(merged)

        # mode-suffixed duplicates and synonyms → the canonical key runtime
        # code reads (the suffixed names exist because translate/scorer modes
        # share one flag registry with training); config-file values count
        # as explicit too, and the canonical key wins if the user set both
        for alias, (canon, modes, vmap) in _CANONICAL.items():
            if modes is not None and self.mode not in modes:
                continue
            if alias in explicit and canon not in explicit:
                val = merged[alias]
                if vmap is not None:
                    if str(val) not in vmap:
                        raise SystemExit(
                            f"--{alias}: unknown value '{val}' "
                            f"(expected one of {sorted(vmap)})")
                    val = vmap[str(val)]
                merged[canon] = val

        opts = Options(merged)

        for meta in ("authors", "cite", "build-info", "version"):
            if cli.get(meta):
                print(_META_TEXT[meta]())
                raise SystemExit(0)

        dump = cli.get("dump-config") or (True if "dump-config" in cli else None)
        if dump:
            self.dump(opts, mode=dump if isinstance(dump, str) else "full")
            raise SystemExit(0)
        return opts

    def dump(self, opts: Options, mode: str = "full", stream=None) -> None:
        """--dump-config: print effective config as YAML (reference:
        config_parser.cpp dumpConfig)."""
        stream = stream or sys.stdout
        data = opts.as_dict()
        if mode == "minimal":
            defaults = self.defaults()
            data = {k: v for k, v in data.items() if defaults.get(k) != v}
        data.pop("dump-config", None)
        yaml.safe_dump(data, stream, default_flow_style=False, sort_keys=True)


# Mode-suffixed duplicates / synonyms → the canonical key runtime code
# reads: alias → (canonical, applicable modes or None for all, value map or
# None for identity). The mode gate matters: in training mode the
# translate-suffixed names configure the validation decoder only and must
# NOT clobber the training-side canonical keys (e.g. the token budget).
_CANONICAL = {
    "max-length-factor-translate":
        ("max-length-factor", ("translation", "scoring"), None),
    "mini-batch-words-translate":
        ("mini-batch-words", ("translation", "scoring"), None),
    "normalize-scorer": ("normalize", ("scoring",), None),
    "train-sets-scorer": ("train-sets", ("scoring",), None),
    "attention-kernel":
        ("transformer-flash-attention", None,
         {"auto": "auto", "dense": "off", "flash": "on"}),
}

_META_TEXT = {
    "authors": lambda: "marian-tpu contributors (TPU-native rebuild of the "
                       "Marian NMT toolkit; reference authors: Junczys-"
                       "Dowmunt et al., see --cite)",
    "cite": lambda: ("@inproceedings{junczys2018marian,\n"
                     "  title={Marian: Fast Neural Machine Translation in "
                     "C++},\n  author={Junczys-Dowmunt, Marcin and others},\n"
                     "  booktitle={Proceedings of ACL 2018, System "
                     "Demonstrations},\n  year={2018}\n}"),
    "build-info": lambda: _build_info(),
    "version": lambda: "marian-tpu v0.1.0 (jax %s)" % __import__("jax").__version__,
}


def _build_info() -> str:
    import platform
    try:
        import jax
        backend = jax.default_backend()
        jv = jax.__version__
    except Exception:  # pragma: no cover
        backend, jv = "?", "?"
    return (f"marian-tpu 0.1.0; python {platform.python_version()}; "
            f"jax {jv}; backend {backend}")


_ENV_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)\}")


def _interpolate_env_vars(obj: Any) -> Any:
    """${ENV_VAR} substitution in string config values (reference:
    cli::interpolateEnvVars)."""
    if isinstance(obj, str):
        return _ENV_RE.sub(lambda m: os.environ.get(m.group(1), m.group(0)), obj)
    if isinstance(obj, list):
        return [_interpolate_env_vars(v) for v in obj]
    if isinstance(obj, dict):
        return {k: _interpolate_env_vars(v) for k, v in obj.items()}
    return obj


# Config keys holding filesystem paths, for --relative-paths (reference:
# cli::makeAbsolutePaths / ConfigParser's PATHS list).
_PATH_KEYS = {
    "model", "models", "pretrained-model", "train-sets", "vocabs",
    "valid-sets", "valid-script-path", "valid-translation-output",
    "valid-log", "log", "sqlite", "shortlist", "embedding-vectors",
    "guided-alignment", "data-weighting", "input", "output", "tempdir",
    "ulr-keys-vectors", "ulr-query-vectors", "train-embedder-rank",
}


def _make_paths_absolute(cfg: Dict[str, Any], base: str) -> Dict[str, Any]:
    def fix(v):
        if isinstance(v, str) and v and not os.path.isabs(v) \
                and v not in ("stdin", "stdout", "stderr", "-"):
            return os.path.normpath(os.path.join(base, v))
        return v

    out = dict(cfg)
    for k in _PATH_KEYS & set(out.keys()):
        v = out[k]
        if isinstance(v, list):
            # e.g. shortlist is [path, k, ...]: only fix path-looking strings
            out[k] = [fix(x) if isinstance(x, str) and not str(x).isdigit()
                      else x for x in v]
        else:
            out[k] = fix(v)
    return out


# ---------------------------------------------------------------------------
# Unimplemented-flag audit (reference parity rule: same behavior per flag —
# accept-and-silently-ignore is never allowed; VERDICT r1). Every flag that
# is parsed but has no runtime reader is registered here with an action:
#   warn  — the TPU design makes it unnecessary or a safe no-op; a one-line
#           rationale is logged when the user sets it to a non-default value
#   error — honoring it would require semantics we don't provide; training or
#           decoding would silently differ, so refuse to run
# Implementing a flag removes it from this table (tests assert every parsed
# flag is either read somewhere in the package or listed here).
# ---------------------------------------------------------------------------

UNIMPLEMENTED_FLAGS: Dict[str, tuple] = {
    # -- safe no-ops under the TPU/XLA design --
    "workspace": ("warn", "XLA owns device memory; batch fitting uses the "
                          "bucket table (data/batch_generator.py)"),
    "cpu-threads": ("warn", "host threading is managed by XLA/the runtime"),
    "data-threads": ("warn", "the data pipeline prefetches asynchronously; "
                             "thread count is not user-tunable"),
    "no-nccl": ("warn", "collectives are XLA GSPMD over ICI/DCN, not NCCL"),
    "sync-freq": ("warn", "parameter sync is every step under GSPMD data "
                          "parallelism (no stale local copies exist)"),
    "multi-node-overlap": ("warn", "XLA overlaps collectives with compute "
                                   "automatically"),
    "tempdir": ("warn", "corpus shuffling happens in RAM; no temp files"),
    "log-time-zone": ("warn", "log timestamps use the process-local time "
                              "zone; set TZ in the environment instead"),
    "mini-batch-fit-step": ("warn", "bucketed static shapes replace the "
                                    "binary batch-fitting search"),
    "mini-batch-round-up": ("warn", "bucket table already snaps batch sizes "
                                    "to hardware-friendly multiples"),
    "cost-scaling": ("warn", "bf16 training keeps gradients in f32 master "
                             "range; dynamic loss scaling (an fp16 "
                             "necessity) has nothing to rescue"),
    "fuse": ("warn", "XLA fuses elementwise chains into matmuls "
                     "automatically"),
    "sharding": ("warn", "optimizer state is ZeRO-1 sharded over the full "
                         "'data' mesh axis; there is no node-local NVLink "
                         "domain to restrict to on ICI"),
    "shuffle-in-ram": ("warn", "the corpus always shuffles in RAM"),
    "sqlite": ("warn", "the resumable in-RAM corpus replaces the SQLite "
                       "shuffle database; positions checkpoint in "
                       "progress.yml"),
    "best-deep": ("warn", "s2s depth/variant comes from --type and the "
                          "dim/depth flags directly"),
    "skip-cost": ("warn", "hypothesis scores fall out of the beam at no "
                          "extra cost; there is nothing to skip"),
    "bert-sep-symbol": ("warn", "sentence-pair assembly takes the token "
                                "streams as given; separators are not "
                                "re-inserted by the pipeline"),
    "bert-class-symbol": ("warn", "classifier pooling uses the first "
                                  "position; the symbol itself is not "
                                  "re-inserted by the pipeline"),
    "ulr-dim-emb": ("warn", "the ULR query dimension is taken from the "
                            "key-vectors file, not this flag"),
    "interpolate-env-vars": ("none", "handled at config load"),
    "relative-paths": ("none", "handled at config load"),
    "fp16": ("none", "handled at config load (maps to bfloat16 precision)"),
    "sqlite-drop": ("warn", "the resumable in-RAM corpus replaces the "
                            "SQLite shuffle database; there is nothing "
                            "to drop"),
    "diverged-after": ("warn", "fp16 divergence recovery does not apply: "
                               "bf16 keeps the f32 exponent range; use "
                               "--check-gradient-nan + --on-divergence "
                               "rollback (in-process self-heal) or throw"),
    "custom-fallbacks": ("warn", "fp16 fallback machinery does not apply "
                                 "to bf16 training"),
    "fp16-fallback-to-fp32": ("warn", "fp16 fallback machinery does not "
                                      "apply to bf16 training"),
    "recover-from-fallback-after": ("warn", "fp16 fallback machinery does "
                                           "not apply to bf16 training"),
    "overwrite-checkpoint": ("warn", "checkpoint rotation is governed by "
                                     "--overwrite (.iterN copies)"),
    "clip-gemm": ("warn", "legacy intgemm clipping; XLA int8 GEMMs "
                          "quantize with per-channel scales instead"),
    "optimize": ("warn", "legacy int16 GEMM switch; use an int8 "
                         "marian-conv checkpoint for quantized decode"),
    "model-mmap": ("warn", ".bin checkpoints are always mmap-loaded; "
                           ".npz loads copy (convert with marian-conv "
                           "for mmap)"),
    "mini-batch-track-optimum": ("warn", "bucketed static batch shapes "
                                         "replace dynamic batch-size "
                                         "tracking"),
    "lemma-dependency": ("warn", "factor prediction is lemma-conditioned "
                                 "via --lemma-dim-emb soft re-embedding "
                                 "(layers/logits.py); the reference's "
                                 "per-mechanism selector is collapsed "
                                 "into that one implementation"),
    # -- would silently change training/decoding semantics: refuse --
    "transformer-pool": ("error", "pooled attention variant is not "
                                  "implemented"),
    "train-embedder-rank": ("error", "margin-based embedder-rank training "
                                     "is not implemented (semantics "
                                     "unverifiable against the empty "
                                     "reference mount)"),
}


def audit_flags(opts: Options, parser: "ConfigParser") -> None:
    """Warn or refuse for parsed-but-unimplemented flags the user actually
    set (compared against the registry defaults)."""
    from . import logging as log
    for name, spec in UNIMPLEMENTED_FLAGS.items():
        f = parser.flags.get(name)
        if f is None or not opts.has(name):
            continue
        val = opts.get(name)
        if val == f.default or val in (None, [], False, "", 0, 0.0):
            continue
        action = spec[0]
        if action == "none":
            continue
        if action == "error-unless":
            allowed, why = spec[1], spec[2]
            if val == allowed:
                continue
            raise ValueError(f"--{name} {val}: {why} is supported")
        why = spec[1]
        if action == "error":
            raise ValueError(
                f"--{name} is accepted for Marian config compatibility but "
                f"its semantics are not implemented ({why}); refusing to "
                f"silently ignore it")
        log.warn("--{} has no effect on TPU: {}", name, why)


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("1", "true", "yes", "on")


def _as_list(v: Any) -> List[Any]:
    if v is None:
        return []
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v]


def parse_options(argv: Optional[Sequence[str]] = None, mode: str = "training",
                  validate: bool = True) -> Options:
    """Module-level convenience mirroring ConfigParser::parseOptions."""
    parser = ConfigParser(mode)
    opts = parser.parse(argv)
    if validate:
        from .config_validator import validate_options
        validate_options(opts, mode)
        audit_flags(opts, parser)
    return opts
