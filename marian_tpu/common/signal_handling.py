"""Graceful SIGTERM handling (reference: src/common/signal_handling.cpp ::
setSignalHandlers/getSignalFlag). The trainer checks ``signal_flag()`` after
every update: finish the step, save a full checkpoint, exit 0. Covers TPU
preemption notices delivered as SIGTERM."""

from __future__ import annotations

import signal
from typing import Optional

_flags = {}


def _handler(signum, frame):
    _flags[signum] = True


def set_signal_handlers() -> None:
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _handler)
        except ValueError:
            pass  # not on main thread — harness/test context


def signal_flag(signum: Optional[int] = None) -> bool:
    if signum is None:
        return bool(_flags)
    return _flags.get(signum, False)


def clear_signal_flags() -> None:
    _flags.clear()
