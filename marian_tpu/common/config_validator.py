"""Config validation (reference: src/common/config_validator.cpp ::
ConfigValidator::validateOptions). Raises ValueError on inconsistent setups."""

from __future__ import annotations

from .options import Options


def validate_options(opts: Options, mode: str) -> None:
    if mode == "training":
        _validate_training(opts)
    elif mode in ("translation", "server"):
        _validate_translation(opts)
    elif mode == "scoring":
        _validate_scoring(opts)


def _validate_common_model(opts: Options) -> None:
    if opts.get("dim-emb", 512) <= 0:
        raise ValueError("--dim-emb must be positive")
    t = opts.get("type", "transformer")
    known = {"transformer", "s2s", "nematus", "amun", "multi-s2s",
             "char-s2s", "multi-transformer", "bert", "bert-classifier",
             "transformer-lm", "lm", "lm-transformer"}
    if t not in known:
        raise ValueError(f"Unknown model --type '{t}' (known: {sorted(known)})")
    if t == "transformer" and opts.get("dim-emb", 512) % opts.get("transformer-heads", 8) != 0:
        raise ValueError("--dim-emb must be divisible by --transformer-heads")


def _validate_training(opts: Options) -> None:
    _validate_common_model(opts)
    ga_flag = opts.get("guided-alignment", "none")
    if opts.get("type", "") in ("transformer-lm", "lm-transformer", "lm") \
            and ga_flag and ga_flag != "none":
        raise ValueError("--guided-alignment requires cross-attention; a "
                         "decoder-only LM (--type transformer-lm) has none")
    if opts.get("right-left", False):
        # token-position side data is NOT remapped when the target is
        # reversed — refuse rather than silently corrupt the supervision
        ga = opts.get("guided-alignment", "none")
        if ga and ga != "none":
            raise ValueError("--right-left cannot be combined with "
                             "--guided-alignment (alignment target indices "
                             "are not remapped under target reversal)")
        if opts.get("data-weighting", None) \
                and str(opts.get("data-weighting-type", "sentence")) == "word":
            raise ValueError("--right-left cannot be combined with "
                             "word-level --data-weighting (per-token "
                             "weights are not remapped under reversal)")
    if not opts.get("train-sets", []):
        raise ValueError("No train sets given in --train-sets")
    vocabs = opts.get("vocabs", [])
    trains = opts.get("train-sets", [])
    if opts.get("tsv", False):
        if len(trains) != 1:
            raise ValueError(
                f"--tsv expects ONE tab-separated --train-sets file, "
                f"got {len(trains)}")
    elif vocabs and len(vocabs) != len(trains):
        raise ValueError(
            f"Number of --vocabs ({len(vocabs)}) must match --train-sets ({len(trains)})")
    if opts.get("label-smoothing", 0.0) < 0 or opts.get("label-smoothing", 0.0) >= 1:
        raise ValueError("--label-smoothing must be in [0, 1)")
    if opts.get("optimizer-delay", 1.0) <= 0:
        raise ValueError("--optimizer-delay must be positive")
    es = opts.get("early-stopping", 10)
    if es < 0:
        raise ValueError("--early-stopping must be >= 0")
    if opts.get("cost-type", "ce-sum") not in (
            "ce-sum", "ce-mean", "ce-mean-words", "ce-rescore", "perplexity"):
        raise ValueError(f"Unknown --cost-type {opts.get('cost-type')}")


def _validate_translation(opts: Options) -> None:
    _validate_common_model(opts)
    if not opts.get("models", []) and not opts.get("model", None):
        raise ValueError("No model given in --models")
    w = opts.get("weights", [])
    m = opts.get("models", [])
    if w and len(w) != len(m):
        raise ValueError("--weights count must match --models count")
    if opts.get("beam-size", 12) < 1:
        raise ValueError("--beam-size must be >= 1")


def _validate_scoring(opts: Options) -> None:
    _validate_common_model(opts)
