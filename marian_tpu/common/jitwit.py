"""Runtime retrace witness: the dynamic half of mtlint's compile-cache
analysis (ISSUE 17) — the lockdep/ownwit move, applied to XLA
compilation.

The static side (marian_tpu/analysis/jitgraph.py + the MT-JIT rule
family) enumerates every jit boundary and derives each site's
compile-key domain from the ``# buckets: <REGISTRY>`` vocabulary. Its
documented blind spots — jit objects reached through dynamic dispatch,
eager ops compiling per shape, a closure constant that quietly varies —
are exactly where a compile-cache melt would hide from it. This module
keeps the model honest the way ``MARIAN_LOCKDEP=1`` keeps the lock
lattice honest: record what the backend actually compiled, and
cross-check.

With ``MARIAN_JITWIT=1`` in the environment (tests/conftest.py arms it
for the whole tier-1 process) and the listener installed
(:func:`install`, idempotent), every ``backend_compile`` the runtime
performs is attributed — via the ``jax.monitoring`` event-duration
hook, which fires synchronously in the compiling thread — to the
nearest stack frame inside ``marian_tpu/``, identified
``<rel>::<co_name>``: exactly the identity the static site scan
derives. Separately, the engines declare their compile keys as they
create jit objects (:func:`note_compile_key`): the key tuple plus the
(registry, value) domain pairs each axis is drawn from.

The verdict (:func:`check_against_static`, asserted at module teardown
of the tier-1 serving/iteration/beam suites):

- an observed backend compile attributed to a site the static model
  does not mark compile-capable → blind spot; FAIL (extend
  analysis/jitgraph.py, never baseline it);
- a compile key NOTED TWICE by the same engine at the same site → a
  retrace: the jit object for that key was rebuilt, so its previous
  compile was wasted (the ``jit.closure_vary`` faultpoint drill proves
  this trips);
- a noted domain pair naming a registry the static scan cannot find,
  or a value outside the registry's table (cap-clamped values at or
  below the table max are in-domain: ``min(b, max_rows)``) → the
  annotation vocabulary and reality disagree.

Sites outside ``marian_tpu/`` (tests jitting directly) record as
``<external>`` and are exempt — the static model does not cover test
code; engine-driven traffic is what the witness audits. The
closed-shape-set regression (tests/test_iteration.py) additionally
uses :func:`strict`: a window in which EVERY backend compile is
captured, so "warmed engine + mixed traffic = zero compiles" is
directly assertable — the executable form of the paper's "compile
once, serve forever".

Without ``MARIAN_JITWIT=1`` the listener records nothing and
``note_compile_key`` is one env read. Stdlib-only; jax is imported
lazily by :func:`install` alone, so the analysis layer never pulls it.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

ENV_VAR = "MARIAN_JITWIT"

EXTERNAL_SITE = "<external>"


def enabled() -> bool:
    return os.environ.get(ENV_VAR, "") == "1"


_TOKENS = itertools.count(1)


def new_token() -> int:
    """Process-unique engine identity for the noted-key table. Two
    engines legitimately note the same (site, key) — a raw ``id()``
    can be reused after collection and would fabricate a retrace."""
    return next(_TOKENS)


# -- observed model ----------------------------------------------------------
# Guarded by _WITNESS_LOCK — a plain lock, deliberately NOT lockdep-
# witnessed and excluded from lock discovery (callgraph
# _INSTRUMENTATION_MODULES): the monitoring listener runs inside jax's
# compile path and is instrumentation, not part of the modeled lattice.

_WITNESS_LOCK = threading.Lock()
# every backend compile: (site, seconds)
_COMPILES: List[Tuple[str, float]] = []
_COMPILE_SITES: Set[str] = set()
# noted compile keys: (site, token, key) -> count (count > 1 = retrace)
_NOTED: Dict[Tuple[str, int, tuple], int] = {}
# noted (registry, value) domain pairs per (site, key axis)
_DOMAINS: List[Tuple[str, str, int]] = []     # (site, registry, value)
_RETRACES: List[Tuple[str, tuple]] = []       # (site, key)
_STRICT: List["StrictWindow"] = []

_INSTALLED = False

# frames inside this file are instrumentation, not call sites
_SKIP_SUFFIXES = ("common/jitwit.py", "common\\jitwit.py")

_ROOT: Optional[str] = None

# per-file cache: does this module hold jax in its globals? (compiles
# are rare, but the frame walk must stay cheap under repeat events)
_JAX_GLOBALS: Dict[str, bool] = {}


def _frame_uses_jax(f) -> bool:
    """Runtime mirror of the static model's per-module capability claim
    (jitgraph ``_imports_jax``): a module with no jax binding in scope
    cannot ORIGINATE a compile — when its frame is on the stack at
    compile time it is invoking someone else's computation (the
    pipelined/scheduler higher-order shape), and attribution belongs to
    the frame that built it. Module objects named jax/jax.* in the
    frame's globals count; locals cover the lazy in-function
    ``import jax`` idiom."""
    import types
    fname = f.f_code.co_filename
    uses = _JAX_GLOBALS.get(fname)
    if uses is None:
        uses = any(
            isinstance(v, types.ModuleType)
            and (v.__name__ == "jax" or v.__name__.startswith("jax."))
            for v in list(f.f_globals.values()))
        _JAX_GLOBALS[fname] = uses
    if uses:
        return True
    return any(
        isinstance(v, types.ModuleType)
        and (v.__name__ == "jax" or v.__name__.startswith("jax."))
        for v in list(f.f_locals.values()))


def _find_root() -> Optional[str]:
    global _ROOT
    if _ROOT is None:
        cur = os.path.dirname(os.path.abspath(__file__))
        for _ in range(6):
            if os.path.exists(os.path.join(cur, "pyproject.toml")):
                _ROOT = cur
                break
            cur = os.path.dirname(cur)
    return _ROOT


def _site() -> str:
    """The acting site: nearest stack frame inside ``marian_tpu/``
    (skipping this module), '<rel>::<co_name>' — the static model's
    site identity. Unlike ownwit's walk, frames OUTSIDE marian_tpu are
    stepped over, not terminal: the monitoring listener fires under a
    stack of jax internals and the attribution target is the marian
    frame beneath them. Synthetic frames (``<listcomp>``/``<lambda>``)
    and frames in jax-free marian modules are stepped over too — the
    static identity is the enclosing FUNCTION, and a module with no
    jax in scope is running someone else's computation
    (:func:`_frame_uses_jax`). No attributable marian frame at all →
    EXTERNAL_SITE (tests jitting directly), exempt from the
    cross-check."""
    root = _find_root()
    if root is None:
        return EXTERNAL_SITE
    f = sys._getframe(1)
    while f is not None:
        fname = f.f_code.co_filename
        norm = fname.replace("\\", "/")
        if not norm.endswith(_SKIP_SUFFIXES[0]) \
                and not f.f_code.co_name.startswith("<"):
            # synthetic frames (<listcomp>/<genexpr>/<lambda>/<module>,
            # pre-3.12 comprehensions get their own frame) are stepped
            # over: the static model's site identity is the enclosing
            # FUNCTION — `[enc(x) for x in ...]` compiles in the frame
            # that wrote the comprehension
            try:
                rel = os.path.relpath(fname, root).replace("\\", "/")
            except ValueError:              # different drive (windows)
                rel = ""
            if rel.startswith("marian_tpu/") and _frame_uses_jax(f):
                return f"{rel}::{f.f_code.co_name}"
        f = f.f_back
    return EXTERNAL_SITE


# -- the jax.monitoring listener ---------------------------------------------

def install() -> bool:
    """Register the backend-compile listener (idempotent; returns
    whether a listener is in place). Imports jax lazily — call sites
    that must stay stdlib-only guard on :func:`enabled` first. The
    listener itself re-checks ``enabled()`` per event, so clearing the
    env var disarms recording without unregistration (jax.monitoring
    has no public remove)."""
    global _INSTALLED
    if _INSTALLED:
        return True
    try:
        import jax.monitoring as jmon
    except Exception:                        # pragma: no cover - no jax
        return False

    def _on_event(name: str, secs: float, **_kw) -> None:
        if not name.endswith("backend_compile_duration"):
            return
        if not enabled():
            return
        site = _site()
        with _WITNESS_LOCK:
            _COMPILES.append((site, secs))
            _COMPILE_SITES.add(site)
            for w in _STRICT:
                w.compiles.append((site, secs))

    jmon.register_event_duration_secs_listener(_on_event)
    _INSTALLED = True
    return True


# -- engine-side declarations ------------------------------------------------

def note_compile_key(token: int, key: tuple,
                     domains: Sequence[Tuple[str, int]] = ()) -> None:
    """Declare that a NEW jit object keyed by ``key`` now exists for
    the engine identified by ``token`` (placed exactly where engines
    create/cache jit objects — ``_make_step``, ``_install`` shape
    admission, fork-pad creation). ``domains`` names the registry each
    bucketed axis was drawn from, e.g. ``(("ROW_BUCKETS", rb),)``.

    A second note of the same (site, token, key) is a RETRACE: the
    engine rebuilt a jit object it already paid for — the varying-
    closure failure mode, and what the ``jit.closure_vary`` drill
    seeds."""
    if not enabled():
        return
    site = _site()
    with _WITNESS_LOCK:
        k = (site, token, key)
        n = _NOTED.get(k, 0) + 1
        _NOTED[k] = n
        if n > 1:
            _RETRACES.append((site, key))
        for reg, val in domains:
            _DOMAINS.append((site, reg, int(val)))


class StrictWindow:
    """Every backend compile observed while the window is open."""

    def __init__(self):
        self.compiles: List[Tuple[str, float]] = []

    def __enter__(self) -> "StrictWindow":
        with _WITNESS_LOCK:
            _STRICT.append(self)
        return self

    def __exit__(self, *exc) -> None:
        with _WITNESS_LOCK:
            if self in _STRICT:
                _STRICT.remove(self)


def strict() -> StrictWindow:
    """Open a capture window: ``with jitwit.strict() as w: ...`` then
    assert on ``w.compiles`` — the closed-shape-set regression asserts
    it stays EMPTY across mixed traffic on a grid-warmed engine."""
    return StrictWindow()


# -- inspection / verdict ----------------------------------------------------

def observed_compiles() -> List[Tuple[str, float]]:
    with _WITNESS_LOCK:
        return list(_COMPILES)


def observed_compile_sites() -> Set[str]:
    with _WITNESS_LOCK:
        return set(_COMPILE_SITES)


def noted_keys() -> Dict[Tuple[str, int, tuple], int]:
    with _WITNESS_LOCK:
        return dict(_NOTED)


def retraces() -> List[Tuple[str, tuple]]:
    """(site, key) for every duplicate note — the drill surface."""
    with _WITNESS_LOCK:
        return list(_RETRACES)


def reset() -> None:
    """Forget everything observed so far (tests). The installed
    listener stays; state is what resets."""
    with _WITNESS_LOCK:
        _COMPILES.clear()
        _COMPILE_SITES.clear()
        _NOTED.clear()
        _DOMAINS.clear()
        _RETRACES.clear()


def _value_in_domain(model, reg: str, val: int) -> bool:
    if reg == "POW2":
        return val >= 1 and (val & (val - 1)) == 0
    if reg == "HALVING":
        return val >= 1
    vals = model.registry_values(reg)
    if vals is None:
        return False
    # cap-clamped draws (min(b, max_rows)) land at or below the table
    # max without being table members — in-domain: the table still
    # bounds the key count
    return val in vals or val <= max(vals)


def check(model) -> List[str]:
    """Violations of the static model by what actually compiled,
    against an ``analysis.jitgraph.JitModel``. Empty list = every
    observed backend compile originated at a compile-capable site,
    no noted key was retraced, and every noted domain pair is drawn
    from a known registry within its table. ``<external>`` compile
    sites (tests jitting directly) are exempt by design."""
    violations: List[str] = []
    for s in sorted(observed_compile_sites() - {EXTERNAL_SITE}):
        if s not in model.compile_capable:
            violations.append(
                f"observed backend compile at site {s}, which the "
                f"static jit model never predicted — "
                f"analysis/jitgraph.py's site scan or capability map "
                f"has a blind spot; extend the model, do not baseline "
                f"this")
    for site, key in retraces():
        violations.append(
            f"compile key {key!r} at site {site} was noted more than "
            f"once by the same engine — a RETRACE: the jit object was "
            f"rebuilt and its previous compile wasted (varying "
            f"closure / cache eviction); fix the site, do not "
            f"baseline this")
    with _WITNESS_LOCK:
        domains = list(_DOMAINS)
    for site, reg, val in domains:
        if not model.known_registry(reg):
            violations.append(
                f"site {site} noted compile-key domain registry "
                f"'{reg}' which the static registry scan cannot find "
                f"— the # buckets: vocabulary and runtime disagree")
        elif not _value_in_domain(model, reg, val):
            violations.append(
                f"site {site} noted key value {val} as drawn from "
                f"{reg}, but it is outside that table — the declared "
                f"domain does not bound this site's compile keys")
    return violations


def check_against_static(root) -> List[str]:
    """:func:`check` against the jit model built from the repo at
    ``root`` — the cross-check the tier-1 serving/iteration/beam
    suites assert at module teardown. The analysis layer is
    stdlib-only, so this never imports jax."""
    from ..analysis.jitgraph import static_jit_model
    return check(static_jit_model(root))
