"""Analytic FLOPs accounting and MFU (model FLOPs utilization).

The reference reports raw words/s only; on TPU a throughput number is
uninterpretable without knowing how far it sits from the chip's matmul
ceiling (is a 2.3x gap MXU idle time, or is the target near roofline for
this chip generation?). This module prices a transformer train step in
matmul FLOPs from the batch shapes, and maps ``device_kind`` strings to
published peak bf16 FLOPs so bench.py can report ``mfu`` next to
``vs_baseline`` (VERDICT r2 missing-item #5).

Conventions (PaLM-appendix style "model FLOPs"):
- only matmul work is counted (elementwise/softmax/norms are HBM-bound
  noise on the MXU);
- a matmul [m,k]x[k,n] costs 2*m*k*n;
- token counts are REAL (mask-counted) tokens — padding rows burn MXU
  cycles but do no useful work, so they lower MFU, which is the point;
- attention-score terms use the PADDED sequence width: each real token
  genuinely attends over the padded row on the device;
- causal self-attention is priced at full width (the kernels compute
  full blocks; no causal-sparsity discount);
- train = 3x forward (activation grads + weight grads each replay every
  forward matmul once).
"""

from __future__ import annotations

from typing import Optional


def transformer_train_flops(emb: int, ffn: int, enc_depth: int,
                            dec_depth: int, vocab: int,
                            src_tokens: float, trg_tokens: float,
                            src_width: int, trg_width: int) -> float:
    """Matmul FLOPs for ONE training step (fwd+bwd) of an encoder-decoder
    transformer on a batch with the given real token counts and padded
    widths. Tied embeddings are assumed (the output projection is the
    only embedding matmul priced; input embedding is a gather)."""
    d, f = float(emb), float(ffn)
    # encoder layer, per src token: QKV+out projections (4 matmuls of
    # d x d) + FFN (d x f, f x d); scores+values: QK^T and AV, each
    # 2*width*d per token.
    enc_tok = 8 * d * d + 4 * d * f + 4 * src_width * d
    enc = enc_depth * src_tokens * enc_tok
    # decoder layer: self-attn like the encoder (trg width); cross-attn
    # Q+out projections per trg token, K+V projections per SRC token
    # (computed once over encoder output), scores over src width.
    dec_tok = (8 * d * d + 4 * trg_width * d      # self-attn
               + 4 * d * d + 4 * src_width * d    # cross-attn Q/out+scores
               + 4 * d * f)                       # FFN
    dec_kv = 4 * d * d * src_tokens               # cross K/V per src token
    dec = dec_depth * (trg_tokens * dec_tok + dec_kv)
    logits = 2 * d * float(vocab) * trg_tokens
    return 3.0 * (enc + dec + logits)


# Published peak dense bf16 FLOPs/s per JAX DEVICE. On v2/v3 a chip has
# two TensorCores and jax.devices() lists each core as its own device,
# so the per-device peak is HALF the published per-chip number; v4
# onward is megacore (one device per chip). Substring match on jax
# Device.device_kind; None = unknown generation (mfu is reported as
# null rather than guessed).
_PEAK_BF16 = (
    ("v6 lite", 918e12),   # Trillium / v6e
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),   # v5e
    ("v5e", 197e12),
    ("v4 lite", 138e12),   # v4i inference chip
    ("v4", 275e12),
    ("v3", 61.5e12),       # 123 TFLOP/chip, 2 cores/chip → per device
    ("v2", 22.5e12),       # 45 TFLOP/chip, 2 cores/chip → per device
)


def peak_bf16_flops(device_kind: str) -> Optional[float]:
    """Peak dense bf16 FLOPs/s for ONE jax device of the given
    ``device_kind``, or None for unrecognized kinds (e.g. the axon
    tunnel may report a virtual name; CPU always returns None). Matches
    bench.py's per-device throughput accounting (value / len(devices))."""
    kind = (device_kind or "").lower()
    if "tpu" not in kind:
        return None
    for tag, peak in _PEAK_BF16:
        if tag in kind:
            return peak
    return None
