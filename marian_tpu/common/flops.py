"""Analytic FLOPs accounting and MFU (model FLOPs utilization).

The reference reports raw words/s only; on TPU a throughput number is
uninterpretable without knowing how far it sits from the chip's matmul
ceiling (is a 2.3x gap MXU idle time, or is the target near roofline for
this chip generation?). This module prices a transformer train step in
matmul FLOPs from the batch shapes, and maps ``device_kind`` strings to
published peak bf16 FLOPs so bench.py can report ``mfu`` next to
``vs_baseline`` (VERDICT r2 missing-item #5).

Conventions (PaLM-appendix style "model FLOPs"):
- only matmul work is counted (elementwise/softmax/norms are HBM-bound
  noise on the MXU);
- a matmul [m,k]x[k,n] costs 2*m*k*n;
- token counts are REAL (mask-counted) tokens — padding rows burn MXU
  cycles but do no useful work, so they lower MFU, which is the point;
- attention-score terms use the PADDED sequence width: each real token
  genuinely attends over the padded row on the device;
- causal self-attention is priced at full width (the kernels compute
  full blocks; no causal-sparsity discount);
- train = 3x forward (activation grads + weight grads each replay every
  forward matmul once).
"""

from __future__ import annotations

from typing import Optional


def transformer_train_flops(emb: int, ffn: int, enc_depth: int,
                            dec_depth: int, vocab: int,
                            src_tokens: float, trg_tokens: float,
                            src_width: int, trg_width: int) -> float:
    """Matmul FLOPs for ONE training step (fwd+bwd) of an encoder-decoder
    transformer on a batch with the given real token counts and padded
    widths. Tied embeddings are assumed (the output projection is the
    only embedding matmul priced; input embedding is a gather)."""
    d, f = float(emb), float(ffn)
    # encoder layer, per src token: QKV+out projections (4 matmuls of
    # d x d) + FFN (d x f, f x d); scores+values: QK^T and AV, each
    # 2*width*d per token.
    enc_tok = 8 * d * d + 4 * d * f + 4 * src_width * d
    enc = enc_depth * src_tokens * enc_tok
    # decoder layer: self-attn like the encoder (trg width); cross-attn
    # Q+out projections per trg token, K+V projections per SRC token
    # (computed once over encoder output), scores over src width.
    dec_tok = (8 * d * d + 4 * trg_width * d      # self-attn
               + 4 * d * d + 4 * src_width * d    # cross-attn Q/out+scores
               + 4 * d * f)                       # FFN
    dec_kv = 4 * d * d * src_tokens               # cross K/V per src token
    dec = dec_depth * (trg_tokens * dec_tok + dec_kv)
    logits = 2 * d * float(vocab) * trg_tokens
    return 3.0 * (enc + dec + logits)


def transformer_serve_flops(emb: int, ffn: int, enc_depth: int,
                            dec_depth: int, vocab: int,
                            src_tokens: float, trg_tokens: float,
                            src_width: int, trg_width: int,
                            beam: int = 1) -> float:
    """Matmul FLOPs for serving ONE batch: encoder forward over the real
    source tokens plus incremental beam decode of the real target
    tokens. The live-MFU companion of :func:`transformer_train_flops`
    (obs/perf.py — ISSUE 9).

    Conventions as above (real tokens, padded widths for attention
    spans), plus decode-specifics:
    - every generated target token is paid ``beam`` times (each beam
      hypothesis runs the full decoder stack per step);
    - self-attention over the growing cache is priced at the AVERAGE
      past length ``trg_width/2`` (the cache grows 0..trg_width);
    - cross K/V projections are paid once per source token (cached);
    - the output projection prices the full vocab (no shortlist
      discount — the gauge should read LOW when a shortlist would
      help, same reasoning as padding lowering MFU).
    """
    d, f = float(emb), float(ffn)
    enc_tok = 8 * d * d + 4 * d * f + 4 * src_width * d
    enc = enc_depth * src_tokens * enc_tok
    dec_tok = (8 * d * d + 4 * (trg_width / 2.0) * d   # self + cache
               + 4 * d * d + 4 * src_width * d         # cross Q/out+scores
               + 4 * d * f)                            # FFN
    rows = max(1, int(beam))
    dec = dec_depth * (trg_tokens * rows * dec_tok
                       + 4 * d * d * src_tokens)       # cross K/V once
    logits = 2 * d * float(vocab) * trg_tokens * rows
    return enc + dec + logits


# Published peak dense bf16 FLOPs/s per JAX DEVICE. On v2/v3 a chip has
# two TensorCores and jax.devices() lists each core as its own device,
# so the per-device peak is HALF the published per-chip number; v4
# onward is megacore (one device per chip). Substring match on jax
# Device.device_kind; None = unknown generation (mfu is reported as
# null rather than guessed).
_PEAK_BF16 = (
    ("v6 lite", 918e12),   # Trillium / v6e
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),   # v5e
    ("v5e", 197e12),
    ("v4 lite", 138e12),   # v4i inference chip
    ("v4", 275e12),
    ("v3", 61.5e12),       # 123 TFLOP/chip, 2 cores/chip → per device
    ("v2", 22.5e12),       # 45 TFLOP/chip, 2 cores/chip → per device
)


def peak_bf16_flops(device_kind: str) -> Optional[float]:
    """Peak dense bf16 FLOPs/s for ONE jax device of the given
    ``device_kind``, or None for unrecognized kinds (e.g. the axon
    tunnel may report a virtual name; CPU always returns None). Matches
    bench.py's per-device throughput accounting (value / len(devices))."""
    kind = (device_kind or "").lower()
    if "tpu" not in kind:
        return None
    for tag, peak in _PEAK_BF16:
        if tag in kind:
            return peak
    return None


# Published HBM bandwidth per JAX device, bytes/s (same per-core halving
# for v2/v3 as _PEAK_BF16).
_HBM_BW = (
    ("v6 lite", 1640e9), ("v6e", 1640e9),
    ("v5p", 2765e9),
    ("v5 lite", 819e9), ("v5e", 819e9),
    ("v4 lite", 614e9), ("v4", 1228e9),
    ("v3", 450e9),     # 900 GB/s/chip, 2 cores
    ("v2", 350e9),     # 700 GB/s/chip, 2 cores
)


def hbm_bandwidth(device_kind: str) -> Optional[float]:
    kind = (device_kind or "").lower()
    if "tpu" not in kind:
        return None
    for tag, bw in _HBM_BW:
        if tag in kind:
            return bw
    return None


# ---------------------------------------------------------------------------
# Beam-decode step roofline (VERDICT r3 #5: prove the int8/shortlist
# decode levers analytically — when does each help, and what should the
# defaults be?)
# ---------------------------------------------------------------------------

def decode_step_cost(emb: int, ffn: int, dec_depth: int, vocab: int,
                     rows: int, t_past: int, src_width: int,
                     weight_bytes: float = 2.0,
                     shortlist: int = 0,
                     cache_bytes: float = 2.0) -> dict:
    """FLOPs and HBM bytes for ONE incremental decoder step over ``rows``
    flattened batch×beam rows (translator/beam_search.py's hot loop;
    reference: the per-step scorer->step in beam_search.cpp).

    Decode is the opposite regime from training: each weight matrix is
    read once from HBM to process only `rows` tokens, so arithmetic
    intensity per weight is 2*rows/weight_bytes FLOPs/byte — tiny next
    to a TPU's ~200 FLOPs/byte ridge unless rows is in the hundreds.
    That makes the WEIGHT-BYTES column, not FLOPs, the roofline term
    that int8 (halving weight_bytes vs bf16) and the shortlist (logits
    table V → K rows) actually move.

    Returns a dict of flops, weight_bytes, cache_bytes, total hbm bytes.
    ``shortlist`` > 0 prices the output projection at that many vocab
    rows instead of `vocab`.
    """
    d, f, r = float(emb), float(ffn), float(rows)
    v_out = float(shortlist) if shortlist else float(vocab)
    # per-row matmul FLOPs: self QKV/out + cross Q/out + FFN + logits;
    # attention scores/values over the cached past and the source
    flops_row = (8 * d * d             # self-attn projections
                 + 4 * t_past * d      # self scores+values over cache
                 + 4 * d * d           # cross-attn Q + out
                 + 4 * src_width * d   # cross scores+values
                 + 4 * d * f)          # FFN
    flops = dec_depth * r * flops_row + 2 * d * v_out * r
    # weights read once per step regardless of rows
    w_layer = (4 * d * d + 2 * d * d + 2 * d * f)   # self(QKVO)+cross(QO)+FFN
    # cross K/V projections are priced in the encoder phase (computed
    # once), but their weights still stream per step only if the layer
    # re-reads them — they don't: cross K/V are cached. Logits table:
    # full vocab, or the gathered shortlist slice.
    w_bytes = (dec_depth * w_layer + d * v_out) * weight_bytes
    # KV cache: read the whole past for every row, append one entry
    kv = dec_depth * r * (2 * t_past + 2) * d * cache_bytes
    return {
        "flops": flops,
        "weight_bytes": w_bytes,
        "kv_bytes": kv,
        "hbm_bytes": w_bytes + kv,
    }


def decode_step_time(cost: dict, peak_flops: float, bw: float,
                     int8_matmul_speedup: float = 1.0) -> float:
    """Roofline time for one decode step: max of the compute and memory
    terms (perfect overlap assumed — optimistic on both, so RATIOS
    between configs are meaningful even where absolutes are not)."""
    return max(cost["flops"] / (peak_flops * int8_matmul_speedup),
               cost["hbm_bytes"] / bw)


def decode_defaults_hint(emb: int, ffn: int, dec_depth: int, vocab: int,
                         rows: int, device_kind: str,
                         int8_on: bool, shortlist_on: bool,
                         t_past: int = 16, src_width: int = 24,
                         shortlist_k: int = 256) -> Optional[str]:
    """The decode-defaults decision (docs/DECODE_ROOFLINE.md) applied to a
    concrete run: if this device/batch sits in the weight-bound regime and
    an available lever (int8 weights via marian-conv, lexical shortlist)
    is off, return a one-line recommendation with the roofline speedup;
    None when the config is already right or the device is unknown/CPU."""
    peak = peak_bf16_flops(device_kind)
    bw = hbm_bandwidth(device_kind)
    if peak is None or bw is None or (int8_on and shortlist_on):
        return None
    cur = decode_step_cost(emb, ffn, dec_depth, vocab, rows, t_past,
                           src_width,
                           weight_bytes=1.0 if int8_on else 2.0,
                           shortlist=shortlist_k if shortlist_on else 0)
    t_cur = decode_step_time(cur, peak, bw)
    # each missing lever is judged on its OWN projected gain — the
    # shortlist also cuts logits FLOPs, so it can pay even when the step
    # is compute-bound (int8 cannot: it only moves bytes)
    missing = []
    for on, wb, sl, label in (
            (int8_on, 1.0, shortlist_k if shortlist_on else 0,
             "int8 weights (marian-conv --gemm-type int8tpu)"),
            (shortlist_on, 1.0 if int8_on else 2.0, shortlist_k,
             "a lexical shortlist (--shortlist)")):
        if on:
            continue
        c = decode_step_cost(emb, ffn, dec_depth, vocab, rows, t_past,
                             src_width, weight_bytes=wb, shortlist=sl)
        if t_cur / decode_step_time(c, peak, bw) >= 1.15:
            missing.append(label)
    if not missing:
        return None
    best = decode_step_cost(emb, ffn, dec_depth, vocab, rows, t_past,
                            src_width, weight_bytes=1.0,
                            shortlist=shortlist_k)
    gain = t_cur / decode_step_time(best, peak, bw)
    bound = ("HBM-weight-bound"
             if cur["hbm_bytes"] / bw > cur["flops"] / peak
             else "compute-bound")
    return (f"decode is {bound} on {device_kind} at "
            f"{rows} batchxbeam rows; enabling {' and '.join(missing)} "
            f"projects ~{gain:.1f}x on the analytic roofline "
            f"(docs/DECODE_ROOFLINE.md)")


def decode_lever_report(emb: int, ffn: int, dec_depth: int, vocab: int,
                        t_past: int, src_width: int, shortlist_k: int,
                        device_kind: str = "TPU v4") -> dict:
    """Evaluate the decode levers (int8 weights, lexical shortlist) across
    batch×beam row counts on the analytic roofline. Returns
    ``ridge_flops_per_byte``, ``break_even_rows`` (the row count above
    which the bf16 full-vocab step stops being memory-bound — below it
    the bandwidth levers pay), and per-rows speedups vs bf16/full-vocab.

    The defaults decision this feeds (docs/DECODE_ROOFLINE.md): int8 and
    the shortlist are BANDWIDTH levers — they help exactly while the step
    is weight-bound (rows below the ridge point), which covers every
    realistic beam-decode batch on TPU; marian-conv therefore defaults to
    int8 + shortlist-compatible output, and the CPU dry-run inversion
    (VERDICT r3 weak #3) is expected, not a design failure: a 1-core CPU
    is compute-bound at any batch, so int8 dequant overhead and the
    shortlist gather only add work there.
    """
    peak = peak_bf16_flops(device_kind) or 275e12
    bw = hbm_bandwidth(device_kind) or 1228e9
    ridge = peak / bw                       # FLOPs/byte at the roofline knee
    # closed-form break-even: flops = A*rows, hbm = W + C*rows →
    # memory-bound iff W + C*r > A*r/ridge, i.e. r < W / (A/ridge - C)
    one = decode_step_cost(emb, ffn, dec_depth, vocab, 1, t_past,
                           src_width, weight_bytes=2.0)
    a, w, c = one["flops"], one["weight_bytes"], one["kv_bytes"]
    denom = a / ridge - c
    break_even = float("inf") if denom <= 0 else w / denom
    out = {"device": device_kind, "ridge_flops_per_byte": ridge,
           "break_even_rows": break_even, "rows": {}}
    for rows in (1, 8, 32, 64, 128, 256, 512, 1024, 4096):
        base = decode_step_cost(emb, ffn, dec_depth, vocab, rows,
                                t_past, src_width, weight_bytes=2.0)
        i8 = decode_step_cost(emb, ffn, dec_depth, vocab, rows,
                              t_past, src_width, weight_bytes=1.0)
        sl = decode_step_cost(emb, ffn, dec_depth, vocab, rows,
                              t_past, src_width, weight_bytes=2.0,
                              shortlist=shortlist_k)
        i8sl = decode_step_cost(emb, ffn, dec_depth, vocab, rows,
                                t_past, src_width, weight_bytes=1.0,
                                shortlist=shortlist_k)
        t0 = decode_step_time(base, peak, bw)
        out["rows"][rows] = {
            "memory_bound": base["hbm_bytes"] / bw
                            > base["flops"] / peak,
            "int8_speedup": t0 / decode_step_time(i8, peak, bw),
            "shortlist_speedup": t0 / decode_step_time(sl, peak, bw),
            "int8_shortlist_speedup": t0 / decode_step_time(i8sl, peak, bw),
        }
    return out
