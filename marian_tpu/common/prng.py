"""PRNG-key discipline — replaces the reference's per-device cuRAND generators
(src/tensors/gpu/backend.gpu.cpp seeds). One root key derived from --seed;
every consumer folds in a static stream id + step so dropout masks etc. are
reproducible and resume-exact."""

from __future__ import annotations

import time
from typing import Optional

import jax


def root_key(seed: int) -> jax.Array:
    if seed == 0:
        seed = int(time.time_ns() % (2**31))
    return jax.random.key(seed)


# Stable stream ids (fold_in constants) for the different consumers.
STREAM_SHUFFLE = 1
STREAM_DROPOUT = 2
STREAM_INIT = 3
STREAM_SAMPLING = 4
STREAM_SPM = 5


def stream(key: jax.Array, stream_id: int, step: Optional[int] = None) -> jax.Array:
    k = jax.random.fold_in(key, stream_id)
    if step is not None:
        k = jax.random.fold_in(k, step)
    return k
