"""Runtime ownership witness: the dynamic half of mtlint's
resource-ownership analysis (ISSUE 15) — the lockdep move, applied to
resource lifetimes.

The static side (marian_tpu/analysis/ownership.py + the MT-OWN rule
family) enumerates the acquire/release/transfer SITES of the refcounted
``KVPool`` and derives which (acquire-site → release-site) pairings are
possible. Its documented blind spots — owners built from expressions,
calls through locals, exception edges outside the modeled raisers — are
exactly where a page leak would hide from it. This module keeps the
model honest the same way ``MARIAN_LOCKDEP=1`` keeps the lock lattice
honest: record what actually ran, and cross-check.

With ``MARIAN_OWNWIT=1`` in the environment (read at pool-construction
time; tests/conftest.py arms it for the whole tier-1 process), every
``KVPool`` acquire/release/transfer records the CALL SITE that drove it
— the nearest stack frame inside ``marian_tpu/`` outside the
instrumented modules, identified ``<rel>::<co_name>``, exactly the
identity the static site scan derives. A successful release/transfer of
an owner records the pairing (its acquire sites → this release site).

The verdict (:func:`check_against_static`, asserted at module teardown
of the tier-1 serving/iteration/beam/prefix suites):

- an observed acquire or release site the static registry never
  modeled → blind spot; FAIL (extend analysis/ownership.py, never
  baseline it);
- an observed (acquire-site → release-site) pairing absent from the
  static ownership graph → same.

Sites outside ``marian_tpu/`` (tests driving a pool directly) record as
``<external>`` and are exempt from the cross-check — the static
analysis does not model test code either; engine-driven traffic is what
the witness audits. Leak detection is separate from the pairing check
(live resources mid-suite are normal): :func:`live_owners` /
:func:`check_balanced` report owners still holding references — the
``pool.release_drop`` faultpoint drill suppresses one real release and
the drill test asserts the witness (and the pool auditor) catch it.

Without ``MARIAN_OWNWIT=1`` nothing is recorded and the pool pays one
attribute read per verb. Stdlib-only; imports nothing from the analyzed
layers.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

ENV_VAR = "MARIAN_OWNWIT"

EXTERNAL_SITE = "<external>"


def enabled() -> bool:
    return os.environ.get(ENV_VAR, "") == "1"


_TOKENS = itertools.count(1)


def new_token() -> int:
    """Process-unique container identity for the live-owner table. A
    raw ``id(pool)`` can be REUSED after the pool is collected — a
    stale live entry would then pair an old pool's acquire site with a
    new pool's release site and fabricate a witness violation."""
    return next(_TOKENS)


# -- observed model ----------------------------------------------------------
# Guarded by _WITNESS_LOCK — a plain lock, deliberately NOT lockdep-
# witnessed and excluded from lock discovery (callgraph
# _INSTRUMENTATION_MODULES): it is taken while KVPool._lock-adjacent
# code runs and is instrumentation, not part of the modeled lattice.

_WITNESS_LOCK = threading.Lock()
# cls -> {(acquire_site, release_site) -> thread name (first observer)}
_PAIRS: Dict[str, Dict[Tuple[str, str], str]] = {}
_ACQ_SITES: Dict[str, Set[str]] = {}
_REL_SITES: Dict[str, Set[str]] = {}
# (cls, id(container), owner-repr) -> set of acquire sites still live
_LIVE: Dict[Tuple[str, int, str], Set[str]] = {}

# frames inside these files are instrumentation, not call sites
_SKIP_SUFFIXES = ("common/ownwit.py", "common\\ownwit.py",
                  "ops/pallas/kv_pool.py", "ops\\pallas\\kv_pool.py")

_ROOT: Optional[str] = None


def _find_root() -> Optional[str]:
    global _ROOT
    if _ROOT is None:
        cur = os.path.dirname(os.path.abspath(__file__))
        for _ in range(6):
            if os.path.exists(os.path.join(cur, "pyproject.toml")):
                _ROOT = cur
                break
            cur = os.path.dirname(cur)
    return _ROOT


def _site() -> str:
    """The acting call site: nearest non-instrumentation frame. Frames
    under <root>/marian_tpu resolve to '<rel>::<co_name>' (the static
    model's site identity); anything else — tests, library callers —
    is EXTERNAL_SITE, exempt from the cross-check."""
    root = _find_root()
    f = sys._getframe(2)
    while f is not None:
        fname = f.f_code.co_filename
        norm = fname.replace("\\", "/")
        if not norm.endswith(_SKIP_SUFFIXES[0]) \
                and not norm.endswith(_SKIP_SUFFIXES[2]):
            if root is not None:
                try:
                    rel = os.path.relpath(fname, root).replace("\\", "/")
                except ValueError:          # different drive (windows)
                    rel = ""
                if rel.startswith("marian_tpu/"):
                    return f"{rel}::{f.f_code.co_name}"
            return EXTERNAL_SITE
        f = f.f_back
    return EXTERNAL_SITE


def _key(cls: str, container, owner) -> Tuple[str, int, str]:
    tok = container if isinstance(container, int) else id(container)
    return (cls, tok, repr(owner))


def note_acquire(cls: str, container, owner) -> None:
    """A fresh or extended claim for ``owner`` (claim/claim_extra/share,
    or a retable that created/extended the owner)."""
    site = _site()
    with _WITNESS_LOCK:
        _ACQ_SITES.setdefault(cls, set()).add(site)
        _LIVE.setdefault(_key(cls, container, owner), set()).add(site)


def note_release(cls: str, container, owner) -> None:
    """Owner dropped every reference (release, retable-to-empty):
    records the (acquire-site → release-site) pairings."""
    site = _site()
    thread = threading.current_thread().name
    with _WITNESS_LOCK:
        _REL_SITES.setdefault(cls, set()).add(site)
        acq = _LIVE.pop(_key(cls, container, owner), None) or set()
        pairs = _PAIRS.setdefault(cls, {})
        for a in acq:
            pairs.setdefault((a, site), thread)


def note_transfer(cls: str, container, src_owner, dst_owner) -> None:
    """References changed hands (KVPool.transfer): pairs the source's
    acquire sites with this site, and the destination becomes live as
    acquired HERE — the prefix-cache adoption shape."""
    site = _site()
    thread = threading.current_thread().name
    with _WITNESS_LOCK:
        _REL_SITES.setdefault(cls, set()).add(site)
        _ACQ_SITES.setdefault(cls, set()).add(site)
        acq = _LIVE.pop(_key(cls, container, src_owner), None) or set()
        pairs = _PAIRS.setdefault(cls, {})
        for a in acq:
            pairs.setdefault((a, site), thread)
        _LIVE.setdefault(_key(cls, container, dst_owner), set()).add(site)


def drop_container(cls: str, container) -> None:
    """A whole pool is being discarded (engine teardown): forget its
    live owners — their lifetime ends with the container, which is not
    a leak the witness should carry across tests."""
    cid = container if isinstance(container, int) else id(container)
    with _WITNESS_LOCK:
        for k in [k for k in _LIVE if k[0] == cls and k[1] == cid]:
            del _LIVE[k]


# -- inspection / verdict ----------------------------------------------------

def observed_pairs(cls: str) -> Dict[Tuple[str, str], str]:
    with _WITNESS_LOCK:
        return dict(_PAIRS.get(cls, {}))


def observed_sites(cls: str) -> Tuple[Set[str], Set[str]]:
    with _WITNESS_LOCK:
        return (set(_ACQ_SITES.get(cls, set())),
                set(_REL_SITES.get(cls, set())))


def live_owners(cls: str) -> List[Tuple[str, List[str]]]:
    """(owner repr, acquire sites) for every owner still holding
    references — the leak-drill surface (a suppressed release leaves
    its owner here)."""
    with _WITNESS_LOCK:
        return sorted((k[2], sorted(sites))
                      for k, sites in _LIVE.items() if k[0] == cls)


def check_balanced(cls: str) -> List[str]:
    """Violations for resources still live — used by the seeded-leak
    drill and by scopes that expect a drained pool; NOT part of the
    suite-teardown cross-check (live resources mid-suite are normal)."""
    return [f"{cls} owner {owner} acquired at "
            f"{', '.join(sites) or EXTERNAL_SITE} was never "
            f"released or transferred (leak)"
            for owner, sites in live_owners(cls)]


def reset() -> None:
    """Forget everything observed so far (tests)."""
    with _WITNESS_LOCK:
        _PAIRS.clear()
        _ACQ_SITES.clear()
        _REL_SITES.clear()
        _LIVE.clear()


def check(graph) -> List[str]:
    """Violations of the static model by what actually ran, against an
    ``analysis.ownership.OwnershipGraph``. Empty list = every observed
    site and pairing is modeled. ``<external>`` sites (direct library
    use from tests) are exempt by design."""
    violations: List[str] = []
    from ..analysis.ownership import GRAPH_CLASSES
    for cls in GRAPH_CLASSES:
        static_acq = graph.acquire_sites(cls)
        static_rel = graph.release_sites(cls)
        obs_acq, obs_rel = observed_sites(cls)
        for s in sorted(obs_acq - {EXTERNAL_SITE}):
            if s not in static_acq:
                violations.append(
                    f"observed {cls} ACQUIRE site {s} is unknown to the "
                    f"static ownership model — analysis/ownership.py's "
                    f"verb registry or site scan has a blind spot; "
                    f"extend the model, do not baseline this")
        for s in sorted(obs_rel - {EXTERNAL_SITE}):
            if s not in static_rel:
                violations.append(
                    f"observed {cls} RELEASE site {s} is unknown to the "
                    f"static ownership model — extend "
                    f"analysis/ownership.py, do not baseline this")
        static_pairs = graph.pairs.get(cls, set())
        for (a, r), thread in sorted(observed_pairs(cls).items()):
            if a == EXTERNAL_SITE or r == EXTERNAL_SITE:
                continue
            if a not in static_acq or r not in static_rel:
                continue          # already reported as an unknown site
            if (a, r) not in static_pairs:
                violations.append(
                    f"observed {cls} ownership pairing {a} -> {r} (first "
                    f"seen on thread {thread!r}) is absent from the "
                    f"static ownership graph — the model never derived "
                    f"this handoff; extend analysis/ownership.py")
    return violations


def check_against_static(root) -> List[str]:
    """:func:`check` against the ownership graph built from the repo at
    ``root`` — the cross-check the tier-1 serving/iteration/beam/prefix
    suites assert at module teardown. The analysis layer is
    stdlib-only, so this never imports jax."""
    from ..analysis.ownership import static_ownership_graph
    return check(static_ownership_graph(root))
