"""Wall-clock timers (reference: src/common/timer.h :: timer::Timer)."""

from __future__ import annotations

import time


class Timer:
    def __init__(self):
        self.start()

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def elapsed(self) -> float:
        """Seconds since start()."""
        return time.perf_counter() - self._t0
