"""Frequency/amount specs like ``100u``, ``10e``, ``1Mt`` (reference:
src/common/scheduling_parameter.h :: SchedulingParameter::parse).

Units: t = target labels, e = epochs, u = updates (default when no unit).
Multipliers: k/K = 1e3, m/M = 1e6, g/G = 1e9 (Marian accepts K/M/G; we accept
both cases).
"""

from __future__ import annotations

import dataclasses
from enum import Enum
from typing import Union


class SchedulingUnit(Enum):
    TRG_LABELS = "t"
    EPOCHS = "e"
    UPDATES = "u"


@dataclasses.dataclass(frozen=True)
class SchedulingParameter:
    n: int = 0
    unit: SchedulingUnit = SchedulingUnit.UPDATES

    @classmethod
    def parse(cls, spec: Union[str, int, float, "SchedulingParameter"]) -> "SchedulingParameter":
        if isinstance(spec, SchedulingParameter):
            return spec
        if isinstance(spec, (int, float)):
            return cls(int(spec), SchedulingUnit.UPDATES)
        s = str(spec).strip()
        if not s:
            return cls(0, SchedulingUnit.UPDATES)
        unit = SchedulingUnit.UPDATES
        if s[-1] in "teu":
            unit = SchedulingUnit(s[-1])
            s = s[:-1]
        mult = 1
        if s and s[-1] in "kKmMgG":
            mult = {"k": 10**3, "m": 10**6, "g": 10**9}[s[-1].lower()]
            s = s[:-1]
        if not s:
            raise ValueError(f"Malformed scheduling parameter '{spec}'")
        return cls(int(float(s) * mult), unit)

    def __bool__(self) -> bool:
        return self.n != 0

    def __str__(self) -> str:
        return f"{self.n}{self.unit.value}"

    def mult(self, factor: float) -> "SchedulingParameter":
        return SchedulingParameter(int(self.n * factor), self.unit)
