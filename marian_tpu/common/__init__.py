from .options import Options
from .config_parser import ConfigParser, parse_options
from .scheduling_parameter import SchedulingParameter, SchedulingUnit
from . import faultpoints, io, logging, prng, signal_handling, timer

__all__ = [
    "Options", "ConfigParser", "parse_options",
    "SchedulingParameter", "SchedulingUnit",
    "faultpoints", "io", "logging", "prng", "signal_handling", "timer",
]
