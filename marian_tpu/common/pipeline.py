"""Depth-1 dispatch/finalize pipelining over device batches.

The reference hides per-batch host work (n-best extraction, score
bookkeeping, vector copy-out) behind worker thread pools
(src/translator/translator.h); on TPU the same overlap falls out of XLA
async dispatch — dispatch batch i+1's jitted computation BEFORE forcing
batch i's results, and every batch's host cost except the last hides
behind device compute. One shared skeleton so the translator, rescorer,
embedder, and bench loops cannot drift apart."""

from __future__ import annotations

from typing import Callable, Iterable, TypeVar

B = TypeVar("B")
H = TypeVar("H")


def pipelined(batches: Iterable[B],
              dispatch: Callable[[B], H],
              finalize: Callable[[B, H], None]) -> None:
    """For each batch: ``handle = dispatch(batch)`` (must only ENQUEUE
    device work — anything that blocks defeats the overlap), then
    ``finalize(prev_batch, prev_handle)`` for the previous batch; the
    trailing batch is finalized at the end. ``finalize`` is where
    blocking (np.asarray / .collect()) belongs."""
    pending = None
    for b in batches:
        h = dispatch(b)
        if pending is not None:
            finalize(*pending)
        pending = (b, h)
    if pending is not None:
        finalize(*pending)
