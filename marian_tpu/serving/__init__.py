"""Production serving subsystem (ISSUE 1): continuous token-budget batching
scheduler, admission control/backpressure, and the dependency-free metrics
registry + /metrics /healthz /readyz endpoints shared by serve, train, and
translate."""

from .admission import AdmissionController, Overloaded
from .metrics import (Counter, Gauge, Histogram, MetricsServer, Registry,
                      REGISTRY, counter, gauge, histogram,
                      maybe_start_metrics_server)
from .scheduler import ContinuousScheduler, RequestTimeout

__all__ = [
    "AdmissionController", "Overloaded",
    "Counter", "Gauge", "Histogram", "MetricsServer", "Registry",
    "REGISTRY", "counter", "gauge", "histogram",
    "maybe_start_metrics_server",
    "ContinuousScheduler", "RequestTimeout",
]
