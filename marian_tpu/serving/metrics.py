"""Dependency-free metrics registry + Prometheus text exposition + health
endpoints (the observability layer of the serving subsystem — ISSUE 1).

The reference ships no serving metrics at all (marian_server.cpp logs
connections and nothing else); production traffic needs queue depth, batch
fill, shed counts and latency percentiles scrapeable by any Prometheus-
compatible collector. Everything here is stdlib-only — http.server for the
endpoint, threading.Lock for safety across the asyncio loop, the device
executor thread, and the scraping thread — so the registry is importable
from ANY layer (training/scheduler.py and translator/translator.py emit
through the same types as the server; one metrics vocabulary end to end).

Exposition format: https://prometheus.io/docs/instrumenting/exposition_formats/
(text format 0.0.4 — the stable plain-text one).
"""

from __future__ import annotations

import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..common import lockdep
from ..common import logging as log

# Default histogram buckets: latency-shaped (seconds), 1ms..60s. Chosen so
# one bucket table serves both the ~5ms coalescing window and multi-second
# device batches under load.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
# Ratio-shaped buckets (fill ratios, waste fractions) in [0, 1].
RATIO_BUCKETS = (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 0.95, 1.0)


def _fmt(v: float) -> str:
    """Prometheus number formatting: integers without exponent, floats as
    repr (Go-parseable); +Inf for the histogram top bucket."""
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    pairs = ",".join(
        '%s="%s"' % (n, str(v).replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n"))
        for n, v in zip(names, values))
    return "{" + pairs + "}"


class _Metric:
    """Base: name, help, optional label names; children per label values."""

    kind = "untyped"

    def __init__(self, name: str, help_: str = "",
                 labels: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(labels)
        self._lock = lockdep.make_lock("_Metric._lock")
        self._children: Dict[Tuple[str, ...], "_Metric"] = {}

    def labels(self, *values: str) -> "_Metric":
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {values}")
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._child()
                self._children[key] = child
            return child

    def _child(self) -> "_Metric":
        raise NotImplementedError

    def children(self) -> Dict[Tuple[str, ...], "_Metric"]:
        """Snapshot of label-value tuple -> child metric — the public
        read the SLO engine (obs/slo.py) uses to sum a counter across
        one label dimension without touching private state."""
        with self._lock:
            return dict(self._children)

    def _sample_lines(self, label_values: Tuple[str, ...],
                      exemplars: bool = False) -> List[str]:
        raise NotImplementedError

    def render(self, exemplars: bool = False) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            children = dict(self._children)
        if self.label_names:
            for key, child in sorted(children.items()):
                lines.extend(child._sample_lines(key, exemplars))
        else:
            lines.extend(self._sample_lines((), exemplars))
        return lines


class Counter(_Metric):
    """Monotonically increasing count (requests, sheds, timeouts...)."""

    kind = "counter"

    def __init__(self, name: str, help_: str = "",
                 labels: Sequence[str] = ()):
        super().__init__(name, help_, labels)
        self._value = 0.0

    def _child(self) -> "Counter":
        return Counter(self.name, self.help, labels=self.label_names)

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _sample_lines(self, lv: Tuple[str, ...],
                      exemplars: bool = False) -> List[str]:
        return [f"{self.name}{_label_str(self.label_names, lv)} "
                f"{_fmt(self.value)}"]


class Gauge(_Metric):
    """A value that goes up and down (queue depth, inflight batches...)."""

    kind = "gauge"

    def __init__(self, name: str, help_: str = "",
                 labels: Sequence[str] = ()):
        super().__init__(name, help_, labels)
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def _child(self) -> "Gauge":
        return Gauge(self.name, self.help, labels=self.label_names)

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def set_function(self, fn: Callable[[], float]) -> None:
        """Sample a callable at scrape time (e.g. live queue depth)."""
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a scrape must never raise
                return float("nan")
        with self._lock:
            return self._value

    def _sample_lines(self, lv: Tuple[str, ...],
                      exemplars: bool = False) -> List[str]:
        return [f"{self.name}{_label_str(self.label_names, lv)} "
                f"{_fmt(self.value)}"]


class Histogram(_Metric):
    """Cumulative-bucket histogram (latency, batch fill ratio...).

    ``observe(v, trace_id=...)`` additionally keeps the LAST trace id
    observed per bucket as an exemplar (ISSUE 8): scraping
    ``/metrics?exemplars=1`` renders OpenMetrics-style ``# {trace_id=..}``
    suffixes on the bucket series, so a p99 outlier links straight to
    its span tree on ``/tracez`` / in a flight dump. The default
    exposition stays plain text-format 0.0.4 (exemplar suffixes would
    break strict 0.0.4 parsers, including scripts/loadgen.py's scraper).
    """

    kind = "histogram"

    def __init__(self, name: str, help_: str = "",
                 labels: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_, labels)
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)   # +1 for +Inf
        # last (value, trace_id, unix_ts) per bucket — see class docstring
        self._exemplars: List[Optional[Tuple[float, str, float]]] = \
            [None] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def _child(self) -> "Histogram":
        return Histogram(self.name, self.help, labels=self.label_names,
                         buckets=self.buckets)

    def observe(self, v: float, trace_id: Optional[str] = None) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    if trace_id:
                        self._exemplars[i] = (float(v), str(trace_id),
                                              time.time())
                    return
            self._counts[-1] += 1
            if trace_id:
                self._exemplars[-1] = (float(v), str(trace_id), time.time())

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def mean(self) -> float:
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def snapshot(self) -> Tuple[Tuple[float, ...], List[int], int, float]:
        """(bucket edges, per-bucket counts incl. the +Inf tail, total
        count, sum) — one consistent read for burn-rate math
        (obs/slo.py: how many observations sat at or under a latency
        objective's bucket edge)."""
        with self._lock:
            return self.buckets, list(self._counts), self._count, self._sum

    def _sample_lines(self, lv: Tuple[str, ...],
                      exemplars: bool = False) -> List[str]:
        with self._lock:
            counts, total, s = list(self._counts), self._count, self._sum
            exs = list(self._exemplars) if exemplars else None
        lines = []
        cum = 0
        edges = list(self.buckets) + [float("inf")]
        for i, (c, edge) in enumerate(zip(counts, edges)):
            cum += c
            le = _label_str(self.label_names + ("le",), lv + (_fmt(edge),))
            line = f"{self.name}_bucket{le} {cum}"
            if exs is not None and exs[i] is not None:
                ev, etid, ets = exs[i]
                line += (f' # {{trace_id="{etid}"}} {_fmt(ev)} '
                         f"{ets:.3f}")
            lines.append(line)
        ls = _label_str(self.label_names, lv)
        lines.append(f"{self.name}_sum{ls} {_fmt(s)}")
        lines.append(f"{self.name}_count{ls} {total}")
        return lines


class Registry:
    """Named metric collection; get-or-create semantics so any layer can
    declare its series idempotently (re-instantiating a Scheduler or a
    Translate in one process must not collide)."""

    def __init__(self):
        self._lock = lockdep.make_lock("Registry._lock")
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help_: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(m).__name__}, requested {cls.__name__}")
                return m
            m = cls(name, help_, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_, labels=labels)

    def gauge(self, name: str, help_: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_, labels=labels)

    def histogram(self, name: str, help_: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help_, labels=labels,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def render(self, exemplars: bool = False) -> str:
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        out: List[str] = []
        for m in metrics:
            out.extend(m.render(exemplars))
        return "\n".join(out) + "\n"


# The process-wide default registry: train, translate, and serve all emit
# here, so one /metrics endpoint exposes the whole process.
REGISTRY = Registry()

# process start, anchored at import (close enough to exec for the
# standard process_start_time_seconds semantics)
_PROCESS_START = time.time()


def _rss_bytes() -> float:
    """Resident set size. /proc on Linux; ru_maxrss (peak) as the
    portable fallback — better a labeled approximation than no memory
    signal at all. ru_maxrss units differ by platform: kilobytes on
    Linux (where /proc usually wins anyway), BYTES on macOS/BSD — an
    unconditional *1024 would read 1024x high exactly where the
    fallback is the path taken."""
    try:
        with open("/proc/self/statm", "r", encoding="ascii") as fh:
            pages = int(fh.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        try:
            import resource
            import sys
            scale = 1 if sys.platform == "darwin" else 1024
            return float(resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss * scale)
        except Exception:  # noqa: BLE001 — a scrape must never raise
            return float("nan")


def _open_fds() -> float:
    try:
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:
        return float("nan")


def register_process_metrics(registry: Optional[Registry] = None) -> None:
    """Standard process self-metrics (ISSUE 9 satellite): the scrape
    surface previously had no view of host-side health — a leaking
    server looked identical to a healthy one until the OOM killer said
    otherwise. Names follow the Prometheus client-library convention so
    stock dashboards/alerts work unchanged. Idempotent (get-or-create),
    called by every MetricsServer start."""
    r = registry if registry is not None else REGISTRY
    m_start = r.gauge(
        "process_start_time_seconds",
        "Unix time the process started (well, imported the metrics "
        "layer)")
    m_start.set(_PROCESS_START)
    m_up = r.gauge(
        "process_uptime_seconds", "Seconds since process start")
    m_up.set_function(lambda: time.time() - _PROCESS_START)
    m_rss = r.gauge(
        "process_resident_memory_bytes",
        "Resident set size (NaN where /proc and getrusage are both "
        "unavailable)")
    m_rss.set_function(_rss_bytes)
    m_fds = r.gauge(
        "process_open_fds",
        "Open file descriptors (NaN without /proc)")
    m_fds.set_function(_open_fds)


def counter(name: str, help_: str = "", labels: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help_, labels)


def gauge(name: str, help_: str = "", labels: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help_, labels)


def histogram(name: str, help_: str = "", labels: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help_, labels, buckets)


class MetricsServer:
    """/metrics + /healthz + /readyz on a ThreadingHTTPServer daemon thread.

    - /metrics — Prometheus text of the given registry.
    - /healthz — 200 as long as the process serves HTTP (liveness).
    - /readyz  — 200 only while ``ready_fn()`` is truthy (readiness: model
      loaded, warmed and live, scheduler running, not draining); 503
      otherwise, so load balancers stop routing to a replica that is
      draining — or still warming a model (ISSUE 5).
    - ``routes`` — extra path handlers (the lifecycle's /lifecyclez state
      dump and /admin/* verbs): ``path -> fn(method, query) ->
      (status, body_bytes, content_type)``. GET and POST both dispatch
      here; a raising handler is a 500, never a dead endpoint thread.
      POST (the mutating admin verbs) is accepted from LOOPBACK peers
      only — the scrape port is routinely opened cluster-wide for
      Prometheus, and rollback/pin must not be a network-wide control
      surface; operators ssh/port-forward to the replica
      (docs/DEPLOYMENT.md).

    Port 0 binds an ephemeral port (tests); ``.port`` reports the bound one.
    """

    def __init__(self, port: int, registry: Optional[Registry] = None,
                 ready_fn: Optional[Callable[[], bool]] = None,
                 host: str = "0.0.0.0",
                 routes: Optional[Dict[str, Callable[[str, str],
                                                     Tuple[int, bytes,
                                                           str]]]] = None):
        self.registry = registry if registry is not None else REGISTRY
        self.ready_fn = ready_fn or (lambda: True)
        self.routes = dict(routes or {})
        self._started = time.time()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    # ?exemplars=1: OpenMetrics-style trace-id exemplar
                    # suffixes on histogram buckets (ISSUE 8) — opt-in,
                    # the default stays strict text-format 0.0.4
                    ex = "exemplars=1" in query
                    body = outer.registry.render(
                        exemplars=ex).encode("utf-8")
                    self._send(200, body,
                               "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    self._send(200, b"ok\n", "text/plain")
                elif path == "/readyz":
                    try:
                        ready = bool(outer.ready_fn())
                    except Exception:  # noqa: BLE001
                        ready = False
                    self._send(200 if ready else 503,
                               b"ready\n" if ready else b"not ready\n",
                               "text/plain")
                elif path in outer.routes:
                    self._route(path, "GET", query)
                else:
                    self._send(404, b"not found\n", "text/plain")

            def do_POST(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                if self.client_address[0] not in ("127.0.0.1", "::1",
                                                  "::ffff:127.0.0.1"):
                    self._send(403, b"admin verbs are loopback-only\n",
                               "text/plain")
                elif path in outer.routes:
                    self._route(path, "POST", query)
                else:
                    self._send(404, b"not found\n", "text/plain")

            def _route(self, path: str, method: str, query: str) -> None:
                try:
                    code, body, ctype = outer.routes[path](method, query)
                except Exception as e:  # noqa: BLE001 — endpoint stays up
                    code, body, ctype = (500, f"error: {e}\n".encode(),
                                         "text/plain")
                self._send(code, body, ctype)

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes are not log-worthy
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="metrics-http")

    def start(self) -> "MetricsServer":
        # any scrape surface gets the standard process self-metrics
        # (ISSUE 9 satellite) — host-side health next to the app series
        register_process_metrics(self.registry)
        self._thread.start()
        log.info("Metrics endpoint on port {} (/metrics /healthz /readyz)",
                 self.port)
        return self

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass


def maybe_start_metrics_server(options,
                               ready_fn: Optional[Callable[[], bool]] = None,
                               routes: Optional[Dict] = None
                               ) -> Optional[MetricsServer]:
    """--metrics-port PORT (0 = off): start the scrape endpoint for any
    long-running entry point (server, training). Failure to bind degrades
    to a warning — observability must never take down the serving path."""
    port = int(options.get("metrics-port", 0) or 0)
    if port <= 0:
        return None
    try:
        return MetricsServer(port, ready_fn=ready_fn, routes=routes).start()
    except OSError as e:
        log.warn("--metrics-port {}: failed to bind ({}); metrics endpoint "
                 "disabled", port, e)
        return None
