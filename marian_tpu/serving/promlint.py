"""Prometheus/OpenMetrics text-format lint (ISSUE 9 satellite).

The metrics registry renders text-format 0.0.4 by hand (metrics.py);
every new emitter is a chance to silently break parseability — an
unescaped label value, a histogram whose ``+Inf`` bucket disagrees with
``_count``, a sample emitted before its ``# TYPE``. Collectors differ in
how loudly they fail on such output (some drop the whole scrape), so
the tier-1 suite lints a REAL ``/metrics`` scrape (default and
``?exemplars=1``) with this module: emitters cannot rot the exposition
format without a test going red.

``lint_metrics_text(text)`` returns a list of problem strings (empty =
clean). Checks:

- ``# HELP``/``# TYPE`` comment shape; at most one TYPE per family,
  declared before the family's first sample;
- metric/label name charset, label-value escaping, float-parseable
  sample values (``+Inf``/``-Inf``/``NaN`` allowed);
- every sample belongs to a declared family (histograms own their
  ``_bucket``/``_sum``/``_count`` suffixes);
- histogram integrity: ``le`` present on buckets, cumulative bucket
  counts non-decreasing, ``+Inf`` bucket present and equal to
  ``_count``, ``_sum``/``_count`` present;
- no duplicate series (same name + label set);
- exemplar suffixes (``# {...} value [ts]``) only with
  ``allow_exemplars=True`` and only on histogram bucket samples — the
  default exposition must stay strict 0.0.4.

Stdlib-only, independent of the registry implementation — it lints the
bytes a collector would see, not our objects.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Set, Tuple

METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_value(tok: str) -> Optional[float]:
    t = tok.strip()
    if t in ("+Inf", "Inf"):
        return math.inf
    if t == "-Inf":
        return -math.inf
    if t == "NaN":
        return math.nan
    try:
        return float(t)
    except ValueError:
        return None


def _parse_labels(body: str) -> Optional[List[Tuple[str, str]]]:
    """Parse `a="x",b="y"` honoring \\" escapes; None on malformed.
    Pairs MUST be comma-separated (`{a="x" b="y"}` or `{a="x"b="y"}`
    are rejected — real Prometheus parsers fail the whole scrape on
    them, which is exactly the breakage this lint exists to catch);
    a trailing comma is legal, per the text format."""
    out: List[Tuple[str, str]] = []
    i, n = 0, len(body)
    while i < n:
        if out:
            if body[i] != ",":
                return None      # missing separator between pairs
            i += 1
        while i < n and body[i] == " ":
            i += 1
        if i >= n:
            break                # trailing comma
        eq = body.find("=", i)
        if eq < 0:
            return None
        name = body[i:eq]
        if not LABEL_NAME_RE.match(name):
            return None
        if eq + 1 >= n or body[eq + 1] != '"':
            return None
        j = eq + 2
        val = []
        while j < n:
            c = body[j]
            if c == "\\":
                if j + 1 >= n:
                    return None
                val.append(body[j + 1])
                j += 2
                continue
            if c == '"':
                break
            val.append(c)
            j += 1
        else:
            return None
        out.append((name, "".join(val)))
        i = j + 1
    return out


def _split_sample(line: str) -> Optional[Tuple[str, str, str]]:
    """-> (name, label body or '', rest-after-labels) — None on shape
    errors (unbalanced braces, missing value)."""
    if "{" in line:
        name, _, tail = line.partition("{")
        depth_end = _find_close(tail)
        if depth_end < 0:
            return None
        return name.strip(), tail[:depth_end], tail[depth_end + 1:].strip()
    parts = line.split(None, 1)
    if len(parts) < 2:
        return None
    return parts[0], "", parts[1].strip()


def _find_close(tail: str) -> int:
    in_str = False
    i = 0
    while i < len(tail):
        c = tail[i]
        if in_str:
            if c == "\\":
                i += 2
                continue
            if c == '"':
                in_str = False
        elif c == '"':
            in_str = True
        elif c == "}":
            return i
        i += 1
    return -1


class _Hist:
    def __init__(self):
        self.buckets: List[Tuple[Tuple[Tuple[str, str], ...],
                                 float, float]] = []  # (labels-no-le, le, v)
        self.count: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self.sum_seen: Set[Tuple[Tuple[str, str], ...]] = set()


def lint_metrics_text(text: str, allow_exemplars: bool = False
                      ) -> List[str]:
    problems: List[str] = []
    types: Dict[str, str] = {}
    helped: Set[str] = set()
    seen_series: Set[Tuple[str, Tuple[Tuple[str, str], ...]]] = set()
    hists: Dict[str, _Hist] = {}

    def family_of(name: str) -> Optional[str]:
        if name in types:
            return name
        for suf in _HIST_SUFFIXES:
            if name.endswith(suf):
                base = name[:-len(suf)]
                if types.get(base) in ("histogram", "summary") \
                        and (suf != "_bucket"
                             or types[base] == "histogram"):
                    return base
        return None

    for ln, raw in enumerate(text.splitlines(), 1):
        if not raw.strip():
            continue
        if raw.startswith("#"):
            parts = raw.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                mname = parts[2]
                if not METRIC_NAME_RE.match(mname):
                    problems.append(f"line {ln}: bad metric name in "
                                    f"{parts[1]}: {mname!r}")
                    continue
                if parts[1] == "HELP":
                    if mname in helped:
                        problems.append(f"line {ln}: duplicate HELP for "
                                        f"{mname}")
                    helped.add(mname)
                else:
                    mtype = parts[3].strip() if len(parts) > 3 else ""
                    if mtype not in TYPES:
                        problems.append(f"line {ln}: unknown TYPE "
                                        f"{mtype!r} for {mname}")
                    if mname in types:
                        problems.append(f"line {ln}: duplicate TYPE for "
                                        f"{mname}")
                    types[mname] = mtype
            else:
                problems.append(f"line {ln}: stray comment (not HELP/"
                                f"TYPE): {raw[:60]!r}")
            continue
        split = _split_sample(raw)
        if split is None:
            problems.append(f"line {ln}: unparseable sample: {raw[:80]!r}")
            continue
        name, label_body, rest = split
        if not METRIC_NAME_RE.match(name):
            problems.append(f"line {ln}: bad sample name {name!r}")
            continue
        labels = _parse_labels(label_body) if label_body else []
        if labels is None:
            problems.append(f"line {ln}: malformed labels on {name}: "
                            f"{{{label_body}}}")
            continue
        # exemplar suffix: `value [ts] # {labels} value [ts]`
        value_part, exemplar = rest, None
        if " # " in rest or rest.startswith("# "):
            value_part, _, exemplar = rest.partition("# ")
            value_part = value_part.strip()
        toks = value_part.split()
        if not toks:
            problems.append(f"line {ln}: missing value on {name}")
            continue
        value = _parse_value(toks[0])
        if value is None:
            problems.append(f"line {ln}: unparseable value {toks[0]!r} "
                            f"on {name}")
            continue
        if len(toks) > 2 or (len(toks) == 2
                             and _parse_value(toks[1]) is None):
            problems.append(f"line {ln}: trailing garbage after value on "
                            f"{name}: {value_part!r}")
        fam = family_of(name)
        if fam is None:
            problems.append(f"line {ln}: sample {name} has no preceding "
                            f"# TYPE family")
        if exemplar is not None:
            if not allow_exemplars:
                problems.append(
                    f"line {ln}: exemplar on {name} in strict 0.0.4 "
                    f"output (only /metrics?exemplars=1 may emit them)")
            elif not name.endswith("_bucket"):
                problems.append(f"line {ln}: exemplar on non-bucket "
                                f"sample {name}")
            else:
                ex = exemplar.strip()
                m = re.match(r"^\{(.*)\}\s+(\S+)(\s+\S+)?$", ex)
                if not m or _parse_labels(m.group(1)) is None \
                        or _parse_value(m.group(2)) is None:
                    problems.append(f"line {ln}: malformed exemplar "
                                    f"{ex!r}")
        series_key = (name, tuple(sorted(labels)))
        if series_key in seen_series:
            problems.append(f"line {ln}: duplicate series {name}"
                            f"{dict(labels)}")
        seen_series.add(series_key)
        if fam is not None and types.get(fam) == "histogram" \
                and name != fam:
            h = hists.setdefault(fam, _Hist())
            base_labels = tuple(sorted((k, v) for k, v in labels
                                       if k != "le"))
            if name.endswith("_bucket"):
                le = dict(labels).get("le")
                le_v = _parse_value(le) if le is not None else None
                if le_v is None:
                    problems.append(f"line {ln}: histogram bucket "
                                    f"without a valid le label: {raw[:80]!r}")
                else:
                    h.buckets.append((base_labels, le_v, value))
            elif name.endswith("_count"):
                h.count[base_labels] = value
            elif name.endswith("_sum"):
                h.sum_seen.add(base_labels)

    for fam, h in sorted(hists.items()):
        per_child: Dict[Tuple, List[Tuple[float, float]]] = {}
        for base, le, v in h.buckets:
            per_child.setdefault(base, []).append((le, v))
        for base, rows in per_child.items():
            rows.sort(key=lambda r: r[0])
            lab = dict(base)
            last = -1.0
            for le, v in rows:
                if v < last:
                    problems.append(
                        f"{fam}{lab}: bucket counts not cumulative "
                        f"(le={le:g} has {v:g} < {last:g})")
                last = v
            if not rows or not math.isinf(rows[-1][0]):
                problems.append(f"{fam}{lab}: missing +Inf bucket")
            else:
                cnt = h.count.get(base)
                if cnt is None:
                    problems.append(f"{fam}{lab}: missing _count")
                elif rows[-1][1] != cnt:
                    problems.append(
                        f"{fam}{lab}: +Inf bucket {rows[-1][1]:g} != "
                        f"_count {cnt:g}")
            if base not in h.sum_seen:
                problems.append(f"{fam}{lab}: missing _sum")
    return problems
