"""Admission control & backpressure for the serving subsystem (ISSUE 1).

The reference marian-server accepts every connection and every frame; under
sustained overload the request queue (and the per-request futures behind
it) grows without bound until the host OOMs, while every client sees
unbounded latency. Production serving wants the opposite failure mode:
a bounded queue, an EXPLICIT cheap rejection ("shed") the client can retry
against another replica, and a drain mode that lets in-flight work finish
while a load balancer (watching /readyz) routes new traffic elsewhere.

Units are SENTENCES, not requests — a 1-sentence request and a 500-sentence
request occupy very different amounts of queue, and the device batch former
thinks in sentences too, so the bound composes with the scheduler's token
budget instead of fighting it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .. import obs
from ..common import lockdep
from . import metrics as msm


class Overloaded(RuntimeError):
    """Request shed by admission control (queue full or draining).

    Transports turn this into an explicit error reply / status — never a
    silent hang. ``retriable`` distinguishes "try again shortly / another
    replica" (queue full) from "this replica is going away" (draining)."""

    def __init__(self, message: str, retriable: bool = True):
        super().__init__(message)
        self.retriable = retriable


class AdmissionController:
    """Bounded-queue gate in front of the scheduler.

    ``depth_fn`` reports the scheduler's current queued sentence count so
    the bound tracks reality (units leave the queue when batches dispatch,
    not when requests resolve). ``max_queue_units <= 0`` disables the bound
    (the reference's behavior, kept reachable for benchmarking the
    difference)."""

    def __init__(self, max_queue_units: int,
                 depth_fn: Callable[[], int],
                 registry: Optional[msm.Registry] = None,
                 max_queue_pages: int = 0,
                 pages_fn: Optional[Callable[[], int]] = None):
        self.max_queue_units = int(max_queue_units)
        self.depth_fn = depth_fn
        # iteration mode (--batching-mode iteration): queue debt is
        # ALSO priced in KV-pool PAGES — a 500-token sentence owes far
        # more pool time than a 5-token one, which the sentence bound
        # cannot see. pages_fn reports the scheduler's live queued-page
        # debt; requests add their own page estimate at admit time.
        self.max_queue_pages = int(max_queue_pages)
        self.pages_fn = pages_fn
        # drain state crosses threads: transports admit() on the event-loop
        # thread, begin_drain() fires from a signal handler / main thread,
        # and /readyz reads `draining` from the metrics scrape thread —
        # lock discipline enforced by mtlint's guarded-by checker
        self._lock = lockdep.make_lock("AdmissionController._lock")
        self._draining = False                  # guarded-by: _lock
        self._drain_started: Optional[float] = None   # guarded-by: _lock
        # brownout ladder (serving/brownout.py, ISSUE 11): at level >= 3
        # requests below the configured priority are shed explicitly —
        # the top rung of the degradation ladder. Written by the
        # brownout evaluator thread, read at every admit.
        self._brownout_level = 0                # guarded-by: _lock
        self._brownout_min_priority = 1         # guarded-by: _lock
        r = registry if registry is not None else msm.REGISTRY
        self.m_admitted = r.counter(
            "marian_serving_admitted_sentences_total",
            "Sentences admitted into the scheduler queue")
        self.m_shed = r.counter(
            "marian_serving_shed_total",
            "Requests rejected by admission control", labels=("reason",))
        self.m_queue_limit = r.gauge(
            "marian_serving_queue_limit_sentences",
            "Configured admission bound in sentences (0 = unbounded)")
        self.m_queue_limit.set(self.max_queue_units)

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def set_brownout(self, level: int, min_priority: int = 1) -> None:
        """Arm/disarm the ladder's admission rung (brownout evaluator
        thread): at ``level >= 3`` requests with priority below
        ``min_priority`` are shed with an explicit, retriable
        !!SERVER-OVERLOADED — low lanes degrade predictably while high
        lanes keep their queue."""
        with self._lock:
            self._brownout_level = max(0, int(level))
            self._brownout_min_priority = int(min_priority)

    def _gate_state(self):
        with self._lock:
            return (self._draining, self._brownout_level,
                    self._brownout_min_priority)

    def admit(self, n_units: int, n_pages: int = 0,
              priority: int = 0) -> None:
        """Gate one request of ``n_units`` sentences (owing ``n_pages``
        KV-pool pages in iteration mode); raises Overloaded instead of
        queueing when a bound would be exceeded, the server is
        draining, or the brownout ladder sheds the request's priority
        lane. Admission is all-or-nothing per request — partial
        admission would split one client's reply across a shed
        boundary."""
        draining, b_level, b_minp = self._gate_state()
        if draining:
            self.m_shed.labels("draining").inc()
            # shed decisions land on the obs timeline so a flight dump
            # shows them next to the victims (ISSUE 8); the admit-OK hot
            # path records nothing
            obs.event("admission.shed", reason="draining", units=n_units)
            raise Overloaded("server is draining (shutting down); "
                             "retry against another replica",
                             retriable=False)
        if b_level >= 3 and priority < b_minp:
            self.m_shed.labels("brownout").inc()
            obs.event("admission.shed", reason="brownout", units=n_units,
                      priority=priority, level=b_level)
            raise Overloaded(
                f"brownout level {b_level}: priority-{priority} lane is "
                f"shed under sustained overload (lanes >= {b_minp} keep "
                f"serving); retry later or against another replica")
        if self.max_queue_units > 0:
            depth = int(self.depth_fn())
            if depth + n_units > self.max_queue_units:
                self.m_shed.labels("queue_full").inc()
                obs.event("admission.shed", reason="queue_full",
                          units=n_units, depth=depth)
                raise Overloaded(
                    f"queue full ({depth}/{self.max_queue_units} sentences "
                    f"queued, request adds {n_units}); retry later")
        if self.max_queue_pages > 0 and self.pages_fn is not None:
            pages = int(self.pages_fn())
            if pages + n_pages > self.max_queue_pages:
                self.m_shed.labels("pages_full").inc()
                obs.event("admission.shed", reason="pages_full",
                          units=n_units, pages=pages)
                raise Overloaded(
                    f"queue page debt full ({pages}/"
                    f"{self.max_queue_pages} KV-pool pages owed, request "
                    f"adds {n_pages}); retry later")
        self.m_admitted.inc(n_units)

    def begin_drain(self) -> None:
        """Stop admitting; /readyz flips to 503 via the owner's ready_fn.
        Idempotent."""
        fresh = False
        with self._lock:
            if not self._draining:
                self._draining = True
                self._drain_started = time.time()
                fresh = True
        if fresh:                       # timeline event OUTSIDE the lock
            obs.event("admission.drain_started")
