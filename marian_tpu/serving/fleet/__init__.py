"""marian_tpu.serving.fleet — multi-tenant fleet serving (ISSUE 20).

N concurrent model families in one process: per-tenant lifecycle stacks
(SwapController + BundleWatcher) under a shared HBM budget with
evict-coldest + warm-on-demand (tenancy.py), and per-tenant KV-page
accounting / isolation auditing over the refcount plane (accounting.py).
Requests select their tenant with the ``#model:<tag>`` protocol header.
"""

from .accounting import (audit_tenants, check_tenant_isolation,  # noqa: F401
                         cross_tenant_pages, merge_expected,
                         tenant_of_label, tenant_of_owner,
                         tenant_page_sums, tenant_sums_from_state)
from .tenancy import (FLEET_LATENCY_METRIC, FLEET_OUTCOMES_METRIC,  # noqa: F401
                      HBM_OVERHEAD, FleetManager, TenantSpec,
                      UnknownTenant, parse_fleet_spec, valid_tag)
