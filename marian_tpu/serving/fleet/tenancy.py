"""FleetManager — N concurrent model families in one serving process
(ISSUE 20 tentpole).

The single-model lifecycle (serving/lifecycle/) tracks versions of
exactly one model. A fleet serves many language pairs and domains per
process; this module scales the SAME building blocks out to N tenants:

- **Per-tenant lifecycle stacks.** Each tenant owns its own
  ``SwapController`` (+ ``ModelRegistry``) and ``BundleWatcher`` over
  ``<model>.bundles/`` — canary, auto-rollback, pin and manual rollback
  all work per tenant, unchanged, because the controller never knew it
  was "the" controller.
- **Shared HBM budget.** Warmed executors pin whole models; under
  ``--fleet-hbm-budget-mb`` the fleet evicts the COLDEST idle tenant's
  executors (LRU by last-routed batch; a tenant with in-flight batches
  is never a victim) to make room for the one being warmed. Residency
  is estimated from the bundle manifest's member byte counts times
  ``HBM_OVERHEAD`` (params dominate; jit executables and activation
  scratch ride the factor) — an honest, documented proxy, not a device
  query, so the budget works identically on the CPU tier tests run on.
- **Warm-on-demand.** A request for a cold tenant warms it
  synchronously on the device worker thread (the requester pays the
  cold start — which the persisted compile cache turns from full-jit
  into load+verify, see lifecycle/compile_cache.py). The newest valid
  bundle wins; a tenant with no bundles warms from its flat model path.
- **Per-tenant SLOs + admission.** One ``SloEngine`` per tenant over
  the fleet's tenant-labeled outcome/latency series (obs/slo.py grew
  label filtering for exactly this), ticked by one fleet thread. A
  tenant in fast-burn sheds its OWN low-priority traffic
  (:meth:`gate`) — tenant A's incident never browns out tenant B.
- **Per-tenant KV-page accounting.** When a shared paged pool is
  attached, claims group by tenant through the refcount plane's
  ``claims()`` snapshot (fleet/accounting.py); eviction releases ONLY
  the victim's references — the evict-coldest test pins that a hot
  tenant's live rows survive a cold tenant's eviction untouched.

Requests pick their tenant with the ``#model:<tag>`` protocol header
(server/server.py); the scheduler forms single-tenant batches and
resolves the executor through :meth:`executor_for` per batch, so a
hot-swap inside one tenant stays atomic at batch granularity exactly
like the single-model lifecycle.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

from ... import obs
from ...common import lockdep
from ...common import logging as log
from ...obs import slo as mslo
from ...training import bundle as bdl
from .. import metrics as msm
from ..admission import Overloaded
from ..lifecycle.controller import SwapController
from ..lifecycle.warmup import DEFAULT_GOLDEN, warm_executor
from ..lifecycle.watcher import BundleWatcher
from . import accounting

# Residency estimate = bundle member bytes x this factor: parameters
# dominate a warmed executor's HBM, and the factor covers the jit
# executables + activation scratch riding along. Deliberately a module
# constant, not a flag — operators size the BUDGET, not the estimator.
HBM_OVERHEAD = 2.0

# fleet tenant-labeled serving series (per-tenant SLO engines read these)
FLEET_OUTCOMES_METRIC = "marian_fleet_request_outcomes_total"
FLEET_LATENCY_METRIC = "marian_fleet_request_latency_seconds"

# tenant tags share the #trace id alphabet minus nothing extra — dots
# allowed for domain-style tags ("en-de.legal")
_TAG_CHARS = set("abcdefghijklmnopqrstuvwxyz"
                 "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_.-")


class UnknownTenant(RuntimeError):
    """The #model: tag names no configured tenant — an explicit client
    error (!!SERVER-ERROR), never a silent default-model reply."""


def valid_tag(tag: str) -> bool:
    return bool(tag) and len(tag) <= 64 and all(c in _TAG_CHARS
                                                for c in tag)


class TenantSpec:
    __slots__ = ("tag", "model_path")

    def __init__(self, tag: str, model_path: str):
        self.tag = tag
        self.model_path = model_path


def parse_fleet_spec(spec: str) -> List[TenantSpec]:
    """``--fleet A=/models/a.npz,B=/models/b.npz`` → tenant specs.
    Malformed entries are hard errors — a fleet boot must never
    silently drop a tenant."""
    out: List[TenantSpec] = []
    seen = set()
    for entry in (e.strip() for e in spec.split(",") if e.strip()):
        tag, sep, path = entry.partition("=")
        tag = tag.strip()
        if not sep or not path.strip() or not valid_tag(tag):
            raise ValueError(
                f"--fleet entry {entry!r}: expected <tag>=<model-path> "
                f"with tag in [A-Za-z0-9_.-]{{1,64}}")
        if tag in seen:
            raise ValueError(f"--fleet: duplicate tenant tag {tag!r}")
        seen.add(tag)
        out.append(TenantSpec(tag, path.strip()))
    if not out:
        raise ValueError("--fleet: no tenants configured")
    return out


class _Tenant:
    """One tenant's slot in the fleet: spec + (when resident) its
    lifecycle stack. Residency fields are guarded by the FLEET lock;
    ``warm_lock`` serializes concurrent cold starts of the same tenant
    without holding up the fleet."""

    __slots__ = ("spec", "controller", "watcher", "resident_bytes",
                 "last_used", "inflight", "cold_starts", "warm_lock",
                 "last_cold_start_s")

    def __init__(self, spec: TenantSpec):
        self.spec = spec
        # residency fields below are guarded by the owning
        # FleetManager's _lock (cross-object — mtlint's guarded-by
        # vocabulary only names same-class locks, so the contract lives
        # here + in the class docstring, enforced by the fleet tests)
        self.controller: Optional[SwapController] = None
        self.watcher: Optional[BundleWatcher] = None
        self.resident_bytes = 0
        self.last_used = 0.0
        self.inflight = 0
        self.cold_starts = 0
        self.last_cold_start_s = 0.0
        self.warm_lock = threading.Lock()


class FleetManager:
    def __init__(self, specs: List[TenantSpec],
                 executor_factory: Callable,
                 metrics_registry: Optional[msm.Registry] = None,
                 hbm_budget_bytes: int = 0,
                 watch_interval: float = 0.0,
                 golden: Optional[List[str]] = None,
                 canary_fraction: float = 0.0,
                 rollback_error_rate: float = 0.5,
                 rollback_p99_factor: float = 0.0,
                 canary_min_batches: int = 8,
                 brownout_min_priority: int = 1,
                 kv_pool=None,
                 clock: Callable[[], float] = time.monotonic):
        self.executor_factory = executor_factory
        self.registry = metrics_registry if metrics_registry is not None \
            else msm.REGISTRY
        self.hbm_budget_bytes = max(0, int(hbm_budget_bytes))
        self.watch_interval = float(watch_interval)
        self.golden = list(golden) if golden else list(DEFAULT_GOLDEN)
        self.canary_fraction = float(canary_fraction)
        self.rollback_error_rate = float(rollback_error_rate)
        self.rollback_p99_factor = float(rollback_p99_factor)
        self.canary_min_batches = int(canary_min_batches)
        self.brownout_min_priority = int(brownout_min_priority)
        # optional shared paged KV pool (iteration-style engines or the
        # future paged fleet): eviction releases the victim tenant's
        # claims through the per-tenant grouping, nothing else
        self.kv_pool = kv_pool
        self.clock = clock
        self._lock = lockdep.make_lock("FleetManager._lock")
        self._tenants: Dict[str, _Tenant] = {
            s.tag: _Tenant(s) for s in specs}
        self._slos: Dict[str, mslo.SloEngine] = {}
        self._slo_thread: Optional[threading.Thread] = None
        self._slo_stop = threading.Event()
        self._slo_interval = mslo.DEFAULT_EVAL_INTERVAL_S

        r = self.registry
        self.m_tenants = r.gauge(
            "marian_fleet_tenants", "Configured tenants in this process")
        self.m_resident = r.gauge(
            "marian_fleet_resident",
            "1 while the tenant's executors are warm in HBM, 0 when cold",
            labels=("tenant",))
        self.m_hbm_budget = r.gauge(
            "marian_fleet_hbm_budget_bytes",
            "Shared executor HBM budget (--fleet-hbm-budget-mb; 0 = "
            "unbudgeted)")
        self.m_hbm_resident = r.gauge(
            "marian_fleet_hbm_resident_bytes",
            "Estimated bytes pinned by resident tenants' executors "
            "(manifest member bytes x overhead factor)")
        self.m_outcomes = r.counter(
            FLEET_OUTCOMES_METRIC,
            "Resolved fleet requests by outcome and tenant (per-tenant "
            "SLO engines read this)",
            labels=("outcome", "tenant"))
        self.m_latency = r.histogram(
            FLEET_LATENCY_METRIC,
            "End-to-end request latency by tenant",
            labels=("tenant",))
        self.m_shed = r.counter(
            "marian_fleet_shed_total",
            "Requests shed at the fleet layer, by tenant and reason "
            "(tenant_brownout = that tenant's own SLO fast-burn; "
            "unknown_tenant = unconfigured #model: tag)",
            labels=("tenant", "reason"))
        self.m_evictions = r.counter(
            "marian_fleet_evictions_total",
            "Tenant executor evictions (hbm_pressure = coldest idle "
            "tenant displaced under the shared budget)",
            labels=("reason",))
        self.m_cold_starts = r.counter(
            "marian_fleet_cold_starts_total",
            "Warm-on-demand cold starts, by tenant",
            labels=("tenant",))
        self.m_cold_start_s = r.gauge(
            "marian_fleet_cold_start_seconds",
            "Wall seconds of the tenant's most recent cold start "
            "(compile-cache-backed bundles cut this >= 5x)",
            labels=("tenant",))
        self.m_tenants.set(len(self._tenants))
        self.m_hbm_budget.set(self.hbm_budget_bytes)
        for tag in self._tenants:
            self.m_resident.labels(tag).set(0)

    # -- tenant lookup / routing (device worker thread) ---------------------
    def tags(self) -> List[str]:
        return sorted(self._tenants)

    def has_tenant(self, tag: str) -> bool:
        return tag in self._tenants

    def executor_for(self, tag: str) -> Callable[[List[str]], List[str]]:
        """The scheduler's tenant router: resolve (warming on demand)
        the tenant's live route for THIS batch. Runs on the device
        worker thread, so a cold start blocks only the batch that
        needs it. The returned callable carries in-flight accounting —
        a tenant mid-batch is never an eviction victim."""
        t = self._tenants.get(tag)
        if t is None:
            raise UnknownTenant(f"unknown model tag '{tag}'")
        self._ensure_live(t)
        now = self.clock()
        with self._lock:
            t.last_used = now
            t.inflight += 1
            controller = t.controller
        if controller is None:           # evicted between ensure and here
            with self._lock:
                t.inflight -= 1
            raise RuntimeError(f"tenant '{tag}' lost residency mid-route")

        def run(lines: List[str]) -> List[str]:
            try:
                return controller.route(lines)
            finally:
                with self._lock:
                    t.inflight -= 1
                    t.last_used = self.clock()
        return run

    def live_version_name(self, tag: str) -> str:
        """Per-tenant model_version label for the scheduler's outcome
        metrics: ``<tag>:<bundle name>`` (``<tag>:cold`` while not
        resident)."""
        t = self._tenants.get(tag)
        if t is None:
            return f"{tag}:unknown"
        with self._lock:
            c = t.controller
        return f"{tag}:{c.live_version_name() if c is not None else 'cold'}"

    # -- warm-on-demand + HBM budget ----------------------------------------
    def _ensure_live(self, t: _Tenant) -> None:
        with self._lock:
            live = t.controller is not None and t.controller.has_live()
        if live:
            return
        with t.warm_lock:
            with self._lock:
                if t.controller is not None and t.controller.has_live():
                    return
            self._warm(t)

    def _warm(self, t: _Tenant) -> None:
        """Cold start one tenant (caller holds its warm_lock): newest
        valid bundle if any, else the flat model path; budget is made
        first, the wall time is the cold-start ledger entry."""
        tag = t.spec.tag
        root = bdl.bundle_root(t.spec.model_path)
        found = bdl.latest_valid_bundle(t.spec.model_path)
        bundle_dir, manifest = found if found else (None, None)
        est = self._estimate_bytes(bundle_dir, manifest, t.spec.model_path)
        self._make_room(est, exclude=tag)
        t0 = time.perf_counter()
        controller = SwapController(
            executor_factory=self.executor_factory,
            metrics_registry=self.registry,
            canary_fraction=self.canary_fraction,
            rollback_error_rate=self.rollback_error_rate,
            rollback_p99_factor=self.rollback_p99_factor,
            canary_min_batches=self.canary_min_batches,
            golden=self.golden)
        if bundle_dir is not None:
            v = controller.ingest(bundle_dir, manifest)
            if v is None or not controller.has_live():
                raise RuntimeError(
                    f"fleet: tenant '{tag}' cold start failed — bundle "
                    f"{bundle_dir} did not reach live "
                    f"({getattr(v, 'error', 'not ingested')})")
        else:
            executor = warm_executor(  # mtlint: disable=MT-LOCK-BLOCKING -- warm_lock exists precisely to make a second requester of the SAME tenant wait out this warmup instead of duplicating it; the fleet lock is NOT held here, other tenants are unaffected
                t.spec.model_path, None, self.executor_factory,
                self.golden, version=f"{tag}:boot")
            controller.seed_live(0, f"{tag}:boot", executor,
                                 bundle_dir=t.spec.model_path)
        dt = time.perf_counter() - t0
        watcher = None
        if self.watch_interval > 0:
            watcher = BundleWatcher(
                root, controller.ingest, interval=self.watch_interval,
                last_seq=controller.live_version().seq
                if bundle_dir is not None else 0)
            watcher.start()
        with self._lock:
            t.controller = controller
            t.watcher = watcher
            t.resident_bytes = est
            t.last_used = self.clock()
            t.cold_starts += 1
            t.last_cold_start_s = dt
        self.m_resident.labels(tag).set(1)
        self.m_cold_starts.labels(tag).inc()
        self.m_cold_start_s.labels(tag).set(dt)
        self._update_hbm_gauge()
        obs.event("fleet.cold_start", tenant=tag,
                  bundle=os.path.basename(bundle_dir or
                                          t.spec.model_path),
                  seconds=round(dt, 3), est_bytes=est)
        log.info("fleet: tenant '{}' warm in {:.2f}s ({}; ~{} MB "
                 "resident)", tag, dt,
                 os.path.basename(bundle_dir or t.spec.model_path),
                 est // (1 << 20))

    @staticmethod
    def _estimate_bytes(bundle_dir: Optional[str], manifest: Optional[Dict],
                        model_path: str) -> int:
        """Manifest member bytes (or the flat file's size) x
        HBM_OVERHEAD — the documented residency proxy."""
        total = 0
        for info in ((manifest or {}).get("members", {}) or {}).values():
            total += int(info.get("bytes", 0) or 0)
        if total == 0:
            try:
                total = os.path.getsize(model_path)
            except OSError:
                total = 0
        return int(total * HBM_OVERHEAD)

    def _make_room(self, need: int, exclude: str) -> None:
        """Evict coldest idle tenants until ``need`` fits the budget.
        Victims: resident, zero in-flight batches, not the requester —
        picked by oldest last-routed time. When only busy tenants
        remain the fleet runs over budget LOUDLY rather than deadlock
        the cold start."""
        if self.hbm_budget_bytes <= 0:
            return
        while True:
            with self._lock:
                resident = sum(t.resident_bytes
                               for t in self._tenants.values()
                               if t.controller is not None)
                if resident + need <= self.hbm_budget_bytes:
                    return
                victims = [t for t in self._tenants.values()
                           if t.controller is not None and t.inflight == 0
                           and t.spec.tag != exclude]
                victim = min(victims, key=lambda t: t.last_used,
                             default=None)
            if victim is None:
                log.warn("fleet: HBM budget exceeded ({} + {} needed > "
                         "{}) but every resident tenant is busy — "
                         "running over budget", resident, need,
                         self.hbm_budget_bytes)
                return
            self.evict(victim.spec.tag, reason="hbm_pressure")

    def evict(self, tag: str, reason: str = "admin") -> bool:
        """Drop one tenant's executors (LRU victim, admin verb, or
        shutdown). Releases ONLY that tenant's KV-page claims when a
        shared pool is attached — the per-tenant grouping of
        ``claims()`` is exactly what makes this safe for every other
        tenant's live rows (pinned by tests/test_fleet.py)."""
        t = self._tenants.get(tag)
        if t is None:
            return False
        with self._lock:
            controller, watcher = t.controller, t.watcher
            if controller is None:
                return False
            freed = t.resident_bytes
            t.controller = None
            t.watcher = None
            t.resident_bytes = 0
        if watcher is not None:
            watcher.stop()
        released = self._release_tenant_pages(tag)
        self.m_resident.labels(tag).set(0)
        self.m_evictions.labels(reason).inc()
        self._update_hbm_gauge()
        obs.event("fleet.evict", tenant=tag, reason=reason,
                  freed_bytes=freed, pages_released=released)
        log.info("fleet: evicted tenant '{}' ({}; ~{} MB freed, {} page "
                 "claim(s) released)", tag, reason, freed // (1 << 20),
                 released)
        return True

    def _release_tenant_pages(self, tag: str) -> int:
        """Release every pool claim owned by ``tag`` (per-tenant
        grouping over the refcount plane's one-lock snapshot); other
        tenants' claims are never touched."""
        pool = self.kv_pool
        if pool is None:
            return 0
        released = 0
        for owner, pages in pool.claims().items():
            if accounting.tenant_of_owner(owner) == tag:
                released += pool.release(owner)
        return released

    def _update_hbm_gauge(self) -> None:
        with self._lock:
            resident = sum(t.resident_bytes
                           for t in self._tenants.values())
        self.m_hbm_resident.set(resident)

    # -- per-tenant outcomes / SLO / admission ------------------------------
    def note_outcome(self, tag: str, outcome: str,
                     latency_s: float) -> None:
        """Server hook, once per resolved request: the tenant-labeled
        series the per-tenant SLO engines burn against."""
        self.m_outcomes.labels(outcome, tag).inc()
        self.m_latency.labels(tag).observe(latency_s)

    def note_shed(self, tag: str, reason: str) -> None:
        self.m_shed.labels(tag, reason).inc()

    def gate(self, tag: str, priority: int) -> None:
        """Per-tenant admission: while THIS tenant's SLO fast-burn is
        alerting, shed its below-threshold priority lanes — tenant A's
        burn never sheds tenant B's traffic. Raises the same retriable
        Overloaded the global admission controller uses."""
        engine = self._slos.get(tag)
        if engine is None:
            return
        if engine.fast_burn() >= engine.fast_factor \
                and priority < self.brownout_min_priority:
            self.note_shed(tag, "tenant_brownout")
            obs.event("fleet.shed", tenant=tag, reason="tenant_brownout",
                      priority=priority)
            raise Overloaded(
                f"tenant '{tag}' is burning its error budget "
                f"(fast-burn >= {engine.fast_factor:g}); priority "
                f"{priority} < {self.brownout_min_priority} shed — "
                f"retry later")

    def build_slos(self, availability: float = 0.0, p99_ms: float = 0.0,
                   window_s: float = mslo.DEFAULT_WINDOW_S,
                   eval_interval: float = mslo.DEFAULT_EVAL_INTERVAL_S
                   ) -> int:
        """One SloEngine per tenant over the fleet's tenant-labeled
        series (objective label values prefixed ``<tag>:`` so the
        shared marian_slo_* gauges stay distinguishable). Returns the
        engine count; 0 objectives = no engines, no thread."""
        if availability <= 0 and p99_ms <= 0:
            return 0
        self._slo_interval = max(0.05, float(eval_interval))
        for tag in self._tenants:
            self._slos[tag] = mslo.SloEngine(
                registry=self.registry,
                availability=availability or None,
                p99_ms=p99_ms or None,
                window_s=window_s,
                eval_interval=eval_interval,
                clock=self.clock,
                outcomes_metric=FLEET_OUTCOMES_METRIC,
                latency_metric=FLEET_LATENCY_METRIC,
                label_filter=(1, tag),
                latency_labels=(tag,),
                objective_prefix=f"{tag}:")
        return len(self._slos)

    def slo_engine(self, tag: str) -> Optional[mslo.SloEngine]:
        return self._slos.get(tag)

    def tick_slos(self, now: Optional[float] = None) -> None:
        """One evaluation pass over every tenant engine (the fleet SLO
        thread's body; tests call it directly with a fake clock)."""
        for engine in self._slos.values():
            engine.tick(now)

    # -- lifecycle ----------------------------------------------------------
    def start(self, warm_all: bool = True) -> "FleetManager":
        """Boot the fleet: optionally pre-warm every tenant in spec
        order (budget evictions apply — with a tight budget the
        earliest-warmed tenants are the LRU victims), start the SLO
        evaluator when engines exist."""
        if warm_all:
            for tag in self.tags():
                try:
                    self._ensure_live(self._tenants[tag])
                except Exception as e:  # noqa: BLE001 — a tenant that
                    # cannot warm at boot stays cold (warm-on-demand
                    # retries on first request); the fleet still serves
                    # the others
                    log.error("fleet: tenant '{}' failed boot warm ({}); "
                              "staying cold until first request", tag, e)
        if self._slos and self._slo_thread is None:
            self._slo_stop.clear()
            self._slo_thread = threading.Thread(
                target=self._slo_run, daemon=True, name="fleet-slo")
            self._slo_thread.start()
        return self

    def _slo_run(self) -> None:
        while not self._slo_stop.wait(self._slo_interval):
            try:
                self.tick_slos()
            except Exception as e:  # noqa: BLE001 — evaluator never dies
                log.warn("fleet SLO tick failed: {}", e)

    def stop(self) -> None:
        self._slo_stop.set()
        th, self._slo_thread = self._slo_thread, None
        if th is not None:
            th.join(timeout=2.0)
        for tag in self.tags():
            t = self._tenants[tag]
            with self._lock:
                watcher = t.watcher
                t.watcher = None
            if watcher is not None:
                watcher.stop()

    # -- introspection (/fleetz) --------------------------------------------
    def tenant_pages(self) -> Dict[str, Dict[str, int]]:
        if self.kv_pool is None:
            return {}
        return accounting.tenant_page_sums(self.kv_pool.claims())

    def status(self) -> Dict:
        now = self.clock()
        pages = self.tenant_pages()
        rows = []
        with self._lock:
            resident_total = sum(t.resident_bytes
                                 for t in self._tenants.values())
            for tag in sorted(self._tenants):
                t = self._tenants[tag]
                c = t.controller
                rows.append({
                    "tenant": tag,
                    "model_path": t.spec.model_path,
                    "resident": c is not None,
                    "live": c.live_version_name() if c is not None
                    else None,
                    "est_bytes": t.resident_bytes,
                    "inflight_batches": t.inflight,
                    "idle_s": round(now - t.last_used, 3)
                    if t.last_used else None,
                    "cold_starts": t.cold_starts,
                    "last_cold_start_s": round(t.last_cold_start_s, 3),
                })
        for row in rows:
            tag = row["tenant"]
            engine = self._slos.get(tag)
            row["slo"] = ({"fast_burn": engine.fast_burn()}
                          if engine is not None else None)
            row["pages"] = pages.get(tag)
        return {
            "tenants": rows,
            "hbm_budget_bytes": self.hbm_budget_bytes,
            "hbm_resident_bytes": resident_total,
            "hbm_overhead_factor": HBM_OVERHEAD,
            "watch_interval_s": self.watch_interval,
        }
