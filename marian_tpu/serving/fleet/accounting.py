"""Per-tenant KV-page accounting over the refcount plane (ISSUE 20).

The paged KV pool already proves REFERENCE-level consistency: every
page's refcount equals the number of claim-list references to it
(``KVPool.audit()``). Multi-tenant serving needs one invariant more —
every page reference must be attributable to exactly ONE tenant, and the
per-tenant sums must match what the tenants were actually granted. A
page "charged to the wrong tenant" is refcount-CONSISTENT (moving a
reference between two owners' claim lists changes no refcount), so the
pool auditor alone cannot see it. This module is the tenant-level
auditor layered on top:

- :func:`tenant_of_owner` — THE owner→tenant convention. Scheduler
  units carry ``.tenant`` (set at submit from the ``#model:`` header);
  tuple owners (beam lineages, prefix triples) resolve through their
  first element; string owners use a ``"<tenant>/<rest>"`` prefix.
  Untenanted owners (single-model serving, the shared prefix cache) map
  to ``""`` and are exempt from cross-tenant checks.
- :func:`tenant_page_sums` — group ``KVPool.claims()`` (the refcount
  plane's one-lock snapshot) into per-tenant reference/owner sums.
- :func:`audit_tenants` — compare those sums against an expected
  grant table; a mover leak shows up as one tenant short exactly the
  references another tenant gained. This is what the seeded
  ``tenant.page_leak`` drill proves end-to-end
  (tests/test_fleet.py).
- :func:`cross_tenant_pages` — the intrinsic invariant needing no
  expectations: no page may hold references from two different
  (non-empty) tenants. Refcount page sharing is legal WITHIN a tenant
  (beam COW, prefix followers), never across.
- :func:`tenant_sums_from_state` / :func:`check_tenant_isolation` —
  the same derivations over a ``/poolz`` DOCUMENT (owner labels, not
  live objects), so a dead process's flight dump can prove or disprove
  cross-tenant isolation post-mortem (ISSUE 20 satellite; the
  ``?check=1`` handler in obs/poolz.py calls these).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

# Owner labels in /poolz documents carry the tenant as a "<tag>/" prefix
# (translator/iteration.py :: _owner_label). Tags are validated at the
# protocol layer to [A-Za-z0-9_.-], so the first "/" is unambiguous.
LABEL_SEP = "/"


def tenant_of_owner(owner) -> str:
    """The owner→tenant convention (see module docstring). Returns ""
    for untenanted owners — single-model serving and the shared prefix
    cache stay exempt from tenant checks."""
    t = getattr(owner, "tenant", None)
    if t:
        return str(t)
    req = getattr(owner, "req", None)
    if req is not None:
        t = getattr(req, "tenant", None)
        if t:
            return str(t)
    if isinstance(owner, tuple) and owner:
        return tenant_of_owner(owner[0])
    if isinstance(owner, str) and LABEL_SEP in owner:
        return owner.split(LABEL_SEP, 1)[0]
    return ""


def tenant_of_label(label: str) -> str:
    """Tenant tag of one /poolz owner LABEL (document form)."""
    if LABEL_SEP in label:
        return label.split(LABEL_SEP, 1)[0]
    return ""


def tenant_page_sums(claims: Dict) -> Dict[str, Dict[str, int]]:
    """Group a ``KVPool.claims()`` snapshot into per-tenant sums:
    ``{tenant: {"refs": page references, "owners": claim lists}}``.
    Each (owner, page) reference counts once — a page shared by two
    same-tenant owners contributes two references, matching how the
    refcount plane bills it."""
    sums: Dict[str, Dict[str, int]] = {}
    for owner, pages in claims.items():
        tenant = tenant_of_owner(owner)
        row = sums.setdefault(tenant, {"refs": 0, "owners": 0})
        row["owners"] += 1
        row["refs"] += len(pages)
    return sums


def cross_tenant_pages(claims: Dict) -> List[str]:
    """The intrinsic isolation invariant: violations for every page
    holding references from two different non-empty tenants. Needs no
    expectations — derivable from any claims snapshot."""
    page_tenants: Dict[int, set] = {}
    for owner, pages in claims.items():
        tenant = tenant_of_owner(owner)
        if not tenant:
            continue
        for p in pages:
            page_tenants.setdefault(int(p), set()).add(tenant)
    return [
        f"cross-tenant page: page {p} is referenced by tenants "
        f"{sorted(ts)} — refcount sharing is legal only within a tenant"
        for p, ts in sorted(page_tenants.items()) if len(ts) > 1
    ]


def audit_tenants(pool, expected: Dict[str, int]) -> List[str]:
    """Tenant-level audit of a live pool: per-tenant page-reference
    sums derived from ``pool.claims()`` must equal ``expected``
    (tenant → granted references), and no page may be cross-tenant.
    Returns violation strings ([] = clean). A leak that moves one
    reference between tenants keeps ``pool.audit()`` green — THIS is
    the auditor that catches it (the ``tenant.page_leak`` drill)."""
    claims = pool.claims()
    violations = cross_tenant_pages(claims)
    sums = tenant_page_sums(claims)
    tenants = set(expected) | {t for t in sums if t}
    for t in sorted(tenants):
        want = int(expected.get(t, 0))
        got = sums.get(t, {}).get("refs", 0)
        if got != want:
            violations.append(
                f"tenant page accounting: tenant '{t}' holds {got} page "
                f"reference(s) but was granted {want} — "
                f"{'over' if got > want else 'under'} by "
                f"{abs(got - want)}")
    return violations


def tenant_sums_from_state(state: Dict) -> Dict[str, Dict[str, int]]:
    """Per-tenant sums re-derived from a /poolz DOCUMENT's page map
    (owner labels): ``{tenant: {"refs": n, "pages": n}}``. Runs on the
    dict, not the process, so flight dumps of a dead server remain
    checkable (the poolz discipline)."""
    sums: Dict[str, Dict[str, int]] = {}
    for _p, info in (state.get("pages", {}) or {}).items():
        for label in info.get("owners", []) or []:
            tenant = tenant_of_label(str(label))
            row = sums.setdefault(tenant, {"refs": 0, "pages": 0})
            row["refs"] += 1
        tenants_here = {tenant_of_label(str(l))
                        for l in info.get("owners", []) or []}
        for t in tenants_here:
            sums.setdefault(t, {"refs": 0, "pages": 0})["pages"] += 1
    return sums


def check_tenant_isolation(state: Dict) -> List[str]:
    """Document-level isolation checks for ``/poolz?check=1`` and dead
    flight dumps: (a) re-derive the per-tenant sums and compare them to
    the snapshot's recorded ``tenants`` block (a divergence means the
    dump is internally inconsistent — exactly what a corrupted claims
    plane looks like from outside); (b) no page's owner labels may span
    two non-empty tenants; (c) every decoding slot's pages must be
    owned by that slot's own tenant."""
    problems: List[str] = []
    pages = state.get("pages", {}) or {}
    recorded = state.get("tenants", None)
    derived = tenant_sums_from_state(state)
    if recorded is not None:
        for t in sorted(set(recorded) | set(derived)):
            want = (recorded.get(t) or {}).get("refs", 0)
            got = (derived.get(t) or {}).get("refs", 0)
            if want != got:
                problems.append(
                    f"tenants block disagrees with the page map: tenant "
                    f"'{t}' records {want} reference(s), page map "
                    f"re-derives {got}")
    for p, info in sorted(pages.items()):
        tenants_here = {tenant_of_label(str(l))
                        for l in info.get("owners", []) or []}
        tenants_here.discard("")
        if len(tenants_here) > 1:
            problems.append(
                f"cross-tenant page: page {p} owner labels span tenants "
                f"{sorted(tenants_here)}")
    for slot in (state.get("rows", {}) or {}).get("slots", []) or []:
        st = tenant_of_label(str(slot.get("owner", "")))
        if not st:
            continue
        for p in slot.get("pages", []) or []:
            info = pages.get(str(p)) or {}
            owner_tenants = {tenant_of_label(str(l))
                             for l in info.get("owners", []) or []}
            owner_tenants.discard("")
            if owner_tenants and st not in owner_tenants:
                problems.append(
                    f"slot {slot.get('slot')} (tenant '{st}') references "
                    f"page {p} owned by tenant(s) "
                    f"{sorted(owner_tenants)}")
    return problems


def merge_expected(grants: Iterable[Tuple[str, int]]) -> Dict[str, int]:
    """Fold (tenant, refs) grant events into an expected table for
    :func:`audit_tenants` — the fleet plane records one entry per claim
    grant and one negative entry per release."""
    out: Dict[str, int] = {}
    for tenant, refs in grants:
        out[tenant] = out.get(tenant, 0) + int(refs)
    return {t: n for t, n in out.items() if n != 0 or t in out}
