"""Continuous token-budget batching scheduler — the host-side layer that
keeps the one jitted static-shape beam program fed under real load
(ISSUE 1 tentpole; replaces server/server.py :: _batching_worker's fixed
5 ms window + unbounded per-request batches).

Design (the serving-time mirror of data/batch_generator's maxi-batching,
which the reference applies only at training time):

- Requests split into SENTENCE UNITS; the scheduler packs units from many
  concurrent requests into one device batch by PADDED-TOKEN BUDGET against
  the same bucketed length table training uses (data/batch_generator
  bucket_length / padded_batch_cost) — batches land on warm jit-cache
  shapes instead of minting new ones per traffic pattern.
- CONTINUOUS: the worker loops as long as units are queued; a new batch
  forms the moment the device frees up, seeded by the oldest unit (no
  starvation), topped up with whatever else fits the budget.
- Per-request deadlines (--request-timeout) resolve expired requests with
  an explicit error even while queued; cancellation (client disconnect)
  propagates — a cancelled request's units are dropped before they cost
  device time.
- Priority lanes: higher-priority units always pack first.
- Retry-with-bisection on batch failure: one poison request costs
  O(log batch) retries to isolate, not the whole batch (upgrade over the
  previous one-by-one retry, O(batch) device calls).
- Observability (ISSUE 8, docs/OBSERVABILITY.md): with the span tracer
  enabled, every request grows a serve.request → serve.queue /
  serve.dispatch tree and every device batch a serve.batch →
  serve.translate span; watchdog trips and poison isolation fire the
  flight recorder. Tracer off = zero overhead on this hot path (no
  ring, no lock — tier-1 guarded). The reply-metadata breakdown
  (``submit(meta=...)``) is tracing-independent: plain timestamps.

Transport-agnostic and model-agnostic: ``translate_lines`` is any callable
``List[str] -> List[str]``; tests drive it with stubs under
JAX_PLATFORMS=cpu, the server wires in TranslationService, and the same
scheduler could front a scorer or embedder.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Sequence

from .. import obs
from ..common import faultpoints as fp
from ..common import lockdep
from ..common import logging as log
from ..data.batch_generator import (DEFAULT_LENGTH_BUCKETS, bucket_length,
                                    padded_batch_cost)
from . import metrics as msm


class RequestTimeout(RuntimeError):
    """--request-timeout deadline expired before the request completed."""


class DispatchStalled(RuntimeError):
    """The dispatch watchdog (--dispatch-stall-timeout) fired: one device
    batch ran past the stall timeout. The batch's requests fail with THIS
    retriable error (transports reply !!SERVER-RETRY) and the scheduler
    moves onto a fresh device worker instead of wedging behind the stuck
    call."""

    retriable = True


class RowEvicted(RuntimeError):
    """A decoding row was evicted with its pages freed — the quiesce
    deadline expired mid-swap, brownout pressure reclaimed its capacity
    for a higher-priority lane, or a recoverable engine failure dropped
    the round (ISSUE 11). Retriable by contract: the server replies
    ``!!SERVER-RETRY`` and the replica is (or is about to be) healthy —
    a rolled-back / rebuilt engine serves the resend."""

    retriable = True


class _QuiesceOp:
    """One pending quiesce: stop admitting joins, drain active rows
    under ``deadline_s`` (evict the overdue with RowEvicted), run the
    pool audit, then ``install()`` re-points the engine at a step
    boundary with an empty join set. ``event`` releases the waiting
    caller (watcher / admin thread)."""

    __slots__ = ("install", "deadline_s", "reason", "deadline", "event",
                 "ok", "install_ok", "cancelled", "evicted", "t0")

    def __init__(self, install: Callable[[], None], deadline_s: float,
                 reason: str):
        self.install = install
        self.deadline_s = max(0.0, float(deadline_s))
        self.reason = reason
        self.deadline: Optional[float] = None   # set on first round seen
        self.event = threading.Event()
        self.ok = False            # install ran AND both audits clean
        self.install_ok = False    # install() returned without raising
        # a waiter that timed out CANCELS the op (cancel_quiesce): its
        # install must never run late — the caller has already treated
        # the re-point as failed (e.g. the lifecycle released the
        # candidate), so a late install would serve a dead executor
        self.cancelled = False
        self.evicted = 0
        self.t0 = 0.0


def default_length_fn(line: str) -> int:
    """Whitespace token estimate (+1 for EOS) — the budget packer only
    needs bucket-resolution accuracy; the translator re-measures with real
    vocab encodings when it builds the device batch."""
    return len(line.split()) + 1


class _Request:
    __slots__ = ("lines", "future", "priority", "arrival", "deadline",
                 "results", "remaining", "queued", "queued_pages",
                 "first_dispatch", "timeout_handle", "dead_accounted",
                 "trace_id", "span", "own_root", "q_span", "d_span",
                 "meta", "rounds", "prefix_hits", "evictions_n",
                 "on_partial", "ttft", "tenant")

    def __init__(self, lines: List[str], future: "asyncio.Future",
                 priority: int, arrival: float, deadline: Optional[float]):
        self.lines = lines
        self.future = future
        self.priority = priority
        self.arrival = arrival
        self.deadline = deadline
        self.results: List[Optional[str]] = [None] * len(lines)
        self.remaining = len(lines)
        self.queued = len(lines)        # units currently sitting in lanes
        self.queued_pages = 0           # page debt of those units (iteration)
        self.first_dispatch: Optional[float] = None
        self.timeout_handle = None
        # True once _on_request_done added this request's leftover queued
        # units to the scheduler's dead count. future.done() flips at
        # set_exception time but done-CALLBACKS run via call_soon — the
        # forming pass can sweep units in that gap, and must only deduct
        # from the dead count what the callback actually added.
        self.dead_accounted = False
        # observability (ISSUE 8): the request's trace id (client-given
        # or generated), its span tree handles (root/queue/dispatch —
        # None with the tracer disabled), and the caller's reply-metadata
        # dict (queue-wait vs service breakdown, filled at resolution)
        self.trace_id = ""
        self.span = None
        self.own_root = False       # this scheduler opened the root span
        self.q_span = None
        self.d_span = None
        self.meta: Optional[dict] = None
        # iteration-mode row breakdown (ISSUE 14), aggregated across
        # this request's rows and reported in the #trace reply
        # metadata: decode rounds participated (max over rows),
        # prefix-cache hits (replays + live forks), rows evicted with
        # a retriable error. Tracing-independent, like queue_s.
        self.rounds = 0
        self.prefix_hits = 0
        self.evictions_n = 0
        # streaming (ISSUE 16): transport callback for partial-token
        # delivery (#stream: clients; None = no streaming), and the
        # request's time-to-first-token, stamped at its FIRST partial
        self.on_partial: Optional[Callable[[int, str, int], None]] = None
        self.ttft: Optional[float] = None
        # multi-tenant fleet serving (ISSUE 20): the #model: tag this
        # request belongs to ("" = the single-model default). Batches
        # are formed single-tenant and routed through tenant_router;
        # fleet/accounting.py attributes KV-page owners through this
        # field (owner.req.tenant).
        self.tenant = ""


class _Unit:
    """One sentence of one request — the scheduling granule."""

    __slots__ = ("req", "idx", "text", "tokens", "pages", "row_span",
                 "rounds", "evict_reason", "partials_sent")

    def __init__(self, req: _Request, idx: int, text: str, tokens: int,
                 pages: int = 0):
        self.req = req
        self.idx = idx
        self.text = text
        self.tokens = tokens
        # KV-pool pages this sentence will claim (iteration mode's
        # admission currency; 0 in request mode)
        self.pages = pages
        # per-row decode tracing (ISSUE 14, iteration mode): the
        # serve.row span opened at join (None with tracing off), the
        # decode rounds this row participated in, and — when evicted —
        # why (quiesce / brownout / pool_exhausted / cancelled)
        self.row_span = None
        self.rounds = 0
        self.evict_reason: Optional[str] = None
        # streamed partial frames delivered for this row (#stream:);
        # the first one stamps ttft on the serve.row span
        self.partials_sent = 0


class ContinuousScheduler:
    def __init__(self, translate_lines: Callable[[List[str]], List[str]],
                 token_budget: int = 4096,
                 length_buckets: Sequence[int] = DEFAULT_LENGTH_BUCKETS,
                 batch_multiple: int = 8,
                 window_s: float = 0.002,
                 scan_limit: int = 512,
                 length_fn: Callable[[str], int] = default_length_fn,
                 registry: Optional[msm.Registry] = None,
                 executor: Optional[concurrent.futures.Executor] = None,
                 stall_timeout: float = 0.0,
                 version_fn: Optional[Callable[[], str]] = None,
                 batching_mode: str = "request",
                 engine=None,
                 engine_factory: Optional[Callable[[], object]] = None):
        self.translate_lines = translate_lines
        # --batching-mode (ISSUE 10): 'request' packs whole requests
        # into device batches (the PR 6 scheduler); 'iteration' moves
        # scheduling INSIDE the decode loop — the forming pass runs
        # every decode step against the paged KV pool's free pages, so
        # sentences join a RUNNING decode and finished ones leave it
        # (engine = translator/iteration.py::PagedDecodeEngine).
        if batching_mode not in ("request", "iteration"):
            raise ValueError(f"--batching-mode must be request or "
                             f"iteration, got {batching_mode!r}")
        if batching_mode == "iteration" and engine is None:
            raise ValueError("--batching-mode iteration needs a "
                             "PagedDecodeEngine (translate_lines alone "
                             "cannot join rows mid-decode)")
        self.batching_mode = batching_mode
        self.engine = engine
        # rebuilds the engine after a liveness trip (the wedged worker
        # thread owns the old engine's device state)
        self.engine_factory = engine_factory
        # model-version label source for the outcome counter; the
        # lifecycle SwapController installs its live_version_name here
        # so dashboards can pin an outcome shift to the exact hot-swap
        # that caused it (ISSUE 5). Read on the event-loop thread only.
        self.version_fn = version_fn or (lambda: "unversioned")
        # --dispatch-stall-timeout: liveness watchdog over each device
        # call (0 = off). See _translate_units / _trip_watchdog.
        self.stall_timeout = max(0.0, float(stall_timeout))
        # multi-tenant fleet serving (ISSUE 20), set by the server in
        # --fleet mode: tenant_router(tag) resolves (warming on demand)
        # the tenant's route for one batch — called on the DEVICE WORKER
        # thread so a cold start blocks only the batch that needs it;
        # tenant_version_fn(tag) labels outcomes per tenant. Both None
        # in single-model serving (tenant "" uses translate_lines).
        self.tenant_router: Optional[
            Callable[[str], Callable[[List[str]], List[str]]]] = None
        self.tenant_version_fn: Optional[Callable[[str], str]] = None
        self.token_budget = max(1, int(token_budget))
        self.length_buckets = length_buckets
        self.batch_multiple = batch_multiple
        # short coalescing pause before the FIRST batch of an idle period:
        # lets a burst of concurrent clients land in one device batch
        # (successor of the old fixed 5 ms window; once the queue is
        # non-empty the loop never sleeps — the device sets the cadence)
        self.window_s = window_s
        # bound on units examined per batch-forming pass, so one pass is
        # O(scan_limit) regardless of backlog depth
        self.scan_limit = scan_limit
        self.length_fn = length_fn
        # ONE device worker thread: the Translate driver's jit caches and
        # prefix state are not re-entrant, and the TPU program is serial
        # anyway — concurrency comes from batching, not threads.
        self._executor = executor or concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serve-device")
        self._own_executor = executor is None
        # priority lanes: lane per priority value, highest served first.
        # Lanes are event-loop-thread-only; the COUNTERS below cross
        # threads (the metrics HTTP scrape thread samples queued_units via
        # the depth gauge's set_function) and carry a lock discipline that
        # mtlint's guarded-by checker enforces (docs/STATIC_ANALYSIS.md).
        self._lanes: Dict[int, Deque[_Unit]] = collections.defaultdict(
            collections.deque)
        self._state_lock = lockdep.make_lock(
            "ContinuousScheduler._state_lock")
        self._queued = 0                  # guarded-by: _state_lock
        # queue debt in KV-pool PAGES (iteration mode's admission
        # currency — a 500-token sentence owes more pool than a
        # 5-token one, which sentence counts cannot express)
        self._queued_pages = 0            # guarded-by: _state_lock
        self._dead_pages = 0              # guarded-by: _state_lock
        # units in lanes whose request already resolved (timed out /
        # cancelled / failed): still physically queued until the next
        # forming pass sweeps them, but DEAD — admission must not shed
        # live traffic against them (a timeout storm would otherwise
        # convert directly into a shed storm while a long device batch
        # keeps the worker busy)
        self._dead = 0                    # guarded-by: _state_lock
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._loop = None        # captured at start(); request_quiesce
        #                          wakes the worker cross-thread via it
        self._inflight = 0
        # pending quiesce operations (ISSUE 11), processed one at a
        # time by the iteration worker at round boundaries; appended
        # from any thread (the lifecycle watcher, admin verbs), hence
        # the lock
        self._quiesce_q: Deque[_QuiesceOp] = collections.deque()
        #                                   # guarded-by: _state_lock
        # brownout ladder effects (serving/brownout.py): the level is
        # written by the brownout evaluator thread and read per round;
        # a single int with no coupled invariant — no lock
        self._brownout_level = 0
        self._brownout_cap_factor = 0.5
        # lifecycle health hook (iteration mode): called after every
        # engine round with (error, device_s) so SwapController can
        # window per-version round health without owning the round loop
        self.round_observer: Optional[Callable[[bool, float], None]] = None
        # units currently on (or headed to) the device — loop-thread-only.
        # stop() fails their futures: a cancelled worker never returns
        # results for them, and their units left the lanes at forming
        # time, so the lane sweep alone would leave their clients hanging.
        self._inflight_units: List[_Unit] = []
        # iteration mode: units currently decoding in engine slots
        # (loop-thread-only; the engine holds the device-side rows)
        self._active_units: Dict[_Unit, None] = {}

        r = registry if registry is not None else msm.REGISTRY
        self._registry = r       # install_engine re-declares pool gauges
        self.m_requests = r.counter(
            "marian_serving_requests_total", "Requests submitted")
        self.m_queue_depth = r.gauge(
            "marian_serving_queue_depth_sentences",
            "Sentences currently queued (not yet in a device batch)")
        self.m_queue_depth.set_function(self.queued_units)
        self.m_batches = r.counter(
            "marian_serving_batches_total", "Device batches dispatched")
        self.m_batch_rows = r.histogram(
            "marian_serving_batch_rows", "Real sentences per device batch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
        self.m_fill = r.histogram(
            "marian_serving_batch_fill_ratio",
            "Real tokens / padded batch capacity per device batch",
            buckets=msm.RATIO_BUCKETS)
        self.m_waste = r.histogram(
            "marian_serving_padding_waste_ratio",
            "Padded tokens wasted per device batch (1 - fill ratio)",
            buckets=msm.RATIO_BUCKETS)
        self.m_ttfb = r.histogram(
            "marian_serving_time_to_first_batch_seconds",
            "Queue wait from request arrival to its first device batch")
        self.m_latency = r.histogram(
            "marian_serving_request_latency_seconds",
            "End-to-end request latency (submit to resolve)")
        self.m_timeouts = r.counter(
            "marian_serving_timeouts_total",
            "Requests failed by --request-timeout deadline expiry")
        self.m_cancelled = r.counter(
            "marian_serving_cancelled_total",
            "Requests cancelled by the client before completion")
        self.m_failures = r.counter(
            "marian_serving_failures_total",
            "Requests failed by translation errors")
        self.m_bisections = r.counter(
            "marian_serving_retry_bisections_total",
            "Failed-batch bisection retries (device calls re-issued)")
        self.m_watchdog = r.counter(
            "marian_serving_watchdog_trips_total",
            "Device batches failed by the dispatch stall watchdog "
            "(--dispatch-stall-timeout)")
        self.m_outcomes = r.counter(
            "marian_serving_request_outcomes_total",
            "Requests resolved, by outcome and the model version live at "
            "resolution time (ok|failure|timeout|cancelled|stalled|"
            "evicted — evicted is retriable row eviction: quiesce "
            "deadline, brownout, recoverable engine failure; excluded "
            "from the availability SLO like cancelled, because the "
            "client is told to retry and the retry's outcome counts)",
            labels=("outcome", "model_version"))
        # iteration-mode series (--batching-mode iteration): joins and
        # evictions happen PER DECODE STEP, not per batch — these are
        # the counters that prove mid-decode admission actually ran
        # (the loadgen A/B reads their deltas)
        self.m_joins = r.counter(
            "marian_serving_joins_total",
            "Sentences that joined a decode (iteration mode)")
        self.m_mid_joins = r.counter(
            "marian_serving_mid_decode_joins_total",
            "Sentences that joined a RUNNING decode step beside already-"
            "decoding rows (iteration mode)")
        self.m_evictions = r.counter(
            "marian_serving_evictions_total",
            "Mid-decode row evictions, all causes (request cancelled / "
            "timed out while decoding, quiesce deadline, brownout — the "
            "latter two also count in their dedicated series; iteration "
            "mode)")
        self.m_steps = r.counter(
            "marian_serving_decode_steps_total",
            "Decode steps run by the iteration-mode worker")
        self.m_step_rows = r.histogram(
            "marian_serving_step_active_rows",
            "Active decode rows per iteration-mode step (pre-bucket)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
        self.m_queued_pages = r.gauge(
            "marian_serving_queue_depth_pages",
            "KV-pool pages owed by queued sentences (iteration mode's "
            "admission currency)")
        self.m_queued_pages.set_function(self.queued_pages)
        # quiesce + brownout series (ISSUE 11)
        self.m_quiesces = r.counter(
            "marian_serving_quiesces_total",
            "Quiesce operations completed (joins stopped, rows drained "
            "or evicted, engine re-pointed at a step boundary)")
        self.m_quiesce_evictions = r.counter(
            "marian_serving_quiesce_evictions_total",
            "Rows evicted with retriable !!SERVER-RETRY because the "
            "--quiesce-deadline expired before they drained")
        self.m_quiescing = r.gauge(
            "marian_serving_quiescing",
            "Quiesce operations pending/draining (joins are paused "
            "while this is > 0; back-to-back lifecycle verbs can queue "
            "more than one)")
        self.m_quiescing.set_function(self._quiesce_depth)
        self.m_brownout_evictions = r.counter(
            "marian_serving_brownout_evictions_total",
            "Rows evicted with retriable !!SERVER-RETRY by the brownout "
            "ladder (level >= 2) to free capacity for a higher-priority "
            "lane")
        # streaming series (ISSUE 16): #stream: clients get partial
        # target tokens as engine rounds complete (iteration mode)
        self.m_stream_partials = r.counter(
            "marian_stream_partials_total",
            "Partial-token frames delivered to streaming clients "
            "(#stream: protocol header, iteration mode)")
        self.m_stream_ttft = r.histogram(
            "marian_stream_ttft_seconds",
            "Time from request arrival to its first streamed partial "
            "token (#stream: clients; the streaming twin of "
            "time_to_first_batch, which measures join, not delivery)")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        """Start the worker on the RUNNING loop (call from a coroutine)."""
        if self._task is None:
            self._loop = asyncio.get_event_loop()
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        """Hard stop: cancel the worker; queued AND in-flight requests
        fail explicitly (never a silent hang)."""
        # capture before cancelling: _dispatch's finally clears the list
        # while the cancellation unwinds during `await self._task`
        pending = list(self._inflight_units) + list(self._active_units)
        self._active_units.clear()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None
        for u in pending:
            if not u.req.future.done():
                u.req.future.set_exception(
                    RuntimeError("server shut down mid-batch"))
        for lane in self._lanes.values():
            for u in lane:
                # the unit leaves the lanes HERE: zero the request's
                # queued count so the set_exception done-callback (which
                # runs via call_soon AFTER stop returns and adds
                # req.queued to the dead count) cannot re-inflate the
                # counters we zero below — a reused scheduler would
                # otherwise under-report depth to admission forever
                u.req.queued = 0
                u.req.queued_pages = 0
                if not u.req.future.done():
                    u.req.future.set_exception(
                        RuntimeError("server shut down"))
            lane.clear()
        with self._state_lock:
            self._queued = 0
            self._dead = 0
            self._queued_pages = 0
            self._dead_pages = 0
            dangling = list(self._quiesce_q)
            self._quiesce_q.clear()
        for op in dangling:
            # release any thread blocked in request_quiesce(wait=True):
            # the loop is gone, the install will never run
            op.ok = False
            op.event.set()
        if self._own_executor:
            self._executor.shutdown(wait=False)

    async def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: finish everything queued/in flight, then
        stop. Pair with AdmissionController.begin_drain() so nothing new
        arrives. Returns True when fully drained, False on timeout."""
        loop = asyncio.get_event_loop()
        dl = loop.time() + timeout if timeout is not None else None

        def _done() -> bool:
            return (self._queue_size() == 0 and self._inflight == 0
                    and not self._active_units)

        while not _done():
            if dl is not None and loop.time() >= dl:
                await self.stop()
                return False
            self._wake.set()           # keep the worker moving
            await asyncio.sleep(0.005)
        await self.stop()
        return True

    # -- submission ---------------------------------------------------------
    def queued_units(self) -> int:
        """LIVE queued sentences — what admission and the depth gauge see
        (the gauge samples this from the metrics scrape THREAD, hence the
        lock). Dead units (resolved requests not yet swept from the lanes)
        are excluded, so expired backlog never sheds live traffic."""
        with self._state_lock:
            return max(0, self._queued - self._dead)

    def queued_pages(self) -> int:
        """LIVE queue debt in KV-pool pages (iteration mode; 0 in
        request mode) — what page-priced admission and the headroom
        gauge's queue-pressure input see. Sampled from the metrics
        scrape thread, hence the lock."""
        with self._state_lock:
            return max(0, self._queued_pages - self._dead_pages)

    def _queue_size(self) -> int:
        """Raw queued-unit count (live + dead) under the state lock."""
        with self._state_lock:
            return self._queued

    # -- quiesce protocol (ISSUE 11; iteration mode) ------------------------
    def _quiesce_depth(self) -> int:
        with self._state_lock:
            return len(self._quiesce_q)

    def _peek_quiesce(self) -> Optional[_QuiesceOp]:
        with self._state_lock:
            while self._quiesce_q and self._quiesce_q[0].cancelled:
                self._quiesce_q.popleft().event.set()
            return self._quiesce_q[0] if self._quiesce_q else None

    def cancel_quiesce(self, op: _QuiesceOp) -> None:
        """Withdraw a pending quiesce whose waiter gave up (wait budget
        exceeded): its install must not run late — the caller has
        already declared the re-point failed and may have released the
        target executor. A cancelled head is dropped at the next peek;
        an op already past its install cannot be recalled (the caller's
        event was set then)."""
        with self._state_lock:
            op.cancelled = True

    def request_quiesce(self, install: Callable[[], None],
                        deadline_s: float, reason: str,
                        wait: bool = True,
                        timeout: Optional[float] = None) -> _QuiesceOp:
        """Enqueue a quiesce: the iteration worker stops admitting joins,
        drains active rows until ``deadline_s`` (rows past it are evicted
        with retriable ``!!SERVER-RETRY`` and their pages freed), runs
        the pool audit, then calls ``install()`` at a step boundary with
        an empty join set (the only legal moment to re-point the engine)
        and resumes joins. Callable from ANY thread except — with
        ``wait=True`` — the event-loop thread itself (the loop is what
        executes the quiesce; waiting on it there would deadlock, which
        is why the lifecycle's rollback paths pass ``wait=False``).
        Returns the op; ``op.event``/``op.ok`` report completion."""
        op = _QuiesceOp(install, deadline_s, reason)
        with self._state_lock:
            self._quiesce_q.append(op)
        loop = self._loop
        if loop is not None:
            try:
                loop.call_soon_threadsafe(self._wake.set)
            except RuntimeError:   # loop already closed: stop() cleans up
                pass
        if wait:
            # bounded: drain deadline + generous slack for the install's
            # own work; a dead loop must not wedge the watcher forever
            op.event.wait(timeout if timeout is not None
                          else op.deadline_s + 30.0)
            if not op.event.is_set():
                # withdraw it: the caller will treat the re-point as
                # failed, so a LATE install (serving loop catching up
                # after the caller released the target) must not run
                self.cancel_quiesce(op)
                log.error("quiesce ({}) did not complete within its "
                          "wait budget — withdrawn; the serving loop "
                          "may be down", reason)
        return op

    def install_engine(self, engine) -> None:
        """Re-point the paged engine (the quiesce install callback is
        the only legitimate caller — loop thread, empty join set, zero
        active rows). Re-declares the pool gauges so the scrape tracks
        the NEW engine's pool, and re-applies the current brownout cap
        scale (a swap must not silently reset an active brownout)."""
        self.engine = engine
        decl = getattr(engine, "_declare_metrics", None)
        if decl is not None:
            decl(self._registry)
        scale_fn = getattr(engine, "set_cap_scale", None)
        if scale_fn is not None:
            scale_fn(self._brownout_cap_factor
                     if self._brownout_level >= 1 else 1.0)

    # -- brownout ladder effects (ISSUE 11; serving/brownout.py) ------------
    def set_brownout_level(self, level: int,
                           cap_factor: Optional[float] = None) -> None:
        """Apply one brownout level (called by the BrownoutController's
        evaluator thread): >= 1 tightens the decode cap of future joins,
        >= 2 arms the per-round priority eviction pass, >= 3 is enforced
        at admission (AdmissionController.set_brownout)."""
        if cap_factor is not None:
            self._brownout_cap_factor = float(cap_factor)
        self._brownout_level = max(0, int(level))
        engine = self.engine
        scale_fn = getattr(engine, "set_cap_scale", None) \
            if engine is not None else None
        if scale_fn is not None:
            scale_fn(self._brownout_cap_factor
                     if self._brownout_level >= 1 else 1.0)

    def submit(self, lines: List[str], priority: int = 0,
               timeout: Optional[float] = None,
               meta: Optional[dict] = None,
               trace_id: Optional[str] = None,
               on_partial: Optional[Callable[[int, str, int], None]]
               = None, tenant: str = "") -> "asyncio.Future":
        """Enqueue one request (a list of sentences); returns a future
        resolving to the list of translations in input order. Must be
        called from the event-loop thread (transports live there).
        Cancel the future to cancel the request.

        ``meta`` (optional dict) is filled at resolution time with the
        request's queue-wait vs service-time breakdown, outcome, model
        version and trace id — the transport prepends it to the reply
        for clients that asked (#trace protocol header; loadgen's
        client-side swap-blip attribution). ``trace_id`` labels the
        request's span tree; with the tracer enabled and no id given,
        one is generated (or inherited from the context's span).

        ``on_partial`` (iteration mode, #stream: clients) is called on
        the event-loop thread as ``on_partial(sentence_idx, text_so_far,
        n_tokens)`` every engine round a row of this request is still
        decoding; the future's resolution remains the FINAL reply. Never
        called after the future is done."""
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        now = loop.time()
        if not lines:
            # an empty request has nothing to queue: no unit would ever
            # complete it, so resolve NOW (PR 8 review: the future
            # previously hung forever without a timeout)
            self.m_requests.inc()
            fut.set_result([])
            self._outcome("ok")
            return fut
        deadline = now + timeout if timeout and timeout > 0 else None
        req = _Request(lines, fut, priority, now, deadline)
        req.meta = meta
        req.trace_id = trace_id or ""
        req.on_partial = on_partial
        req.tenant = tenant or ""
        if obs.enabled():
            # span tree: reuse the context's request-root span when the
            # transport opened one (server.handle_frame); open our own
            # root for direct scheduler callers (tests, embedders)
            parent = obs.current()
            if parent is None:
                req.span = obs.start_span(
                    "serve.request", trace_id=trace_id or None,
                    n_sentences=len(lines), priority=priority)
                req.own_root = True
            else:
                req.span = parent
            req.trace_id = req.span.trace_id
            req.q_span = obs.start_span("serve.queue", parent=req.span,
                                        n_sentences=len(lines))
        self.m_requests.inc()
        iteration = self.batching_mode == "iteration"
        with self._state_lock:
            for i, text in enumerate(lines):
                pages = (self.engine.pages_for_text(text) if iteration
                         else 0)
                u = _Unit(req, i, text, max(1, int(self.length_fn(text))),
                          pages=pages)
                self._lanes[priority].append(u)
                self._queued += 1
                self._queued_pages += pages
                req.queued_pages += pages
        if deadline is not None:
            # the deadline fires even if the unit is buried deep in the
            # backlog — a timed-out client gets its error ON TIME, and the
            # worker drops the dead units before they cost device work
            req.timeout_handle = loop.call_at(
                deadline, self._expire_request, req, loop)
        fut.add_done_callback(
            lambda f, _req=req: self._on_request_done(f, _req))
        self._wake.set()
        return fut

    def _version_label(self, req: Optional[_Request] = None) -> str:
        try:
            # fleet mode: a tenanted request labels with ITS tenant's
            # live version ("<tag>:<bundle>"), not the global one
            if req is not None and req.tenant \
                    and self.tenant_version_fn is not None:
                return str(self.tenant_version_fn(req.tenant))
            return str(self.version_fn())
        except Exception:  # noqa: BLE001 — labeling must never fail a reply
            return "unknown"

    def _outcome(self, outcome: str, req: Optional[_Request] = None,
                 now: Optional[float] = None) -> None:
        """One request resolved; label with the live model version so a
        swap-correlated outcome shift is visible per version. With
        ``req``, also finish its span tree and fill its reply-metadata
        dict (queue-wait vs service breakdown)."""
        version = self._version_label(req)
        self.m_outcomes.labels(outcome, version).inc()
        if req is None:
            return
        if now is None:
            try:
                now = asyncio.get_event_loop().time()
            except RuntimeError:  # pragma: no cover — loop gone at teardown
                now = req.arrival
        fd = req.first_dispatch
        queue_s = max(0.0, (fd if fd is not None else now) - req.arrival)
        service_s = max(0.0, now - fd) if fd is not None else 0.0
        if req.meta is not None:
            req.meta.update(trace_id=req.trace_id, outcome=outcome,
                            model_version=version,
                            queue_s=round(queue_s, 6),
                            service_s=round(service_s, 6))
            if self.batching_mode == "iteration":
                # the row breakdown (ISSUE 14): rounds participated
                # (max over this request's rows), time-to-first-join
                # (-1 = never joined a decode), prefix-cache hit flag,
                # retriable row evictions suffered
                req.meta.update(
                    rounds=req.rounds,
                    ttfj_ms=round(queue_s * 1e3, 1) if fd is not None
                    else -1.0,
                    prefix_hit=int(req.prefix_hits > 0),
                    evictions=req.evictions_n)
        if req.d_span is not None:
            obs.end(req.d_span, outcome=outcome, model_version=version)
            req.d_span = None
        if req.q_span is not None:       # resolved while still queued
            obs.end(req.q_span, outcome=outcome)
            req.q_span = None
        if req.own_root and req.span is not None:
            obs.end(req.span, outcome=outcome, model_version=version)
            req.span = None

    def _expire_request(self, req: _Request, loop) -> None:
        if not req.future.done():
            self.m_timeouts.inc()
            self._outcome("timeout", req, loop.time())
            req.future.set_exception(RequestTimeout(
                f"request deadline expired after "
                f"{(loop.time() - req.arrival):.3f}s "
                f"({req.remaining}/{len(req.lines)} sentences unfinished)"))

    def _on_request_done(self, fut: "asyncio.Future", req: _Request) -> None:
        if fut.cancelled():
            self.m_cancelled.inc()
            self._outcome("cancelled", req)
        # any units of this request still sitting in lanes are dead until
        # the next forming pass physically sweeps them — discount them
        # from the admission-visible depth IMMEDIATELY (a normal
        # completion has req.queued == 0, so this is a no-op there).
        # req.queued is read inside the lock: a forming pass that swept
        # units between set_exception and this callback already lowered
        # it, so the count added here is exactly the units still in lanes.
        with self._state_lock:
            req.dead_accounted = True
            self._dead += req.queued
            self._dead_pages += req.queued_pages

    # -- worker -------------------------------------------------------------
    async def _run(self) -> None:
        if self.batching_mode == "iteration":
            await self._run_iteration()
            return
        loop = asyncio.get_event_loop()
        while True:
            try:
                was_idle = False
                while self._queue_size() == 0:
                    self._wake.clear()
                    was_idle = True
                    await self._wake.wait()
                if was_idle and self.window_s > 0:
                    # idle-edge coalescing pause only; under sustained load
                    # the previous batch's device time IS the window
                    await asyncio.sleep(self.window_s)
                t_form = time.perf_counter() if obs.enabled() else 0.0
                batch = self._form_batch(loop.time())
                if not batch:
                    continue
                # batch-formation cost rides the batch span as an attr
                # (the forming pass runs under the state lock — no spans
                # from inside it; timed from out here instead)
                form_s = (time.perf_counter() - t_form) if t_form else 0.0
                await self._dispatch(batch, loop, form_s)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — supervision: never die
                log.error("serving scheduler error (recovered): {}", e)

    def _form_batch(self, now: float) -> List[_Unit]:
        """Pack one device batch: seed with the oldest live unit of the
        highest non-empty priority lane, then top up with queued units
        (same lane order) that fit the padded-token budget. Units of
        already-resolved requests (cancelled / timed out / failed) are
        discarded here, before they cost device time.

        Runs entirely under the state lock: one forming pass is bounded
        CPU-only work (O(scan_limit), no awaits), and the counters it
        rebalances must never be observed mid-pass by the metrics scrape
        thread or admission."""
        batch: List[_Unit] = []
        width = 0
        scanned = 0
        tenant: Optional[str] = None
        skipped: List[_Unit] = []
        with self._state_lock:
            for prio in sorted(self._lanes.keys(), reverse=True):
                lane = self._lanes[prio]
                while lane and scanned < self.scan_limit:
                    u = lane.popleft()
                    # dead sweeps count toward the scan bound too: a
                    # timeout storm in unbounded-queue mode must not turn
                    # one forming pass into an O(backlog) stall under the
                    # state lock
                    scanned += 1
                    self._queued -= 1
                    self._queued_pages -= u.pages
                    u.req.queued -= 1
                    u.req.queued_pages -= u.pages
                    if u.req.future.done():
                        if u.req.dead_accounted:
                            # drop a dead unit the done-callback counted;
                            # if the callback hasn't run yet it will see
                            # the already-lowered req.queued instead
                            self._dead -= 1
                            self._dead_pages -= u.pages
                        continue
                    # fleet mode (ISSUE 20): batches are SINGLE-tenant —
                    # one device call serves one model. The first live
                    # unit seeds the batch's tenant; other tenants' units
                    # keep FIFO order for the next pass via skipped
                    if tenant is None:
                        tenant = u.req.tenant
                    elif u.req.tenant != tenant:
                        skipped.append(u)
                        continue
                    new_width = max(width, bucket_length(u.tokens,
                                                         self.length_buckets))
                    # fit check on UNPADDED rows x bucketed width — the
                    # exact budget semantics of training's _split_maxi, so
                    # serving batches land on the shape grid the jit cache
                    # was warmed on. Row snap-up to batch_multiple can pad
                    # the realized device batch past the budget by
                    # < batch_multiple rows (same as training;
                    # --mini-batch-words has always meant real rows, not
                    # padded rows).
                    if batch and (len(batch) + 1) * new_width \
                            > self.token_budget:
                        # does not fit — keep scanning: a shorter unit
                        # further back may still fit this batch's width
                        skipped.append(u)
                        continue
                    batch.append(u)
                    width = new_width
                if scanned >= self.scan_limit:
                    break
            # skipped units go back to the FRONT of their lanes in order,
            # so FIFO is preserved for the next batch
            for u in reversed(skipped):
                self._lanes[u.req.priority].appendleft(u)
                self._queued += 1
                self._queued_pages += u.pages
                u.req.queued += 1
                u.req.queued_pages += u.pages
        return batch

    # -- iteration mode (ISSUE 10) ------------------------------------------
    async def _run_iteration(self) -> None:
        """Scheduling INSIDE the decode loop: every round is one decode
        step of the paged engine, preceded by a join pass that admits
        queued sentences against the pool's free pages. Finished rows
        resolve per step; the device never idles behind a draining
        batch, and a sentence never waits for one."""
        loop = asyncio.get_event_loop()
        while True:
            try:
                was_idle = False
                while self._queue_size() == 0 and not self._active_units \
                        and self._quiesce_depth() == 0:
                    self._wake.clear()
                    was_idle = True
                    await self._wake.wait()
                if was_idle and self.window_s > 0:
                    await asyncio.sleep(self.window_s)
                await self._iteration_round(loop)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — supervision: never die
                log.error("serving scheduler error (recovered): {}", e)

    def _form_join_set(self) -> List[_Unit]:
        """The iteration-mode forming pass: it runs EVERY decode step
        and packs against the pool's free pages + slots, not a token
        budget — a sentence joins the moment capacity exists. Same lane
        order, dead-unit sweep and scan bound as _form_batch."""
        joins: List[_Unit] = []
        budget_pages = self.engine.free_pages()
        budget_slots = self.engine.free_slots()
        scanned = 0
        skipped: List[_Unit] = []
        with self._state_lock:
            for prio in sorted(self._lanes.keys(), reverse=True):
                lane = self._lanes[prio]
                while lane and scanned < self.scan_limit:
                    u = lane.popleft()
                    scanned += 1
                    self._queued -= 1
                    self._queued_pages -= u.pages
                    u.req.queued -= 1
                    u.req.queued_pages -= u.pages
                    if u.req.future.done():
                        if u.req.dead_accounted:
                            self._dead -= 1
                            self._dead_pages -= u.pages
                        continue
                    if u.pages > self.engine.pool.usable_pages:
                        # estimate says this sentence can NEVER fit the
                        # pool: hand it to the engine anyway (outside
                        # the budget) — it re-measures with the real
                        # vocab encoding and either admits or FATALLY
                        # rejects. Skipping it here would park it at
                        # the queue head forever (livelock).
                        joins.append(u)
                        continue
                    if len(joins) >= budget_slots \
                            or u.pages > budget_pages:
                        skipped.append(u)
                        continue
                    budget_pages -= u.pages
                    joins.append(u)
                if scanned >= self.scan_limit:
                    break
            for u in reversed(skipped):
                self._lanes[u.req.priority].appendleft(u)
                self._queued += 1
                self._queued_pages += u.pages
                u.req.queued += 1
                u.req.queued_pages += u.pages
        return joins

    def _requeue_front(self, u: _Unit) -> None:
        """Return a join-rejected unit to the FRONT of its lane (the
        engine's claim re-check lost a capacity race — FIFO preserved)."""
        with self._state_lock:
            self._lanes[u.req.priority].appendleft(u)
            self._queued += 1
            self._queued_pages += u.pages
            u.req.queued += 1
            u.req.queued_pages += u.pages
            if u.req.future.done() and u.req.dead_accounted:
                # died between pop and requeue: restore the dead count
                # the done-callback could no longer see
                self._dead += 1
                self._dead_pages += u.pages

    def _fail_unit(self, u: _Unit, loop, message: str) -> None:
        if u.req.future.done():
            return
        self.m_failures.inc()
        self._outcome("failure", u.req, loop.time())
        log.error("iteration admission: {}", message)
        u.req.future.set_exception(RuntimeError(message))

    def _mark_joined(self, u: _Unit, now: float, rows_before: int,
                     bucket: int = 0) -> None:
        """A sentence entered the decode. queue_ms STOPS HERE — at join
        time, not at some enclosing batch's dispatch time: a sentence
        joining a running decode must not inherit the running rows'
        deadline/queue accounting (ISSUE 10 small fix; the #trace
        breakdown regression test pins it)."""
        self._active_units[u] = None
        self.m_joins.inc()
        if rows_before > 0:
            self.m_mid_joins.inc()
        req = u.req
        if req.first_dispatch is None:
            req.first_dispatch = now
            self.m_ttfb.observe(now - req.arrival,
                                trace_id=req.trace_id or None)
            if req.q_span is not None:
                obs.end(req.q_span)
                req.q_span = None
                req.d_span = obs.start_span(
                    "serve.dispatch", parent=req.span,
                    joined_mid_decode=rows_before > 0)
        if obs.enabled():
            # per-row lifecycle span (ISSUE 14): one serve.row per
            # sentence under the request root, opened at join with the
            # time-to-first-join and the compiled bucket the joining
            # round ran at; closed at EOS / evict / cancel with the
            # rounds count (serve.round spans cross-link back via
            # their `traces` attr)
            u.row_span = obs.start_span(
                "serve.row", parent=req.span,
                trace_id=req.trace_id or None,
                sentence=u.idx, bucket=bucket,
                mid_decode=rows_before > 0,
                ttfj_ms=round((now - req.arrival) * 1e3, 2))

    def _end_row_span(self, u: _Unit, outcome: str, **attrs) -> None:
        """Close one row's lifecycle: fold its rounds count into the
        request aggregate (the #trace row breakdown) and end its
        serve.row span if one was opened."""
        req = u.req
        if u.rounds > req.rounds:
            req.rounds = u.rounds
        sp = u.row_span
        if sp is not None:
            u.row_span = None
            obs.end(sp, outcome=outcome, rounds=u.rounds, **attrs)

    async def _iteration_round(self, loop) -> None:
        """One join-pass + decode-step round on the device worker. With
        a quiesce pending (ISSUE 11) the join set is EMPTY: active rows
        drain until the deadline, overdue rows are evicted with
        retriable errors, and once the engine is empty the op's install
        re-points it before joins resume."""
        engine = self.engine
        q = self._peek_quiesce()
        if q is not None and q.deadline is None:
            q.t0 = loop.time()
            q.deadline = q.t0 + q.deadline_s
            obs.event("quiesce.begin", reason=q.reason,
                      rows=len(self._active_units),
                      deadline_s=q.deadline_s)
            log.info("quiesce ({}): joins paused, draining {} active "
                     "row(s) under a {}s deadline", q.reason,
                     len(self._active_units), q.deadline_s)
        joins = [] if q is not None else self._form_join_set()
        evicts = [u for u in list(self._active_units)
                  if u.req.future.done()]
        if q is None and self._brownout_level >= 2:
            evicts.extend(self._brownout_victims(loop, evicts))
        if q is not None and loop.time() >= q.deadline:
            # quiesce deadline expired: the rows still decoding leave
            # NOW with a retriable error (their pages are freed by the
            # eviction below) — a swap is never held hostage by one
            # long sentence
            for u in list(self._active_units):
                if u in evicts:
                    continue
                u.evict_reason = "quiesce"
                self._evict_with_retry(
                    u, loop,
                    f"row evicted at the quiesce deadline "
                    f"({q.reason})")
                self.m_quiesce_evictions.inc()
                q.evicted += 1
                evicts.append(u)
        rows_before = engine.active_rows()
        if q is not None and not joins and not evicts \
                and not self._active_units:
            # drained (or never had rows): complete the quiesce without
            # burning a device round
            self._finish_quiesce(q, loop)
            return
        # queue_ms stops at JOIN time: stamp accepted units with the
        # round's start, not with a post-step timestamp that would bill
        # the step (and any jit warmup) as queueing
        t_round = loop.time()
        # serve.round span (ISSUE 14): one per engine round, its OWN
        # trace (a round serves many rows); participating rows cross-
        # link via the `traces` attr set at end, like serve.batch
        rspan = None
        if obs.enabled():
            rspan = obs.start_span(
                "serve.round", rows_before=rows_before,
                joins=len(joins), evicts=len(evicts),
                quiescing=q is not None)
        self._inflight += 1
        try:
            fp.fault_point("serving.dispatch")
            # per-row join metadata rides into the engine's claim: the
            # request-local sentence id (n-best numbering) and whether
            # the client asked for streamed partials (#stream:)
            payload = [(u, u.text,
                        {"sid": u.idx,
                         "stream": u.req.on_partial is not None})
                       for u in joins]

            def _round():
                fp.fault_point("serving.translate")
                return engine.admit_and_step(payload, evicts)

            call = loop.run_in_executor(self._executor, _round)
            if self.stall_timeout > 0:
                try:
                    res = await asyncio.wait_for(asyncio.shield(call),
                                                 self.stall_timeout)
                except asyncio.TimeoutError:
                    self._iteration_stalled(call, joins, loop)
                    obs.end(rspan, outcome="stalled")
                    return
            else:
                res = await call
        except asyncio.CancelledError:
            obs.end(rspan, outcome="cancelled")
            raise
        except Exception as e:  # noqa: BLE001
            # an engine-round failure has no per-sentence bisection (the
            # step computes all rows jointly): fail the round's requests
            # explicitly and rebuild the engine if a factory was given
            self._iteration_failed(joins, loop, e)
            obs.end(rspan, outcome="failed", error=str(e)[:200])
            return
        finally:
            self._inflight -= 1
        for u in evicts:
            if u in self._active_units:
                del self._active_units[u]
                self.m_evictions.inc()
                self._end_row_span(u, u.evict_reason or "cancelled",
                                   retriable=u.evict_reason is not None)
        for u in res.accepted:
            self._mark_joined(u, t_round, rows_before, res.bucket)
        if res.rows:
            # count the round for every row that rode this device step
            # (rows finishing this round are still active here). The
            # request aggregate updates HERE, not only at row end: an
            # eviction fills the reply metadata via _outcome before the
            # row's span closes, and must see the rounds already run
            for u in self._active_units:
                u.rounds += 1
                if u.rounds > u.req.rounds:
                    u.req.rounds = u.rounds
        # per-row lifecycle instants the engine reported (prefix hits /
        # COW forks — ISSUE 14): fold into the request's reply-metadata
        # counters always, and onto the timeline/row spans when tracing
        for key, name, attrs in res.row_events:
            u = key if isinstance(key, _Unit) else None
            if u is not None:
                if name.startswith("prefix."):
                    u.req.prefix_hits += 1
                if name == "prefix.fork" and u.row_span is not None:
                    u.row_span.set_attrs(prefix_fork=True, **attrs)
                if obs.enabled():
                    obs.event(name, trace=u.req.trace_id, **attrs)
            elif obs.enabled():
                obs.event(name, **attrs)
        from ..translator.iteration import FATAL_REASONS
        requeue: List[_Unit] = []
        for u, why in res.rejected:
            if why in FATAL_REASONS:
                # operator-actionable rejection: the engine computed the
                # page requirement — the error must say it, not leave
                # the operator guessing which knob to turn (ISSUE 11)
                detail = res.reject_detail.get(
                    u, "exceeds the engine's source cap or the whole "
                       "KV pool")
                self._fail_unit(
                    u, loop,
                    f"sentence cannot be admitted ({why}): {detail}")
            else:
                requeue.append(u)
        # appendleft in REVERSE so the lane keeps FIFO order across
        # rejection rounds (same discipline as _form_batch's skipped
        # path) — forward order would swap same-priority units every
        # round and starve the earliest request under pool pressure
        for u in reversed(requeue):
            self._requeue_front(u)
        # lazy COW claims (beam>1 divergence) that found the pool dry
        # evicted their sentence mid-decode: retriable by contract —
        # the pool is healthy, the resend lands once pressure passes
        for u in getattr(res, "pool_evicted", ()) or ():
            if u in self._active_units:
                del self._active_units[u]
                self.m_evictions.inc()
                u.evict_reason = "pool_exhausted"
                self._end_row_span(u, "pool_exhausted", retriable=True)
                self._evict_with_retry(
                    u, loop, "row evicted: KV pool exhausted mid-decode "
                             "(copy-on-write beam divergence)")
        # streaming fan-out (ISSUE 16): every still-decoding row of a
        # #stream: request delivers its text-so-far as one partial
        # frame per round; the FIRST partial stamps ttft. Rows that
        # finished this round are not in res.partials — the final
        # reply below is always the last frame a client sees.
        for u, text, ntok in getattr(res, "partials", ()) or ():
            req = getattr(u, "req", None)
            if req is None or req.future.done() \
                    or req.on_partial is None:
                continue
            now_p = loop.time()
            if u.partials_sent == 0 and u.row_span is not None:
                u.row_span.set_attrs(
                    ttft_ms=round((now_p - req.arrival) * 1e3, 2))
            if req.ttft is None:
                req.ttft = now_p - req.arrival
                self.m_stream_ttft.observe(
                    req.ttft, trace_id=req.trace_id or None)
            u.partials_sent += 1
            self.m_stream_partials.inc()
            try:
                req.on_partial(u.idx, text, ntok)
            except Exception as e:  # noqa: BLE001 — a broken client
                log.warn("stream partial delivery failed: {}", e)
                req.on_partial = None     # stream must never kill rounds
        src_done = 0
        for u, text in res.finished:
            self._active_units.pop(u, None)
            src_done += u.tokens
            self._end_row_span(u, "eos")
            self._complete_unit(u, text, loop)
        if res.rows:
            self.m_steps.inc(max(1, res.steps))
            self.m_step_rows.observe(res.rows)
            self.m_batches.inc()     # a step IS the device-batch unit here
            self.m_batch_rows.observe(res.rows)
            if obs.PERF.enabled:
                # PER-STEP device-seconds attribution: rows of different
                # ages share a step, so chip-seconds/token integrates
                # step cost over the tokens THIS step emitted (src
                # tokens credit at sentence completion, like request
                # mode credits on delivery)
                # the round's compile key is the (row bucket, encode
                # width, steps) TRIPLE, not the padded width — pass the
                # round key so an unwarmed engine shape fires the
                # steady-state recompile incident (ISSUE 17). res.steps
                # is live for fused-merge beam rounds too (ISSUE 18):
                # the beam scan covers --iteration-steps steps per
                # dispatch, so beam keys read r{block·k}.w{w}.s{steps}
                obs.PERF.record_batch(
                    self._version_label(), rows=res.rows,
                    width=res.bucket, src_tokens=src_done,
                    trg_tokens=res.tokens, device_s=res.device_s,
                    bucket_key=obs.perf.round_bucket_key(
                        res.bucket, res.enc_bucket, res.steps))
        if rspan is not None:
            # rows that finished this round already left _active_units;
            # their trace ids still belong on the round's cross-links
            traces = {u.req.trace_id for u in self._active_units
                      if u.req.trace_id}
            traces.update(u.req.trace_id for u, _ in res.finished
                          if u.req.trace_id)
            obs.end(
                rspan, outcome="ok", rows=res.rows, bucket=res.bucket,
                steps=res.steps, tokens=res.tokens,
                joined=len(res.accepted), left=len(res.finished),
                pool_evicted=len(res.pool_evicted),
                pages_claimed=res.pages_claimed,
                pages_freed=res.pages_freed,
                pages_aliased=res.pages_aliased,
                pages_copied=res.pages_copied,
                device_s=round(res.device_s, 6),
                traces=sorted(traces))
        self._notify_round(False, res.device_s)
        if q is not None and not self._active_units:
            self._finish_quiesce(q, loop)

    def _finish_quiesce(self, q: _QuiesceOp, loop) -> None:
        """The engine reached an empty join set with zero active rows:
        audit the outgoing engine (zero leaked pages is the contract),
        run the install (which may re-point self.engine), audit the
        incoming engine, resume joins. The serving.quiesce fault point
        sits BEFORE the install — kill mode is the kill-mid-quiesce
        chaos schedule (scripts/chaos.py --iteration)."""
        fp.fault_point("serving.quiesce")
        if q.cancelled:
            # the waiter gave up and withdrew the op mid-drain: do NOT
            # install (the target may already be released); just resume
            with self._state_lock:
                if self._quiesce_q and self._quiesce_q[0] is q:
                    self._quiesce_q.popleft()
            obs.event("quiesce.cancelled", reason=q.reason,
                      evicted=q.evicted)
            q.event.set()
            self._wake.set()
            return
        old = self.engine
        pre = self._audit_engine(old, "quiesce-drain")
        install_ok = True
        try:
            q.install()
        except Exception as e:  # noqa: BLE001 — a failed install keeps
            # the drained (but healthy) old engine serving; the caller
            # learns via op.ok and decides (the lifecycle fails the
            # candidate)
            install_ok = False
            log.error("quiesce ({}): install failed ({}); the previous "
                      "engine keeps serving", q.reason, e)
        post = [] if self.engine is old \
            else self._audit_engine(self.engine, "quiesce-install")
        q.install_ok = install_ok
        q.ok = install_ok and not pre and not post
        with self._state_lock:
            if self._quiesce_q and self._quiesce_q[0] is q:
                self._quiesce_q.popleft()
        self.m_quiesces.inc()
        obs.event("quiesce.complete", reason=q.reason, ok=q.ok,
                  evicted=q.evicted, install_ok=install_ok,
                  audit_violations=len(pre) + len(post),
                  duration_ms=round((loop.time() - q.t0) * 1e3, 1))
        if not q.ok:
            # an unhealthy quiesce (failed install or audit violations)
            # is a pool incident: dump — the flight recorder's `pool`
            # provider embeds the page map at this exact moment
            # (ISSUE 14)
            obs.FLIGHT.trip_async(
                "quiesce",
                detail=f"quiesce ({q.reason}) completed unhealthily: "
                       f"install_ok={install_ok}, "
                       f"{len(pre) + len(post)} audit violation(s)")
        log.info("quiesce ({}): complete in {:.0f}ms — {} row(s) "
                 "evicted with retry, audit {} ({} violation(s))",
                 q.reason, (loop.time() - q.t0) * 1e3, q.evicted,
                 "clean" if not (pre or post) else "FAILED",
                 len(pre) + len(post))
        q.event.set()
        self._wake.set()           # joins resume immediately

    @staticmethod
    def _audit_engine(engine, context: str) -> List[str]:
        """Run the engine's pool auditor if it has one (stub engines in
        tests may not); violations are already reported by the engine."""
        audit = getattr(engine, "audit", None)
        if audit is None:
            return []
        try:
            return list(audit(context=context))
        except TypeError:
            return list(audit())

    def _evict_with_retry(self, u: _Unit, loop, msg: str) -> None:
        """Fail one decoding row's request with the retriable RowEvicted
        (transports reply !!SERVER-RETRY); the row itself leaves the
        engine via the caller's evict list, freeing its pages."""
        if u.req.future.done():
            return
        # count the eviction BEFORE _outcome fills the reply metadata,
        # so the client's row breakdown includes this one (ISSUE 14)
        u.req.evictions_n += 1
        self._outcome("evicted", u.req, loop.time())
        u.req.future.set_exception(RowEvicted(msg + " — retry"))

    def _notify_round(self, error: bool, device_s: float) -> None:
        """Report one engine round's health to the lifecycle observer
        (SwapController windows these per version for canary promotion
        and live auto-rollback in iteration mode)."""
        fn = self.round_observer
        if fn is None:
            return
        try:
            fn(error, device_s)
        except Exception as e:  # noqa: BLE001 — health accounting must
            log.warn("round observer failed: {}", e)   # never kill rounds

    def _brownout_victims(self, loop, exclude: List[_Unit]) -> List[_Unit]:
        """Brownout level >= 2: when queued work outranks a decoding
        row and could not join this round, evict the lowest-priority
        active row (tie-break: longest remaining decode) with a
        retriable error — one per round, so the ladder degrades
        gradually and predictably rather than mass-evicting."""
        if self.queued_units() <= 0:
            return []
        with self._state_lock:
            top = max((p for p, lane in self._lanes.items() if lane),
                      default=None)
        if top is None:
            return []
        victims = [u for u in self._active_units
                   if u not in exclude and not u.req.future.done()
                   and u.req.priority < top]
        if not victims:
            return []

        def score(u: _Unit):
            prog = None
            fn = getattr(self.engine, "row_progress", None)
            if fn is not None:
                prog = fn(u)
            remaining = (prog[1] - prog[0]) if prog else 0
            return (u.req.priority, -remaining)

        worst = min(victims, key=score)
        worst.evict_reason = "brownout"
        self._evict_with_retry(
            worst, loop,
            f"row evicted under brownout (level "
            f"{self._brownout_level}) to free capacity for priority "
            f"{top} traffic")
        self.m_brownout_evictions.inc()
        obs.event("brownout.evict", victim_priority=worst.req.priority,
                  queued_priority=top)
        return [worst]

    def _iteration_stalled(self, call, joins: List[_Unit], loop) -> None:
        """The engine round exceeded --dispatch-stall-timeout. Fail every
        involved request retriably, abandon the wedged worker (with the
        old engine's device state) and rebuild via engine_factory.
        (The caller's finally still runs — inflight bookkeeping stays
        with the caller.)"""
        victims = list(self._active_units) + joins
        self._active_units.clear()
        self._trip_watchdog(call, len(victims))
        now = loop.time()
        for u in victims:
            self._end_row_span(u, "stalled", retriable=True)
            if not u.req.future.done():
                self._outcome("stalled", u.req, now)
                u.req.future.set_exception(DispatchStalled(
                    f"decode step stalled past {self.stall_timeout}s — "
                    f"retry"))
        obs.event("serve.watchdog_trip", rows=len(victims),
                  stall_timeout=self.stall_timeout, mode="iteration")
        obs.FLIGHT.trip_async(
            "watchdog",
            detail=f"iteration decode step ({len(victims)} sentences) "
                   f"stalled past {self.stall_timeout}s")
        self._notify_round(True, self.stall_timeout)
        if self.engine_factory is not None:
            try:
                # install_engine, not a bare assignment: the rebuilt
                # engine must inherit the brownout cap scale and take
                # over the pool gauges (the wedged engine's pool would
                # otherwise keep feeding the scrape)
                self.install_engine(self.engine_factory())
            except Exception as e:  # noqa: BLE001
                log.error("engine rebuild after stall failed: {}", e)

    def _iteration_failed(self, joins: List[_Unit], loop, exc) -> None:
        victims = list(self._active_units) + joins
        self._active_units.clear()
        log.error("iteration decode round failed ({} sentences): {}",
                  len(victims), exc)
        now = loop.time()
        # with a recovery path armed (engine_factory rebuild, or the
        # lifecycle observer that can roll back to a warm engine) the
        # victims' requests are retriable by construction — a resend
        # lands on a healthy engine. Without one, fail loud (the
        # documented no-bisection iteration contract).
        retriable = bool(getattr(exc, "retriable", False)) \
            or self.engine_factory is not None \
            or self.round_observer is not None
        for u in victims:
            self._end_row_span(u, "round_failed", retriable=retriable)
            if not u.req.future.done():
                if retriable:
                    self._evict_with_retry(
                        u, loop,
                        f"row evicted: decode round failed ({exc})")
                else:
                    self.m_failures.inc()
                    self._outcome("failure", u.req, now)
                    u.req.future.set_exception(RuntimeError(str(exc)))
        self._notify_round(True, 0.0)
        if self.engine_factory is not None and self._quiesce_depth() == 0:
            # the observer may have just initiated recovery itself (a
            # lifecycle rollback enqueues a quiesce re-point to the warm
            # previous engine) — rebuilding on top of that would load a
            # whole model on the event loop only to be replaced one
            # round later
            try:
                self.install_engine(self.engine_factory())
            except Exception as e:  # noqa: BLE001
                log.error("engine rebuild after failure failed: {}", e)

    async def _dispatch(self, units: List[_Unit], loop,
                        form_s: float = 0.0) -> None:
        self._inflight += 1
        self._inflight_units = list(units)
        bspan = None
        # [device seconds, real target tokens, src tokens delivered] for
        # this batch, summed across bisection retries on the device
        # worker thread (ISSUE 9: obs/perf.py — the happens-before is
        # the executor future)
        dev_acc = [0.0, 0.0, 0.0] if obs.PERF.enabled else None
        try:
            now = loop.time()
            rows = len(units)
            real_tokens = sum(u.tokens for u in units)
            width = max(bucket_length(u.tokens, self.length_buckets)
                        for u in units)
            capacity = padded_batch_cost(rows, width, self.length_buckets,
                                         self.batch_multiple)
            fill = min(1.0, real_tokens / max(capacity, 1))
            self.m_batches.inc()
            self.m_batch_rows.observe(rows)
            self.m_fill.observe(fill)
            self.m_waste.observe(1.0 - fill)
            if obs.enabled():
                # batch-level span: its OWN trace (a batch serves many
                # requests); member request trace ids ride as attrs and
                # each member's serve.dispatch span back-references the
                # batch span id, so the tree is walkable both ways
                bspan = obs.start_span(
                    "serve.batch", rows=rows, width=width,
                    fill=round(fill, 4),
                    form_ms=round(form_s * 1e3, 3),
                    traces=sorted({u.req.trace_id for u in units
                                   if u.req.trace_id}))
            seen: set = set()
            for u in units:
                if id(u.req) in seen:     # one request, many sentences
                    continue
                seen.add(id(u.req))
                if u.req.first_dispatch is None:
                    u.req.first_dispatch = now
                    self.m_ttfb.observe(now - u.req.arrival,
                                        trace_id=u.req.trace_id or None)
                    if u.req.q_span is not None:
                        obs.end(u.req.q_span)
                        u.req.q_span = None
                        u.req.d_span = obs.start_span(
                            "serve.dispatch", parent=u.req.span,
                            batch_span=bspan.span_id if bspan else "",
                            rows=rows)
                elif bspan is not None and u.req.d_span is not None:
                    # a LATER batch of a request split across batches
                    u.req.d_span.attrs["batches"] = \
                        u.req.d_span.attrs.get("batches", 1) + 1
            await self._translate_units(units, loop, bspan, dev_acc)
            if dev_acc is not None:
                # live perf/capacity accounting (obs/perf.py): device
                # seconds are measured to the host-side result fence on
                # the worker thread — translate_lines returns host
                # strings, so the return IS the drain (the StepTimer
                # sync-honesty discipline) — and include bisection
                # retries: poison isolation costs real device time
                obs.PERF.record_batch(
                    self._version_label(), rows=rows, width=width,
                    src_tokens=int(dev_acc[2]), trg_tokens=int(dev_acc[1]),
                    device_s=dev_acc[0])
        finally:
            if bspan is not None:
                if dev_acc is not None:
                    bspan.attrs["device_s"] = round(dev_acc[0], 6)
                obs.end(bspan)
            self._inflight -= 1
            self._inflight_units = []

    def _trip_watchdog(self, pending: "asyncio.Future", n_rows: int) -> None:
        """The in-flight device call exceeded --dispatch-stall-timeout.
        The stuck call cannot be killed (a thread wedged inside a device
        runtime has no cancellation point) — what CAN be saved is the
        scheduler: abandon the wedged worker thread to finish (or not) on
        its own, log if it ever does, and point the executor handle at a
        fresh single worker so subsequent batches keep serving."""
        self.m_watchdog.inc()
        log.error(
            "DISPATCH WATCHDOG: device batch ({} sentences) still running "
            "after {}s — failing its requests with a retriable error and "
            "replacing the device worker (the stuck thread is abandoned; "
            "see docs/ROBUSTNESS.md)", n_rows, self.stall_timeout)

        def _late(f) -> None:
            if f.cancelled():
                return
            exc = f.exception()
            log.warn("watchdog-abandoned device batch eventually {} — "
                     "its results were discarded",
                     f"failed: {exc}" if exc else "completed")
        pending.add_done_callback(_late)
        old, was_own = self._executor, self._own_executor
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serve-device")
        self._own_executor = True
        if was_own and old is not None:
            # injected executors stay the caller's to shut down
            old.shutdown(wait=False)
            # detach the wedged worker from concurrent.futures' atexit
            # join: its threads are non-daemon, so without this a
            # PERMANENTLY stuck device call would hang interpreter
            # shutdown after an otherwise graceful drain (private API —
            # degrade to the documented orchestrator-kill backstop if it
            # moves)
            try:
                from concurrent.futures import thread as _cf_thread
                for t in list(getattr(old, "_threads", ())):
                    _cf_thread._threads_queues.pop(t, None)
            except Exception:  # noqa: BLE001
                pass

    async def _translate_units(self, units: List[_Unit], loop,
                               bspan=None, dev_acc=None) -> None:
        """One device call for the batch; on failure, bisect: split in two
        and retry each half, recursively, until single-unit batches isolate
        the poison request(s). Cost per poison unit: O(log batch) extra
        device calls against the old worker's O(batch) one-by-one retry.
        A call that exceeds --dispatch-stall-timeout instead fails the
        WHOLE batch with a retriable DispatchStalled (no bisection — the
        stall is a liveness event, not a poison sentence) and the
        scheduler moves on. ``bspan`` is the enclosing serve.batch span
        (None when tracing is off); device calls and bisection retries
        hang their spans under it."""
        # requests can die (deadline / cancel / a sibling batch's failure)
        # while this batch waited its turn — especially inside bisection
        # retries. Re-filter here so dead sentences never cost a device
        # call whose result would only be discarded.
        units = [u for u in units if not u.req.future.done()]
        if not units:
            return
        # the worker thread writes into its OWN accumulator, merged into
        # dev_acc only once the call has provably completed (a finished
        # await) — a watchdog-abandoned worker otherwise races its late
        # finally against record_batch and double-bills device seconds /
        # counts discarded outputs. Defined OUTSIDE the try: the generic
        # except below calls _merge_acc, and an injected serving.dispatch
        # fault raises before the try body gets this far.
        # dev_acc slots: [device_s, trg_tokens, src_tokens_done] — src
        # tokens are credited only for units whose results were
        # DELIVERED (below), so a stalled or failed call never counts
        # as throughput (cspt/tokens-per-second must spike, not read
        # "healthy", during an incident)
        local_acc = [0.0, 0.0] if dev_acc is not None else None

        def _merge_acc():
            if dev_acc is not None and local_acc is not None:
                dev_acc[0] += local_acc[0]
                dev_acc[1] += local_acc[1]
                local_acc[0] = local_acc[1] = 0.0

        try:
            # inside the try so an injected dispatch failure routes
            # through the normal failure path (futures fail explicitly —
            # never a dropped batch with hanging clients)
            fp.fault_point("serving.dispatch")
            lines = [u.text for u in units]
            translate = self.translate_lines
            # fleet mode (ISSUE 20): a tenanted batch (single-tenant by
            # _form_batch) resolves its route through the tenant router
            # ON THE WORKER THREAD — a warm-on-demand cold start blocks
            # only this batch, never the event loop
            tenant = units[0].req.tenant
            router = self.tenant_router

            def _call_translate():
                run = translate
                if router is not None and tenant:
                    # resolved BEFORE the device-time fence: a cold
                    # start is warmup, not this batch's service time
                    run = router(tenant)
                # device-time fence: translate_lines returns host-side
                # strings, so the perf_counter read AFTER it is an
                # honest device-seconds boundary (obs/perf.py)
                t0 = time.perf_counter()
                try:
                    out_ = run(lines)
                finally:
                    if local_acc is not None:
                        local_acc[0] += time.perf_counter() - t0
                if local_acc is not None:
                    local_acc[1] += sum(len(l.split()) for l in out_)
                return out_

            def _device_call():
                fp.fault_point("serving.translate")
                if bspan is None:
                    return _call_translate()
                # explicit parent handoff: this runs on the device
                # worker thread, outside the event loop's context; the
                # lifecycle SwapController stamps model_version onto
                # this span from inside route() (TRACER.set_attrs)
                sp = obs.start_span("serve.translate", parent=bspan,
                                    rows=len(lines))
                with obs.TRACER.use(sp):
                    try:
                        return _call_translate()
                    except BaseException as e:
                        sp.attrs.setdefault("error", repr(e))
                        raise
                    finally:
                        obs.end(sp)

            call = loop.run_in_executor(self._executor, _device_call)
            if self.stall_timeout > 0:
                try:
                    out = await asyncio.wait_for(asyncio.shield(call),
                                                 self.stall_timeout)
                except asyncio.TimeoutError:
                    if dev_acc is not None:
                        # the wedged call's own timing lands in
                        # local_acc, which is deliberately NOT merged on
                        # this path (the abandoned worker may still be
                        # running), but the device WAS busy for at least
                        # the stall window — bill that, or repeated
                        # stalls read as busy≈0/headroom≈1 and the
                        # autoscaler sees "idle" mid-incident
                        dev_acc[0] += self.stall_timeout
                    self._trip_watchdog(call, len(units))
                    victims = sorted({u.req.trace_id for u in units
                                      if u.req.trace_id})
                    now = loop.time()
                    for u in units:
                        if not u.req.future.done():
                            self._outcome("stalled", u.req, now)
                            u.req.future.set_exception(DispatchStalled(
                                f"device batch stalled past "
                                f"{self.stall_timeout}s — retry"))
                    # spans are ended ABOVE so the dump holds each
                    # victim's complete ingest→dispatch→failure tree
                    obs.event("serve.watchdog_trip", rows=len(units),
                              stall_timeout=self.stall_timeout,
                              traces=victims)
                    # async: this coroutine runs ON the event loop, and
                    # a dump (ring JSON + metrics render + file write)
                    # must not freeze every connection mid-incident
                    obs.FLIGHT.trip_async(
                        "watchdog",
                        trace_id=victims[0] if victims else None,
                        detail=f"device batch ({len(units)} sentences) "
                               f"stalled past {self.stall_timeout}s",
                        extra={"traces": victims})
                    return
            else:
                out = await call
            _merge_acc()        # the await finished: the worker's write
            if len(out) != len(lines):
                raise RuntimeError(
                    f"translator returned {len(out)} lines for "
                    f"{len(lines)} inputs — reply routing would misalign")
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            # a raising await still completed the worker future, so its
            # device seconds are safe to merge (zeroed after, so the
            # arity-check path above cannot double-merge)
            _merge_acc()
            if len(units) == 1:
                u = units[0]
                if not u.req.future.done():
                    self.m_failures.inc()
                    now = loop.time()
                    self._outcome("failure", u.req, now)
                    log.error("translation error: {}", e)
                    u.req.future.set_exception(RuntimeError(str(e)))
                    # the poison request is isolated (bisection endpoint
                    # or a single-sentence batch): record the victim and
                    # snapshot — the span ring still holds its tree
                    obs.event("serve.poison_isolated",
                              trace_id=u.req.trace_id, error=str(e)[:200])
                    obs.FLIGHT.trip_async(   # off the event loop thread
                        "poison", trace_id=u.req.trace_id or None,
                        detail=f"request failed in isolation: {e}")
                return
            self.m_bisections.inc()
            log.error("batch translation error ({} sentences — bisecting "
                      "to isolate): {}", len(units), e)
            mid = len(units) // 2
            await self._translate_units(units[:mid], loop, bspan, dev_acc)
            await self._translate_units(units[mid:], loop, bspan, dev_acc)
            return
        if dev_acc is not None:
            # results delivered: these units' tokens were really
            # processed (stall/failure paths never reach here)
            dev_acc[2] += sum(u.tokens for u in units)
        for u, line in zip(units, out):
            self._complete_unit(u, line, loop)

    def _complete_unit(self, u: _Unit, line: str, loop) -> None:
        req = u.req
        if req.future.done():
            return                    # cancelled/timed out while in flight
        req.results[u.idx] = line
        req.remaining -= 1
        if req.remaining == 0:
            if req.timeout_handle is not None:
                req.timeout_handle.cancel()
            req.future.set_result([r if r is not None else ""
                                   for r in req.results])
            now = loop.time()
            # trace-id exemplar: a p99 outlier on /metrics?exemplars=1
            # links straight to this request's span tree (ISSUE 8)
            self.m_latency.observe(now - req.arrival,
                                   trace_id=req.trace_id or None)
            self._outcome("ok", req, now)
