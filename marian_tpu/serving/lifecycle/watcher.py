"""BundleWatcher — discovers freshly committed checkpoint bundles and
feeds them to the lifecycle controller (ISSUE 5 tentpole).

A daemon thread polls the bundle root on an interval (``--model-watch``
seconds). No inotify dependency: the commit protocol's atomic
staging→bundle rename bumps the ROOT DIRECTORY's mtime, so a cheap
``os.stat`` guards the (slightly less cheap) listing + validation —
steady-state cost is one stat per interval. Sequence numbers, not
timestamps, decide novelty: a bundle is new iff its seq exceeds the last
seen one, so clock skew between the training and serving hosts (shared
filesystem deployments) cannot replay or skip versions.

Newest VALID wins: when several bundles landed between polls only the
newest valid one is delivered — warming is expensive and the
intermediate versions are already superseded (the skip is logged). A
committed-but-invalid bundle (disk damage after commit — bundles are
immutable, it will not heal) is skipped loudly and marked seen, but it
does not shadow a valid bundle committed just below it; the next HIGHER
seq is still picked up either way.

``notify()`` forces an immediate poll — wire it through
``training/bundle.py :: add_commit_hook`` when trainer and server share a
process (online learning) to get push latency with the same code path.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from ...common import faultpoints as fp
from ...common import logging as log
from ...training import bundle as bdl


class BundleWatcher:
    """Polls ``root`` for newly committed bundles; calls
    ``on_bundle(bundle_dir, manifest)`` ON THE WATCHER THREAD for each
    newly discovered valid one (the controller's ingest — including
    warmup — runs there, off the serving event loop)."""

    def __init__(self, root: str,
                 on_bundle: Callable[[str, Dict], None],
                 interval: float = 2.0,
                 last_seq: int = 0):
        self.root = root
        self.on_bundle = on_bundle
        self.interval = max(0.01, float(interval))
        # poll state is watcher-thread-only once start()ed; tests drive
        # poll_now() single-threaded instead
        self._last_seq = int(last_seq)
        self._last_mtime_ns = -1
        self._stop = threading.Event()
        self._kick = threading.Event()
        # set by notify(): the next poll must do a full listing even if
        # the root mtime looks unchanged (the pushed commit may have
        # landed within the same filesystem-timestamp tick)
        self._force = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "BundleWatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="bundle-watcher")
            self._thread.start()
            log.info("bundle watcher: polling {} every {}s (from seq {})",
                     self.root, self.interval, self._last_seq)
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def notify(self) -> None:
        """Wake the poll loop now (in-process commit hook; tests)."""
        self._force.set()
        self._kick.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_now()
            except Exception as e:  # noqa: BLE001 — supervision: never die
                log.error("bundle watcher error (recovered): {}", e)
            self._kick.wait(self.interval)
            self._kick.clear()

    # -- one poll -----------------------------------------------------------
    def poll_now(self) -> Optional[str]:
        """One poll pass; returns the delivered bundle dir, or None."""
        try:
            st = os.stat(self.root)
        except OSError:
            return None            # no bundles committed yet
        forced = self._force.is_set()
        if forced:
            self._force.clear()
        # an unchanged mtime normally means no rename landed — but a
        # commit can land within the same filesystem-timestamp tick as
        # the recorded mtime (coarse granularity: NFS 1s, same clock
        # tick locally), which equality would skip FOREVER. So the
        # short-circuit is not trusted when notify() pushed, nor while
        # the recorded mtime is too recent for a tick to have elapsed.
        recent = (time.time_ns() - st.st_mtime_ns) < 2_000_000_000
        if st.st_mtime_ns == self._last_mtime_ns \
                and not forced and not recent:
            return None            # no rename landed since last poll
        # the mtime observed BEFORE listing is what gets recorded: a
        # commit racing the listdir is re-examined next poll instead of
        # silently skipped
        mtime_ns = st.st_mtime_ns
        names = bdl.list_bundles(self.root)
        fresh = [(int(n.split("-")[-1]), n) for n in names]
        fresh = sorted((x for x in fresh if x[0] > self._last_seq),
                       reverse=True)          # newest first
        if not fresh:
            self._last_mtime_ns = mtime_ns
            return None
        fp.fault_point("lifecycle.watch")
        # newest VALID wins: a damaged newest bundle (immutable — it
        # will not heal) is skipped loudly but must not shadow a valid
        # bundle committed just below it
        chosen = None
        for s, n in fresh:
            bdir = os.path.join(self.root, n)
            ok, why, manifest = bdl.validate_bundle(bdir)
            if ok:
                chosen = (s, n, bdir, manifest)
                break
            log.error("bundle watcher: new bundle {} failed validation "
                      "({}) — not ingesting", bdir, why)
        # poll state advances only past the fault point + validation, so
        # a transient failure above re-delivers next poll rather than
        # losing the bundle until the commit after it
        self._last_seq = fresh[0][0]
        self._last_mtime_ns = mtime_ns
        if chosen is None:
            return None
        seq, newest, bdir, manifest = chosen
        skipped = sum(1 for s, _ in fresh if s < seq)
        if skipped > 0:
            log.info("bundle watcher: {} intermediate bundle(s) "
                     "superseded by {}", skipped, newest)
        self.on_bundle(bdir, manifest)
        return bdir
