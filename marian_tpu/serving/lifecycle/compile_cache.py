"""Persisted XLA compilation cache as a checkpoint-bundle member
(ISSUE 20 tentpole — the warm-on-demand cold-start enabler).

Since ISSUE 17 the serving compile-key surface is ENUMERABLE: the
``# buckets:`` registries + ``warm_grid`` manifest close the shape set,
so "persist the compile cache" finally has a concrete manifest (the warm
grid IS the list of programs the cache must hold) and a ledger
(``marian_compile_backend_seconds_total{trigger=swap-warmup}`` must stay
~flat across a cache-backed swap — tests/test_compile_cache.py pins it).

Mechanism: jax's persistent compilation cache
(``jax_compilation_cache_dir``) already content-addresses compiled
executables by (computation, compile options, backend). This module adds
the bundle plumbing around it:

- :func:`enable` points the process at a cache directory (thresholds
  zeroed so every serving-shape program persists, not just slow ones).
- :func:`pack_member` is a ``write_bundle``-compatible member writer
  that zips the live cache directory plus a :func:`cache_key` record
  into the bundle (member ``xla_cache.zip`` —
  training/bundle.py :: COMPILE_CACHE_MEMBER).
- :func:`adopt` (called by warmup before the executor factory runs)
  unpacks a candidate bundle's cache member, VERIFIES its recorded key
  against the current (chip, geometry, flags), and only then enables
  it — a cache built for different silicon or XLA flags must never be
  installed (jax would re-key and miss anyway; the refusal makes the
  mismatch visible in the hit/miss ledger instead of silent).

The key is deliberately coarse — chip kind + device count + platform +
jax version + XLA-flags hash + the bundle compat hash. jax's own cache
key does the fine-grained content addressing; ours only answers "was
this cache produced by an equivalent process on equivalent silicon".

Everything degrades to a loud no-op when jax is unavailable (the
stub-or-gate dependency rule) or the cache member is absent — warmup
then pays the full jit exactly as before this ISSUE.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import zipfile
from typing import Callable, Dict, Optional, Tuple

from ...common import logging as log
from .. import metrics as msm

# bundle member name (mirrored as training/bundle.py::COMPILE_CACHE_MEMBER
# so producers need no import of the serving tree)
CACHE_MEMBER = "xla_cache.zip"
# key record inside the zip, checked before enabling the unpacked cache
KEY_FILE = "MARIAN_CACHE_KEY.json"

_m_events = None


def _events():
    """marian_compile_cache_events_total{event}: the hit/miss ledger —
    packed / adopted / miss (no member) / key-mismatch / error."""
    global _m_events
    if _m_events is None:
        _m_events = msm.REGISTRY.counter(
            "marian_compile_cache_events_total",
            "Persisted-compile-cache lifecycle events "
            "(adopted = warm-on-demand is load+verify, not full jit)",
            labels=("event",))
        # pre-declare every event so the ledger renders at zero — an
        # operator alerting on key-mismatch needs the series to exist
        # before the first mismatch
        for ev in ("packed", "adopted", "miss", "key-mismatch", "error"):
            _m_events.labels(ev).inc(0)
    return _m_events


def _flags_sha() -> str:
    """Hash of the env-level compiler knobs that change compiled code
    without changing the computation."""
    blob = "\x1f".join(os.environ.get(k, "") for k in
                       ("XLA_FLAGS", "LIBTPU_INIT_ARGS", "JAX_PLATFORMS"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def cache_key(compat_hash: str = "") -> Optional[Dict[str, str]]:
    """The (chip, geometry, flags) identity of caches this process can
    adopt. None when jax is unavailable."""
    try:
        import jax
        devs = jax.devices()
    except Exception as e:  # noqa: BLE001 — no backend = no cache
        log.warn("compile cache: no jax backend ({}) — cache disabled", e)
        return None
    return {
        "chip": str(getattr(devs[0], "device_kind", "unknown")),
        "platform": str(getattr(devs[0], "platform", "unknown")),
        "n_devices": str(len(devs)),
        "jax": str(getattr(jax, "__version__", "unknown")),
        "flags_sha": _flags_sha(),
        "compat": str(compat_hash or ""),
    }


def key_matches(recorded: Dict, current: Dict) -> Tuple[bool, str]:
    """Strict equality on every field; compat is compared only when both
    sides recorded one (v1 manifests carry none — documented fallback,
    same permissiveness as bundle compat_ok)."""
    for field in ("chip", "platform", "n_devices", "jax", "flags_sha"):
        r, c = str(recorded.get(field, "")), str(current.get(field, ""))
        if r != c:
            return False, f"{field} mismatch (cache '{r}' vs here '{c}')"
    r, c = str(recorded.get("compat", "")), str(current.get("compat", ""))
    if r and c and r != c:
        return False, f"compat mismatch (cache '{r}' vs here '{c}')"
    return True, ""


_enabled_dir: Optional[str] = None


def enable(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``
    (created if missing), with the persistence thresholds zeroed so the
    small CPU-sized serving programs tier-1 runs under persist too.
    Idempotent; returns False (loudly) when jax is unavailable."""
    global _enabled_dir
    try:
        import jax
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # CRITICAL for adoption: by default jax parks XLA's own side
        # caches (e.g. xla_gpu_per_fusion_autotune_cache_dir) INSIDE the
        # cache dir and serializes those absolute paths into the compile
        # options — which are hashed into every cache key. A cache
        # unpacked at any other path (adopt() from a bundle — the whole
        # feature) would then miss on every single entry. "none" keeps
        # the key path-independent, so packed caches are portable across
        # directories and processes.
        try:
            jax.config.update("jax_persistent_cache_enable_xla_caches",
                              "none")
        except Exception as e:  # noqa: BLE001 — option absent in old jax
            log.warn("compile cache: cannot pin "
                     "jax_persistent_cache_enable_xla_caches=none ({}); "
                     "adopted caches may miss if the unpack dir differs "
                     "from the producer's cache dir", e)
        # jax memoizes its cache instance on first use; without a reset
        # a mid-process dir switch (adopt() at swap time — the whole
        # point) is silently ignored and the swap pays the full jit.
        # Private API, so absence degrades to a loud warning: a server
        # that enables the cache BEFORE its first compile is unaffected.
        try:
            from jax._src.compilation_cache import reset_cache
            reset_cache()
        except Exception as e:  # noqa: BLE001 — jax moved the hook
            log.warn("compile cache: could not reset jax's cache "
                     "instance ({}); a cache dir switched after first "
                     "use may not take effect until restart", e)
    except Exception as e:  # noqa: BLE001
        log.warn("compile cache: could not enable persistent cache at "
                 "{}: {}", cache_dir, e)
        return False
    _enabled_dir = cache_dir
    log.info("compile cache: persistent XLA cache enabled at {}",
             cache_dir)
    return True


def active_dir() -> Optional[str]:
    """The enabled cache directory, or None."""
    return _enabled_dir


def pack_member(cache_dir: Optional[str] = None, compat_hash: str = ""
                ) -> Callable[[str], None]:
    """A ``write_bundle`` member writer for ``xla_cache.zip``: zips the
    (enabled or given) cache directory with the current
    :func:`cache_key` record. The writer raises if no cache is enabled
    or the key cannot be derived — a producer asking to persist a cache
    it does not have is a config error, not a silent empty member."""
    def _write(path: str) -> None:
        src = cache_dir or _enabled_dir
        if not src or not os.path.isdir(src):
            raise RuntimeError(
                "compile cache: no persistent cache directory to pack "
                "(call compile_cache.enable() / --compile-cache first)")
        key = cache_key(compat_hash)
        if key is None:
            raise RuntimeError("compile cache: no jax backend — cannot "
                               "record a cache key")
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(KEY_FILE, json.dumps(key, indent=1))
            n = 0
            for root, _dirs, files in os.walk(src):
                for name in files:
                    full = os.path.join(root, name)
                    zf.write(full, os.path.relpath(full, src))
                    n += 1
        _events().labels("packed").inc()
        log.info("compile cache: packed {} cache file(s) into {}", n,
                 os.path.basename(path))
    return _write


def adopt(bundle_dir: str, compat_hash: str = "",
          into_dir: Optional[str] = None) -> Tuple[bool, str]:
    """Warm-on-demand entry point (warmup.py calls this BEFORE the
    executor factory): if the bundle carries ``xla_cache.zip`` and its
    recorded key matches this process, unpack and enable it — the
    subsequent jit compiles become load+verify from disk. Returns
    (adopted, why). Never raises: a bad/missing/mismatched member
    degrades to the pre-cache full-jit warmup, counted in the event
    ledger."""
    member = os.path.join(bundle_dir, CACHE_MEMBER)
    if not os.path.isfile(member):
        _events().labels("miss").inc()
        return False, "no compile-cache member in bundle"
    current = cache_key(compat_hash)
    if current is None:
        _events().labels("error").inc()
        return False, "no jax backend"
    try:
        with zipfile.ZipFile(member) as zf:
            try:
                recorded = json.loads(zf.read(KEY_FILE).decode("utf-8"))
            except KeyError:
                _events().labels("error").inc()
                return False, f"member carries no {KEY_FILE}"
            ok, why = key_matches(recorded, current)
            if not ok:
                _events().labels("key-mismatch").inc()
                log.warn("compile cache: NOT adopting {} ({}) — warmup "
                         "pays the full jit", member, why)
                return False, why
            dest = into_dir or tempfile.mkdtemp(prefix="marian-xla-cache-")
            os.makedirs(dest, exist_ok=True)
            for info in zf.infolist():
                if info.filename == KEY_FILE or info.is_dir():
                    continue
                # path-traversal guard: members must unpack INSIDE dest
                target = os.path.realpath(os.path.join(dest, info.filename))
                if not target.startswith(os.path.realpath(dest) + os.sep):
                    raise RuntimeError(
                        f"compile cache: refusing member path "
                        f"{info.filename!r} (escapes the unpack dir)")
                os.makedirs(os.path.dirname(target), exist_ok=True)
                with zf.open(info) as src, open(target, "wb") as out:
                    shutil.copyfileobj(src, out)
    except (OSError, zipfile.BadZipFile, RuntimeError) as e:
        _events().labels("error").inc()
        log.warn("compile cache: could not adopt {}: {}", member, e)
        return False, str(e)
    if not enable(dest):
        _events().labels("error").inc()
        return False, "could not enable the unpacked cache"
    _events().labels("adopted").inc()
    log.info("compile cache: adopted {} — swap warmup is load+verify "
             "(chip {}, {} device(s))", member, current["chip"],
             current["n_devices"])
    return True, dest
