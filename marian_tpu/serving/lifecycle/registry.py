"""ModelRegistry — per-version lifecycle state for served models
(ISSUE 5 tentpole; the bookkeeping half of the zero-downtime control
plane, consumed by watcher/warmup/controller).

Each served (or candidate) model is a ``ModelVersion`` keyed by its
bundle sequence number, moving through an explicit state machine:

    staged ──► warming ──► canary ──► live ──► retired ──► live
       │          │           │         │              (rollback)
       ▼          ▼           ▼         ▼
    rejected   failed      failed    failed
                  (canary ──► retired: superseded by a newer candidate)

- ``staged``   discovered/registered, nothing loaded yet
- ``rejected`` refused before loading weights (compat mismatch, invalid
               bundle, pinned registry) — terminal
- ``warming``  executor loading + jit compile + golden smoke, off the
               serving path
- ``failed``   warmup error, canary rollback, or live regression
               rollback — terminal
- ``canary``   serving a --canary-fraction slice of batches
- ``live``     the version dispatch points at
- ``retired``  replaced by a newer live; the newest retired version is
               kept warm as the rollback target (``retired → live`` is
               the rollback edge)

Any other transition raises ``LifecycleError`` — state bugs must be loud,
not a silently mislabeled /lifecyclez. Bundle enumeration/validation goes
through training/bundle.py's manifest API (``scan_bundles``), the same
checksum walk restore uses, so serving never trusts a bundle the trainer
side would refuse to resume from.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, NamedTuple, Optional

from ... import obs
from ...common import lockdep
from ...common import logging as log
from ...training import bundle as bdl

STAGED = "staged"
WARMING = "warming"
CANARY = "canary"
LIVE = "live"
RETIRED = "retired"
FAILED = "failed"
REJECTED = "rejected"

_ALLOWED: Dict[str, frozenset] = {
    STAGED: frozenset({WARMING, REJECTED}),
    WARMING: frozenset({CANARY, LIVE, FAILED}),
    CANARY: frozenset({LIVE, FAILED, RETIRED}),
    LIVE: frozenset({RETIRED, FAILED}),
    RETIRED: frozenset({LIVE}),
    FAILED: frozenset(),
    REJECTED: frozenset(),
}


class LifecycleError(RuntimeError):
    """An illegal state transition or a lookup of an unknown version."""


class BundleInfo(NamedTuple):
    seq: int
    bundle_dir: str
    ok: bool
    why: str
    manifest: Optional[Dict]


def scan_bundles(model_path: str) -> List[BundleInfo]:
    """Enumerate + validate every committed bundle under
    ``<model>.bundles/``, oldest first — training/bundle.py's manifest
    API is the single source of truth for 'is this bundle loadable'."""
    root = bdl.bundle_root(model_path)
    out: List[BundleInfo] = []
    for name in bdl.list_bundles(root):
        bdir = os.path.join(root, name)
        ok, why, manifest = bdl.validate_bundle(bdir)
        seq = int(manifest["seq"]) if ok and "seq" in manifest \
            else int(name.split("-")[-1])
        out.append(BundleInfo(seq, bdir, ok, why, manifest))
    return out


class ModelVersion:
    """One model version's lifecycle record. State is owned by the
    registry (read/written under the registry lock); the executor slot
    holds the warmed ``translate_lines`` callable once warming succeeds."""

    __slots__ = ("seq", "name", "bundle_dir", "manifest", "compat",
                 "state", "error", "executor")

    def __init__(self, seq: int, name: str, bundle_dir: str = "",
                 manifest: Optional[Dict] = None,
                 compat: Optional[Dict] = None):
        self.seq = seq
        self.name = name
        self.bundle_dir = bundle_dir
        self.manifest = manifest
        self.compat = compat if compat is not None \
            else bdl.manifest_compat(manifest)
        self.state = STAGED
        self.error = ""
        self.executor: Optional[Callable[[List[str]], List[str]]] = None

    def snapshot(self) -> Dict:
        return {
            "version": self.name,
            "seq": self.seq,
            "state": self.state,
            "compat_hash": bdl.compat_hash(self.compat),
            "bundle_dir": self.bundle_dir,
            "error": self.error,
        }


class ModelRegistry:
    """Thread-safe version table + state machine. The controller, the
    watcher thread, the metrics scrape thread (/lifecyclez) and the admin
    HTTP thread all read it; only controller code transitions it."""

    def __init__(self):
        self._lock = lockdep.make_lock("ModelRegistry._lock")
        self._versions: Dict[int, ModelVersion] = {}   # guarded-by: _lock

    def register(self, seq: int, name: str, bundle_dir: str = "",
                 manifest: Optional[Dict] = None,
                 compat: Optional[Dict] = None) -> ModelVersion:
        """Add a new version in ``staged``; re-registering a seq that was
        already decided (any non-terminal state or live/retired) is a
        LifecycleError — one bundle, one lifecycle record."""
        with self._lock:
            existing = self._versions.get(seq)
            if existing is not None \
                    and existing.state not in (FAILED, REJECTED):
                raise LifecycleError(
                    f"version seq {seq} already registered "
                    f"(state {existing.state})")
            v = ModelVersion(seq, name, bundle_dir, manifest, compat)
            self._versions[seq] = v
            return v

    def get(self, seq: int) -> ModelVersion:
        with self._lock:
            v = self._versions.get(seq)
            if v is None:
                raise LifecycleError(f"unknown model version seq {seq}")
            return v

    def transition(self, seq: int, new_state: str,
                   error: str = "") -> ModelVersion:
        """Move one version to ``new_state``; raises LifecycleError on an
        edge the state machine does not allow."""
        if new_state not in _ALLOWED:
            raise LifecycleError(f"unknown lifecycle state {new_state!r}")
        with self._lock:
            v = self._versions.get(seq)
            if v is None:
                raise LifecycleError(f"unknown model version seq {seq}")
            if new_state not in _ALLOWED[v.state]:
                raise LifecycleError(
                    f"illegal transition {v.state} -> {new_state} "
                    f"for version {v.name} (seq {seq})")
            old_state = v.state
            log.info("model lifecycle: {} (seq {}) {} -> {}{}",
                     v.name, seq, v.state, new_state,
                     f" ({error})" if error else "")
            v.state = new_state
            if error:
                v.error = error
        # timeline event after releasing the REGISTRY lock: every
        # state-machine edge lands on the timeline, so a flight dump
        # shows the lifecycle history leading up to the trip (ISSUE 8).
        # NB: callers (SwapController) legally hold the CONTROLLER lock
        # here — that SwapController._lock -> Tracer._lock edge is the
        # one modeled obs-under-lock edge in the static graph; do not
        # add others without extending docs/lock_order.dot's lattice.
        obs.event("lifecycle.transition", version=v.name, seq=seq,
                  frm=old_state, to=new_state, reason=error)
        return v

    def in_state(self, *states: str) -> List[ModelVersion]:
        with self._lock:
            return [v for v in self._versions.values() if v.state in states]

    def newest_seq(self) -> int:
        with self._lock:
            return max(self._versions, default=0)

    def snapshot(self) -> List[Dict]:
        """Per-version state rows for /lifecyclez, newest first."""
        with self._lock:
            versions = sorted(self._versions.values(),
                              key=lambda v: v.seq, reverse=True)
            return [v.snapshot() for v in versions]
