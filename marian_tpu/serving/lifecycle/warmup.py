"""Warmup pipeline — turn a committed bundle into a serving-ready
executor OFF the serving path (ISSUE 5 tentpole).

Order of operations, cheapest refusal first:

1. **Compat check** (no weights touched): the candidate manifest's
   ``compat`` block (vocab sha256 + model-geometry config hash, written
   by training/bundle.py since manifest v2) must match the live
   version's. A mismatched vocabulary or geometry would serve garbage
   tokens or crash inside the jitted step mid-traffic — refuse here,
   while the refusal costs a dict comparison. v1 manifests carry no
   compat block and are accepted with a warning (documented fallback).
2. **Load**: ``executor_factory(bundle_dir, manifest)`` builds a fresh
   ``TranslationService``-style ``translate_lines`` callable against the
   bundle's members (the server's factory re-reads model.npz; tests
   inject stubs).
3. **Golden smoke**: the executor translates the golden set
   (``--warmup-golden`` file, or a built-in probe). This forces jit
   compilation of the serving shapes AND proves the model actually
   decodes — a checkpoint that loads but cannot run must never reach
   dispatch. Output arity is checked against the input (the scheduler's
   reply-routing invariant).

Everything runs on the caller's thread (the watcher thread in the real
wiring), so a multi-second model load + compile never stalls a batch.
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Dict, List, Optional

from ...common import faultpoints as fp
from ...common import logging as log
from ...data.batch_generator import DEFAULT_LENGTH_BUCKETS, bucket_length
from ...obs.perf import (PERF, TRIGGER_SWAP, round_bucket_key,
                         width_bucket_key)
from ...training import bundle as bdl

# Built-in golden probe when --warmup-golden is unset: short sentences in
# the bucket widths serving traffic most commonly lands on. Unknown
# tokens are fine — warmup proves the decode path runs, not quality.
DEFAULT_GOLDEN = [
    "hello",
    "a b c d",
    "the quick brown fox jumps over the lazy dog",
]


class WarmupError(RuntimeError):
    """The candidate could not be warmed (load error, golden smoke
    failure, bad output arity)."""


class CompatMismatch(WarmupError):
    """Refused before loading weights: the candidate's compat block
    contradicts the live version's."""


def load_golden(path: Optional[str]) -> List[str]:
    """Golden source sentences from --warmup-golden (one per line, blank
    lines dropped); the built-in probe set when unset. An unreadable
    file is a hard error — a typo'd path silently warming with the
    default would void the operator's golden-set contract."""
    if not path:
        return list(DEFAULT_GOLDEN)
    with open(path, "r", encoding="utf-8") as fh:
        lines = [ln.strip() for ln in fh]
    lines = [ln for ln in lines if ln]
    if not lines:
        raise WarmupError(f"--warmup-golden {path} contains no sentences")
    return lines


def check_compat(candidate: Optional[Dict], live: Optional[Dict],
                 name: str) -> None:
    """Raise CompatMismatch on a declared mismatch; log the permissive
    v1-manifest fallback so an operator can see an unchecked swap."""
    ok, why = bdl.compat_ok(candidate, live)
    if not ok:
        raise CompatMismatch(f"bundle {name} is incompatible with the "
                             f"live model: {why}")
    if why:
        log.warn("model lifecycle: {} — swap proceeds unchecked ({})",
                 why, name)


def golden_buckets(golden: List[str],
                   length_buckets=DEFAULT_LENGTH_BUCKETS
                   ) -> "collections.OrderedDict":
    """Group golden sentences by the width bucket their whitespace
    token count (+EOS, matching the scheduler's default_length_fn)
    lands on — one group = one warmup call = one jit shape bucket
    compiled off the serving path (ISSUE 9)."""
    groups: "collections.OrderedDict[int, List[str]]" = \
        collections.OrderedDict()
    for line in golden:
        w = bucket_length(len(line.split()) + 1, length_buckets)
        groups.setdefault(w, []).append(line)
    return groups


def smoke_buckets(executor: Callable[[List[str]], List[str]],
                  golden: List[str], version: str, trigger: str,
                  where: str) -> None:
    """Per-bucket golden smoke with compile telemetry (ISSUE 9): one
    timed executor call per width bucket, reported to the perf meter as
    a warmup compilation for (version, bucket) — so steady-state
    traffic landing on a warmed bucket is provably NOT a recompile, and
    ROADMAP 5's future AOT cache has a hits-vs-misses ledger to beat.
    A combined one-call smoke would warm only the WIDEST bucket's jit
    shape (shorter sentences ride padded), so the split is also what
    makes warmup actually warm the serving shapes. Raises WarmupError
    like the single-call smoke."""
    for width, lines in golden_buckets(golden).items():
        t0 = time.perf_counter()
        try:
            with PERF.compile_context(trigger):
                out = executor(list(lines))
        except Exception as e:  # noqa: BLE001
            raise WarmupError(f"golden-set smoke translation failed for "
                              f"{where} (bucket w{width}): {e}") from e
        dt = time.perf_counter() - t0
        if not isinstance(out, (list, tuple)) or len(out) != len(lines):
            raise WarmupError(
                f"golden-set smoke returned "
                f"{len(out) if isinstance(out, (list, tuple)) else type(out).__name__} "
                f"outputs for {len(lines)} inputs ({where}, bucket "
                f"w{width}) — reply routing would misalign")
        PERF.warm_bucket(version, width_bucket_key(width), dt, trigger)


def smoke_engine_grid(executor, version: str, trigger: str,
                      where: str) -> None:
    """Iteration-mode bucket-grid smoke (ISSUE 17 satellite): when the
    warmed executor wraps a paged decode engine (EngineExecutor), drive
    the engine's FULL compile-key grid — every row bucket and every
    halving encode width (PagedDecodeEngine.warm_grid) — and register
    each (row bucket, encode width, steps) triple in the perf meter's
    warm ledger under the :func:`round_bucket_key` vocabulary the
    scheduler reports rounds with. After this, a steady-state round can
    reach NO round key that was not warmed here, so any
    ``trigger=steady-state`` compile incident on a round key is a real
    compile-cache bug (the closed-shape-set claim, asserted end-to-end
    by the jit retrace witness, common/jitwit.py). The composite grid is
    registered in full: warm_grid drives each row bucket at one width
    and each width at one row bucket, but both component jits (step and
    install) are keyed independently, so every cross pairing is warm by
    construction — the undriven pairings register at 0.0 s.

    This works unchanged for the fused-merge beam engine (ISSUE 18):
    PagedBeamEngine overrides ``row_buckets`` to beam-block multiples
    (block_bucket · beam_size) and ``steps_per_round`` to the scanned
    step count, so the cross-fill below enumerates exactly the beam
    scan's reachable round keys."""
    engine = getattr(executor, "engine", None)
    warm_grid = getattr(engine, "warm_grid", None)
    if warm_grid is None:
        return
    try:
        with PERF.compile_context(trigger):
            driven = warm_grid()
    except Exception as e:  # noqa: BLE001
        raise WarmupError(f"engine bucket-grid smoke failed for "
                          f"{where}: {e}") from e
    seen = set()
    for rb, enc_w, steps, dt in driven:
        key = round_bucket_key(rb, enc_w, steps)
        if key in seen:
            continue
        seen.add(key)
        PERF.warm_bucket(version, key, dt, trigger)
    steps = int(getattr(engine, "steps_per_round", 1))
    for rb in getattr(engine, "row_buckets", ()):
        for enc_w in engine.encode_widths():
            key = round_bucket_key(rb, enc_w, steps)
            if key not in seen:
                seen.add(key)
                PERF.warm_bucket(version, key, 0.0, trigger)
    log.info("model lifecycle: engine bucket grid warmed for {} — {} "
             "round keys registered ({} driven)", where, len(seen),
             len(driven))


def warm_executor(bundle_dir: str, manifest: Optional[Dict],
                  executor_factory: Callable[[str, Optional[Dict]],
                                             Callable[[List[str]],
                                                      List[str]]],
                  golden: List[str],
                  version: str = "", trigger: str = TRIGGER_SWAP
                  ) -> Callable[[List[str]], List[str]]:
    """Steps 2+3: build the executor and golden-smoke it. Returns the
    warmed ``translate_lines``; raises WarmupError on any failure.

    With the perf plane enabled (``--perf-accounting``), the smoke runs
    per width bucket and each bucket's compile is reported as warmup
    telemetry (:func:`smoke_buckets`); otherwise the historical single
    combined call is kept — same refusal semantics, no telemetry."""
    fp.fault_point("lifecycle.warmup")
    t0 = time.perf_counter()
    # persisted compile cache (ISSUE 20): a bundle carrying xla_cache.zip
    # whose recorded (chip, geometry, flags) key matches this process
    # turns the jit compiles below into load+verify from disk — the
    # trigger=swap-warmup compile ledger stays ~flat across the swap.
    # Any mismatch/absence degrades to the full jit, counted, never fatal.
    if manifest is not None and bundle_dir:
        from . import compile_cache as _cc
        import os as _os
        if _os.path.isdir(bundle_dir):
            # merge into the already-enabled dir when there is one, so a
            # server running with --compile-cache keeps its accumulated
            # entries; otherwise adopt() unpacks into a fresh tempdir
            adopted, _why = _cc.adopt(
                bundle_dir,
                compat_hash=bdl.compat_hash(bdl.manifest_compat(manifest)),
                into_dir=_cc.active_dir())
            if adopted:
                log.info("warmup: adopted persisted compile cache from "
                         "{} — expecting cache-hit compiles only",
                         bundle_dir)
    try:
        executor = executor_factory(bundle_dir, manifest)
    except Exception as e:  # noqa: BLE001 — any load error refuses the swap
        raise WarmupError(f"executor load failed for {bundle_dir}: "
                          f"{e}") from e
    t_load = time.perf_counter()
    if PERF.enabled:
        smoke_buckets(executor, golden, version or bundle_dir, trigger,
                      bundle_dir)
        smoke_engine_grid(executor, version or bundle_dir, trigger,
                          bundle_dir)
    else:
        try:
            out = executor(list(golden))
        except Exception as e:  # noqa: BLE001
            raise WarmupError(f"golden-set smoke translation failed for "
                              f"{bundle_dir}: {e}") from e
        if not isinstance(out, (list, tuple)) or len(out) != len(golden):
            raise WarmupError(
                f"golden-set smoke returned "
                f"{len(out) if isinstance(out, (list, tuple)) else type(out).__name__} "
                f"outputs for {len(golden)} inputs ({bundle_dir}) — reply "
                f"routing would misalign")
    t_done = time.perf_counter()
    log.info("model lifecycle: warmed {} (load {:.2f}s, golden smoke of "
             "{} sentences {:.2f}s)", bundle_dir, t_load - t0,
             len(golden), t_done - t_load)
    return executor
