"""SwapController — atomic hot-swap, canary routing, auto-rollback
(ISSUE 5 tentpole; the dispatch half of the zero-downtime control plane).

The controller installs itself as the scheduler's ``translate_lines``:
``route()`` runs on the device worker thread, once per device batch, and
picks which version's executor serves it. Because the scheduler reads
its backend once per batch, re-pointing here is atomic AT BATCH
GRANULARITY — an in-flight batch finishes on the executor it started
with (the closure keeps the old model alive), the next batch sees the
new one, and no request is ever dropped or split across versions.

Canary routing (``--canary-fraction f``): while a warmed candidate is in
``canary`` state, a deterministic f-fraction of batches (counter-based,
not random — reproducible under test) routes to it; per-version
request/error/latency series (``marian_model_*``) record both sides.

Auto-rollback:

- **canary phase** — if the canary's windowed failure rate exceeds
  ``--rollback-error-rate``, or its p99 exceeds
  ``--rollback-p99-factor`` x the live p99 (0 = p99 check off), the
  canary is failed and dispatch stays on live. A canary batch that
  errors is transparently RE-SERVED by the live executor, so a bad
  canary costs latency, never client-visible failures.
- **post-swap** — after a full swap the previous live version is kept
  warm as the rollback target; if the new live's windowed failure rate
  crosses the threshold, dispatch rolls back to it (once — no
  ping-pong; the failed version is terminal).

Promotion: a canary that serves ``canary_min_batches`` batches without
tripping either condition is promoted to live (the old live retires into
the rollback slot).

Threading: ``route`` (device worker), ``ingest`` (watcher thread),
``status``/admin verbs (metrics HTTP threads) and the scheduler's
``version_fn`` (event loop) all cross this object — every shared field
is guarded by ``_lock`` (mtlint guarded-by discipline); executors are
only ever CALLED outside the lock.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ... import obs
from ...common import faultpoints as fp
from ...common import lockdep
from ...common import logging as log
from ...training import bundle as bdl
from .. import metrics as msm
from . import registry as reg
from .warmup import (DEFAULT_GOLDEN, CompatMismatch, WarmupError,
                     check_compat, warm_executor)

# Windowed health accounting: failure rate over the last OUTCOME_WINDOW
# batches (not all-time — a long-lived live version must stay
# roll-back-able on a FRESH error burst), p99 over the last
# LATENCY_WINDOW samples, compared only past P99_MIN_SAMPLES on each side.
OUTCOME_WINDOW = 64
LATENCY_WINDOW = 256
P99_MIN_SAMPLES = 20

ExecutorFactory = Callable[[str, Optional[Dict]],
                           Callable[[List[str]], List[str]]]


class _Stats:
    """Per-version health window (guarded by the controller lock)."""

    __slots__ = ("requests", "errors", "outcomes", "latencies")

    def __init__(self):
        self.requests = 0
        self.errors = 0
        self.outcomes: Deque[bool] = collections.deque(
            maxlen=OUTCOME_WINDOW)          # True = error
        self.latencies: Deque[float] = collections.deque(
            maxlen=LATENCY_WINDOW)

    def error_rate(self) -> float:
        if not self.outcomes:
            return 0.0
        return sum(self.outcomes) / len(self.outcomes)

    def p99(self) -> float:
        if not self.latencies:
            return 0.0
        vals = sorted(self.latencies)
        return vals[int(0.99 * (len(vals) - 1))]


class SwapController:
    def __init__(self,
                 executor_factory: ExecutorFactory,
                 metrics_registry: Optional[msm.Registry] = None,
                 model_registry: Optional[reg.ModelRegistry] = None,
                 canary_fraction: float = 0.0,
                 rollback_error_rate: float = 0.5,
                 rollback_p99_factor: float = 0.0,
                 canary_min_batches: int = 8,
                 rollback_min_batches: int = 2,
                 golden: Optional[List[str]] = None):
        self.executor_factory = executor_factory
        self.registry = model_registry if model_registry is not None \
            else reg.ModelRegistry()
        self.canary_fraction = max(0.0, min(1.0, float(canary_fraction)))
        self.rollback_error_rate = float(rollback_error_rate)
        self.rollback_p99_factor = float(rollback_p99_factor)
        self.canary_min_batches = max(1, int(canary_min_batches))
        self.rollback_min_batches = max(1, int(rollback_min_batches))
        self.golden = list(golden) if golden else None

        # RLock: every state MUTATION (swap, promote, supersede,
        # rollback) holds it end-to-end — decision AND registry
        # transition — so a promotion racing a supersede cannot
        # interleave; readers still take it only for snapshots.
        self._lock = lockdep.make_rlock("SwapController._lock")
        self._live: Optional[reg.ModelVersion] = None      # guarded-by: _lock
        self._canary: Optional[reg.ModelVersion] = None    # guarded-by: _lock
        # the newest retired version, kept warm as the rollback target
        self._previous: Optional[reg.ModelVersion] = None  # guarded-by: _lock
        self._pinned = False                               # guarded-by: _lock
        self._batch_n = 0                                  # guarded-by: _lock
        self._stats: Dict[int, _Stats] = {}                # guarded-by: _lock
        # iteration-mode composition (ISSUE 11): set by attach_iteration.
        # Swap/canary/rollback re-point the scheduler's paged engine
        # through its quiesce protocol instead of relying on route()'s
        # per-batch executor read (which iteration mode never calls).
        self._sched = None
        self._quiesce_deadline = 2.0

        r = metrics_registry if metrics_registry is not None \
            else msm.REGISTRY
        self.m_info = r.gauge(
            "marian_model_info",
            "1 for the version(s) currently routing traffic (live + "
            "canary), 0 once retired/failed — correlate latency/error "
            "shifts with the exact swap that caused them",
            labels=("model_version", "bundle_seq", "compat_hash"))
        self.m_requests = r.counter(
            "marian_model_requests_total",
            "Device batches served, by model version",
            labels=("model_version",))
        self.m_errors = r.counter(
            "marian_model_errors_total",
            "Device batches failed, by model version",
            labels=("model_version",))
        self.m_latency = r.histogram(
            "marian_model_latency_seconds",
            "Device batch latency, by model version",
            labels=("model_version",))
        self.m_swaps = r.counter(
            "marian_lifecycle_swaps_total",
            "Hot-swaps committed (dispatch re-pointed at a new version)")
        self.m_rollbacks = r.counter(
            "marian_lifecycle_rollbacks_total",
            "Auto + manual rollbacks to the previous live version")
        self.m_rejects = r.counter(
            "marian_lifecycle_rejects_total",
            "Candidate bundles refused before serving",
            labels=("reason",))
        self.m_warming = r.gauge(
            "marian_lifecycle_warming",
            "1 while a candidate is loading/compiling/golden-smoking")

    # -- seeding ------------------------------------------------------------
    def seed_live(self, seq: int, name: str,
                  executor: Callable[[List[str]], List[str]],
                  compat: Optional[Dict] = None,
                  bundle_dir: str = "") -> reg.ModelVersion:
        """Register the boot-time model as the live version (the model
        the process loaded at startup — before any watcher ingestion)."""
        v = self.registry.register(seq, name, bundle_dir, compat=compat)
        v.executor = executor
        self.registry.transition(seq, reg.WARMING)
        self.registry.transition(seq, reg.LIVE)
        with self._lock:
            self._live = v
        self._set_info(v)
        return v

    # -- iteration-mode composition (ISSUE 11) ------------------------------
    def attach_iteration(self, scheduler, quiesce_deadline: float = 2.0
                         ) -> None:
        """Compose with ``--batching-mode iteration``: executors are
        EngineExecutor-shaped (callable for the golden smoke, ``.engine``
        for dispatch), swaps re-point the scheduler's paged engine via
        its quiesce protocol (stop joins → drain under
        ``quiesce_deadline`` → evict the overdue with retriable errors →
        install at a step boundary with an empty join set → resume), and
        per-round health flows back through ``round_observer`` so canary
        evaluation and live auto-rollback keep working.

        CANARY SEMANTICS DIFFER by necessity: the decode is ONE joint
        program, so a canary cannot take an f-fraction of batches — it
        takes ALL joins for its evaluation window (temporal canary)
        while the previous live engine stays warm for a cheap rollback.
        ``--canary-fraction > 0`` enables the canary phase; the fraction
        itself is ignored (docs/DEPLOYMENT.md)."""
        self._sched = scheduler
        self._quiesce_deadline = float(quiesce_deadline)
        scheduler.round_observer = self._note_round
        if self.canary_fraction > 0:
            log.info("model lifecycle: iteration mode — canary is "
                     "TEMPORAL (all joins route to the canary during "
                     "evaluation; --canary-fraction {} is ignored)",
                     self.canary_fraction)

    def _repoint(self, v: reg.ModelVersion, kind: str, wait: bool):
        """Re-point dispatch at ``v``'s engine through the scheduler's
        quiesce protocol (iteration mode; request mode is a no-op —
        route() reads ``_live`` per batch). MUST be called with the
        controller lock RELEASED: with ``wait=True`` this blocks on the
        event loop draining the engine, and the loop's rounds take the
        lock via version_fn/_note_round — holding it here would
        deadlock. ``wait=False`` is mandatory when the CALLER is the
        event-loop thread (the rollback paths driven by _note_round):
        the loop cannot wait on work only it can perform."""
        sched = self._sched
        if sched is None:
            return None
        engine = getattr(v.executor, "engine", None)
        if engine is None:
            log.error("model lifecycle: cannot re-point the paged "
                      "engine at {} — its executor has no .engine "
                      "(iteration mode needs EngineExecutor-shaped "
                      "executors)", v.name)
            return None
        return sched.request_quiesce(
            lambda: sched.install_engine(engine),
            self._quiesce_deadline, f"{kind} -> {v.name}", wait=wait)

    def _note_round(self, error: bool, dt: float) -> None:
        """Iteration-mode health hook (event-loop thread, once per
        engine round): attribute the round to the version whose engine
        actually served it — during a quiesce the registry may already
        name the incoming version while the outgoing engine drains, so
        attribution follows ENGINE IDENTITY, not registry state."""
        sched = self._sched
        if sched is None:
            return
        eng = getattr(sched, "engine", None)
        with self._lock:
            ver: Optional[reg.ModelVersion] = None
            is_canary = False
            for v, c in ((self._canary, True), (self._live, False),
                         (self._previous, False)):
                if v is not None \
                        and getattr(v.executor, "engine", None) is eng:
                    ver, is_canary = v, c
                    break
        if ver is None:
            return
        self._record(ver, dt, error=error)
        if is_canary:
            self._evaluate_canary(ver, allow_promote=not error)
        elif error:
            self._maybe_rollback_live(ver)

    def adopt_live_executor(self, executor) -> None:
        """The scheduler rebuilt the live engine after a watchdog trip
        (the wedged thread owns the old one): point the live version's
        executor at the replacement so round attribution and future
        rollbacks see the engine actually serving."""
        with self._lock:
            if self._live is not None:
                self._live.executor = executor

    def live_version(self) -> Optional[reg.ModelVersion]:
        with self._lock:
            return self._live

    # -- ingestion (watcher thread) -----------------------------------------
    def ingest(self, bundle_dir: str, manifest: Dict
               ) -> Optional[reg.ModelVersion]:
        """Take one freshly committed, validated bundle through
        staged → (compat check) → warming → canary|live. Runs fully on
        the calling (watcher) thread; dispatch is untouched until the
        final atomic install. Never raises — a bad candidate is recorded
        and the live version keeps serving."""
        seq = int(manifest.get("seq", 0) or 0)
        name = os.path.basename(bundle_dir)
        with self._lock:
            pinned = self._pinned
            live = self._live
        try:
            v = self.registry.register(seq, name, bundle_dir, manifest)
        except reg.LifecycleError as e:
            log.warn("model lifecycle: not ingesting {}: {}", name, e)
            return None
        if pinned:
            self.registry.transition(seq, reg.REJECTED,
                                     "registry pinned by operator")
            self.m_rejects.labels("pinned").inc()
            obs.event("lifecycle.rejected", version=name, reason="pinned")
            return v
        try:
            check_compat(v.compat, live.compat if live else None, name)
        except CompatMismatch as e:
            self.registry.transition(seq, reg.REJECTED, str(e))
            self.m_rejects.labels("compat").inc()
            obs.event("lifecycle.rejected", version=name, reason="compat")
            log.error("model lifecycle: REFUSED incompatible bundle: {}", e)
            return v
        self.registry.transition(seq, reg.WARMING)
        self.m_warming.set(1)
        obs.event("lifecycle.warming", version=name)
        try:
            # version-labeled warmup: the perf plane records per-bucket
            # compile telemetry under trigger=swap-warmup (ISSUE 9), so
            # the lifecycle swap test can prove zero steady-state
            # recompiles after a hot-swap
            executor = warm_executor(bundle_dir, manifest,  # mtlint: disable=MT-LOCK-BLOCKING -- only the fleet's per-tenant _Tenant.warm_lock reaches here held (FleetManager._warm), and stalling a duplicate cold start of the same tenant behind the first one is that lock's purpose
                                     self.executor_factory,
                                     self.golden or list(DEFAULT_GOLDEN),
                                     version=name)
        except Exception as e:  # noqa: BLE001 — incl. injected faults:
            # ANY warmup error fails the candidate, never the watcher loop
            self.registry.transition(seq, reg.FAILED, str(e))
            self.m_rejects.labels("warmup").inc()
            obs.event("lifecycle.warmup_failed", version=name,
                      error=str(e)[:200])
            log.error("model lifecycle: candidate {} failed warmup: {}",
                      name, e)
            return v
        finally:
            self.m_warming.set(0)
        v.executor = executor
        try:
            self._install(v)
        except Exception as e:  # noqa: BLE001 — a failed install (e.g. an
            # injected lifecycle.swap fault) must leave the LIVE version
            # serving and the candidate in a terminal state, not wedge
            # the watcher with a half-installed executor
            log.error("model lifecycle: install of {} failed ({}); live "
                      "version keeps serving", name, e)
            try:
                self.registry.transition(seq, reg.FAILED,
                                         f"install failed: {e}")
            except reg.LifecycleError:
                pass
            self._release(v)
            self.m_rejects.labels("install").inc()
        return v

    def _release(self, v: Optional[reg.ModelVersion]) -> None:
        """Drop a version's executor AND health window once it can never
        be routed again (it left the {live, canary, rollback-target}
        set). Every warmed executor pins a whole model — host + device
        arrays + jit caches — and every _Stats entry holds sample
        deques, so a server hot-swapping for weeks must not accumulate
        either; the registry keeps only the version's metadata row."""
        if v is not None:
            v.executor = None
            with self._lock:
                self._stats.pop(v.seq, None)

    def _install(self, v: reg.ModelVersion) -> None:
        """A warmed candidate enters service: as a canary when canary
        routing is on and a live version exists, else by immediate swap.
        In iteration mode the engine re-point happens FIRST, through the
        quiesce protocol (watcher thread, blocking until the drain
        completes): the registry only flips once the candidate's engine
        is verifiably serving — a failed install leaves the old engine
        and the old registry state untouched."""
        with self._lock:
            has_live = self._live is not None
        if self._sched is not None and has_live:
            op = self._repoint(
                v, "canary" if self.canary_fraction > 0 else "swap",
                wait=True)
            if op is not None \
                    and not (op.event.is_set() and op.install_ok):
                raise WarmupError(
                    f"quiesce install of {v.name} did not complete "
                    f"(the previous engine keeps serving)")
        if self.canary_fraction > 0 and has_live:
            with self._lock:
                self.registry.transition(v.seq, reg.CANARY)
                superseded = self._canary
                self._canary = v
                self._stats.pop(v.seq, None)     # fresh health window
                if superseded is not None \
                        and superseded.state == reg.CANARY:
                    # a newer candidate replaces a still-evaluating
                    # canary: it leaves routing NOW — terminal state +
                    # executor released, so /lifecyclez and
                    # marian_model_info never show two routable
                    # canaries. The state re-check under the controller
                    # lock is load-bearing: a concurrent promotion
                    # (route thread) may have just made it live, and
                    # live→retired is a legal edge that would otherwise
                    # retire + release the LIVE version.
                    self.registry.transition(superseded.seq, reg.RETIRED,
                                             f"superseded by {v.name}")
                    self._release(superseded)
                else:
                    superseded = None
            if superseded is not None:
                self._set_info(superseded)
            self._set_info(v)
            obs.event("lifecycle.canary", version=v.name,
                      fraction=self.canary_fraction)
            log.info("model lifecycle: {} serving as canary "
                     "({}% of batches; promotes after {} healthy ones)",
                     v.name, round(self.canary_fraction * 100, 1),
                     self.canary_min_batches)
        else:
            self._swap_to_live(v)
            obs.event("lifecycle.swap", version=v.name)

    def _swap_to_live(self, v: reg.ModelVersion) -> None:
        """THE swap: re-point dispatch at ``v`` between batches. The old
        live version retires into the rollback slot (kept warm)."""
        fp.fault_point("lifecycle.swap")
        with self._lock:
            self.registry.transition(v.seq, reg.LIVE)
            old = self._live
            dropped = self._previous
            self._live = v
            if self._canary is v:
                self._canary = None
            self._previous = old
            if old is not None:
                self.registry.transition(old.seq, reg.RETIRED)
            if dropped is not None and dropped is not v \
                    and dropped is not old:
                self._release(dropped)   # no longer the rollback target
        if old is not None:
            self._set_info(old)
        self._set_info(v)
        self.m_swaps.inc()
        # NOTE: no DIRECT obs call here — the canary-promote path runs
        # this whole method under _lock, and the only obs-under-_lock
        # edge the static graph models is the registry.transition
        # timeline event (see registry.py); callers emit the
        # lifecycle.swap event at their unlocked sites
        log.info("model lifecycle: SWAP — {} is now live{}", v.name,
                 f" (rollback target: {old.name})" if old else "")

    # -- dispatch (device worker thread) ------------------------------------
    def route(self, lines: List[str]) -> List[str]:
        """The scheduler's translate_lines. Picks live or canary for THIS
        batch, records per-version health, and transparently re-serves a
        failed canary batch on the live executor."""
        ver, fn, is_canary = self._pick()
        if ver is None or fn is None:
            raise RuntimeError("no live model version to dispatch to")
        # stamp the routing decision onto the scheduler's serve.translate
        # span (this thread's current span — the scheduler set it before
        # calling us), so every span tree carries its model_version
        if obs.enabled():
            obs.set_attrs(model_version=ver.name, canary=is_canary)
        t0 = time.perf_counter()
        try:
            out = fn(lines)
        except Exception as e:  # noqa: BLE001 — health-accounted, re-served
            self._record(ver, time.perf_counter() - t0, error=True)
            if not is_canary:
                self._maybe_rollback_live(ver)
                raise
            log.warn("model lifecycle: canary {} batch failed ({}); "
                     "re-serving on live", ver.name, e)
            # rollback-only evaluation: promoting here could make the
            # just-failed canary live BEFORE the re-serve below, turning
            # the promised transparent retry into a client-visible error
            self._evaluate_canary(ver, allow_promote=False)
            return self._serve_on_live(lines, ver)
        self._record(ver, time.perf_counter() - t0)
        if is_canary:
            self._evaluate_canary(ver)
        return out

    def _pick(self) -> Tuple[Optional[reg.ModelVersion],
                             Optional[Callable[[List[str]], List[str]]],
                             bool]:
        """(version, executor, is_canary) for THIS batch. The executor is
        captured UNDER the lock: a concurrent supersede/swap may
        _release() the version right after, and the captured closure is
        what keeps its model alive until the batch finishes."""
        with self._lock:
            canary = self._canary
            if canary is not None and canary.executor is not None:
                # deterministic f-fraction of batches: fires on exactly
                # the batches where the running product crosses an
                # integer boundary
                self._batch_n += 1
                n, f = self._batch_n, self.canary_fraction
                if int(n * f) != int((n - 1) * f):
                    return canary, canary.executor, True
            live = self._live
            return live, live.executor if live is not None else None, False

    def _serve_on_live(self, lines: List[str],
                       failed_canary: reg.ModelVersion) -> List[str]:
        with self._lock:
            live = self._live
            fn = live.executor if live is not None else None
        if live is None or live is failed_canary or fn is None:
            raise RuntimeError("canary batch failed and no live version "
                               "can re-serve it")
        if obs.enabled():
            obs.set_attrs(model_version=live.name,
                          re_served_after=failed_canary.name)
        t0 = time.perf_counter()
        try:
            out = fn(lines)
        except Exception:
            self._record(live, time.perf_counter() - t0, error=True)
            self._maybe_rollback_live(live)
            raise
        self._record(live, time.perf_counter() - t0)
        return out

    def _record(self, v: reg.ModelVersion, dt: float,
                error: bool = False) -> None:
        with self._lock:
            st = self._stats.get(v.seq)
            if st is None:
                st = self._stats[v.seq] = _Stats()
            st.requests += 1
            st.outcomes.append(error)
            st.latencies.append(dt)
            if error:
                st.errors += 1
        self.m_requests.labels(v.name).inc()
        self.m_latency.labels(v.name).observe(dt)
        if error:
            self.m_errors.labels(v.name).inc()

    # -- health evaluation --------------------------------------------------
    def _health(self, v: Optional[reg.ModelVersion]
                ) -> Tuple[int, float, float, int]:
        """(requests, windowed error rate, p99, latency samples)."""
        with self._lock:
            st = self._stats.get(v.seq) if v is not None else None
            if st is None:
                return 0, 0.0, 0.0, 0
            return (st.requests, st.error_rate(), st.p99(),
                    len(st.latencies))

    def _evaluate_canary(self, canary: reg.ModelVersion,
                         allow_promote: bool = True) -> None:
        """After every canary batch: roll back on a tripped threshold,
        promote after enough healthy batches (``allow_promote=False`` on
        the batch-error path — the failed batch still has to be re-served
        on live). Transition races (an admin verb landing mid-evaluation)
        are logged, never propagated into the serving path."""
        n, err_rate, p99, lat_n = self._health(canary)
        with self._lock:
            live = self._live
        _, _, live_p99, live_lat_n = self._health(live)
        reason = ""
        if n >= self.rollback_min_batches \
                and err_rate > self.rollback_error_rate:
            reason = (f"failure rate {err_rate:.2f} > "
                      f"{self.rollback_error_rate:.2f} over the last "
                      f"{min(n, OUTCOME_WINDOW)} batches")
        elif self.rollback_p99_factor > 0 \
                and lat_n >= P99_MIN_SAMPLES \
                and live_lat_n >= P99_MIN_SAMPLES \
                and p99 > self.rollback_p99_factor * live_p99:
            reason = (f"p99 {p99 * 1e3:.1f}ms > "
                      f"{self.rollback_p99_factor:g}x live "
                      f"{live_p99 * 1e3:.1f}ms")
        try:
            if reason:
                self._rollback_canary(canary, reason)
            elif allow_promote and n >= self.canary_min_batches:
                with self._lock:
                    # a newer candidate may have superseded this canary
                    # (watcher thread) between the batch and this
                    # evaluation — promotion is only legal while it is
                    # still THE canary
                    if self._canary is not canary:
                        return
                    log.info("model lifecycle: canary {} healthy after "
                             "{} batches (failure rate {:.2f}) — "
                             "promoting", canary.name, n, err_rate)
                    self._swap_to_live(canary)
                obs.event("lifecycle.swap", version=canary.name,
                          promoted=True)
        except Exception as e:  # noqa: BLE001 — a raced transition or an
            # injected swap/rollback fault aborts THIS evaluation only;
            # routing stands and the next canary batch re-evaluates
            log.warn("model lifecycle: canary evaluation aborted ({}) — "
                     "keeping current routing", e)

    def _rollback_canary(self, canary: reg.ModelVersion,
                         reason: str) -> None:
        fp.fault_point("lifecycle.rollback")
        with self._lock:
            live = self._live
            self.registry.transition(canary.seq, reg.FAILED, reason)
            if self._canary is canary:
                self._canary = None
            self._release(canary)
        if live is not None:
            # iteration mode: the temporal canary's engine is the one
            # serving — re-point back at the live engine via quiesce.
            # wait=False: this runs on the event-loop thread
            # (_note_round), which is the thread that executes the
            # quiesce; waiting here would deadlock. no-op in request
            # mode (route() already routes to live).
            self._repoint(live, "rollback", wait=False)
        self._set_info(canary)
        self.m_rollbacks.inc()
        log.error("model lifecycle: ROLLBACK — canary {} failed ({}); "
                  "dispatch stays on the live version", canary.name, reason)
        # post-mortem snapshot (ISSUE 8): the span ring still holds the
        # canary batches that tripped the threshold — dump them before
        # they rotate out. Outside the lock, like every obs call here.
        obs.event("lifecycle.rollback", version=canary.name,
                  reason=reason, kind="canary")
        obs.FLIGHT.trip("canary-rollback", detail=reason,
                        extra={"version": canary.name})

    def _maybe_rollback_live(self, live: reg.ModelVersion) -> None:
        """Post-swap safety net: a regressed NEW live rolls back to the
        retired-but-warm previous version. One-shot per swap (the failed
        version is terminal) so two bad versions cannot ping-pong."""
        n, err_rate, _, _ = self._health(live)
        if n < self.rollback_min_batches \
                or err_rate <= self.rollback_error_rate:
            return
        reason = (f"live failure rate {err_rate:.2f} > "
                  f"{self.rollback_error_rate:.2f}")
        rolled_to = None
        try:
            with self._lock:
                if self._live is not live:
                    return                   # already rolled back / swapped
                prev = self._previous
                if prev is None or prev.executor is None:
                    return                   # boot model: nothing to roll to
                self._rollback_to(prev, live, reason, auto=True)
                rolled_to = prev
        except Exception as e:  # noqa: BLE001 — the caller is already on
            # a batch-failure path; a raced/injected rollback error must
            # not mask the original batch exception
            log.warn("model lifecycle: live rollback aborted ({})", e)
        if rolled_to is not None:
            # iteration mode: enqueue the engine re-point (wait=False —
            # this path runs on the event-loop thread via _note_round;
            # the quiesce executes over the NEXT rounds). Request mode:
            # no-op, route() reads the flipped _live per batch.
            self._repoint(rolled_to, "rollback", wait=False)
            # flight dump AFTER the lock is released — dump IO must
            # never run under control-plane locks (MT-LOCK-BLOCKING)
            obs.event("lifecycle.rollback", version=live.name,
                      to=rolled_to.name, reason=reason, kind="live")
            obs.FLIGHT.trip("live-rollback", detail=reason,
                            extra={"from": live.name,
                                   "to": rolled_to.name})

    def _rollback_to(self, prev: reg.ModelVersion,
                     cur: reg.ModelVersion, reason: str,
                     auto: bool) -> None:
        fp.fault_point("lifecycle.rollback")
        with self._lock:
            self.registry.transition(cur.seq,
                                     reg.FAILED if auto else reg.RETIRED,
                                     reason)
            self.registry.transition(prev.seq, reg.LIVE)
            self._live = prev
            # the rolled-back-from version is no rollback target
            self._previous = cur if not auto else None
            if auto:
                self._release(cur)   # terminal (failed) — drop its model
        self._set_info(cur)
        self._set_info(prev)
        self.m_rollbacks.inc()
        log.error("model lifecycle: ROLLBACK — {} -> {} ({})",
                  cur.name, prev.name, reason)

    # -- admin verbs + introspection ----------------------------------------
    def pin(self) -> None:
        """Freeze the registry: new bundles are rejected (state
        ``rejected``) until unpin — the operator's 'stop all rollouts
        NOW' switch."""
        with self._lock:
            self._pinned = True
        log.info("model lifecycle: registry PINNED (new bundles rejected)")

    def unpin(self) -> None:
        with self._lock:
            self._pinned = False
        log.info("model lifecycle: registry unpinned")

    def rollback(self) -> bool:
        """Manual rollback to the previous live version (admin verb).
        Returns False when there is nothing to roll back to."""
        with self._lock:
            prev, cur = self._previous, self._live
            if prev is None or cur is None or prev.executor is None:
                log.warn("model lifecycle: manual rollback requested but "
                         "no previous live version is retained")
                return False
            self._rollback_to(prev, cur, "manual rollback (admin verb)",
                              auto=False)
        # iteration mode: blocking re-point is safe here — admin verbs
        # run on the metrics HTTP thread, not the event loop
        self._repoint(prev, "rollback", wait=True)
        obs.event("lifecycle.rollback", version=cur.name, to=prev.name,
                  kind="manual")
        obs.FLIGHT.trip("manual-rollback",
                        detail=f"{cur.name} -> {prev.name} (admin verb)")
        return True

    def has_live(self) -> bool:
        with self._lock:
            return self._live is not None

    def live_version_name(self) -> str:
        """Label value for the scheduler's outcome metrics."""
        with self._lock:
            return self._live.name if self._live is not None else "none"

    def warming(self) -> bool:
        return bool(self.m_warming.value)

    def status(self) -> Dict:
        """JSON-ready lifecycle state for /lifecyclez."""
        with self._lock:
            live, canary, prev = self._live, self._canary, self._previous
            pinned = self._pinned
            stats = {seq: (st.requests, st.errors, st.error_rate(),
                           st.p99())
                     for seq, st in self._stats.items()}
        rows = self.registry.snapshot()
        for row in rows:
            req, errs, rate, p99 = stats.get(row["seq"], (0, 0, 0.0, 0.0))
            row.update(requests=req, errors=errs,
                       windowed_error_rate=round(rate, 4),
                       p99_seconds=round(p99, 6))
        return {
            "live": live.name if live else None,
            "canary": canary.name if canary else None,
            "rollback_target": prev.name if prev else None,
            "pinned": pinned,
            "warming": self.warming(),
            "canary_fraction": self.canary_fraction,
            "versions": rows,
        }

    def _set_info(self, v: reg.ModelVersion) -> None:
        self.m_info.labels(
            v.name, str(v.seq), bdl.compat_hash(v.compat)
        ).set(1 if v.state in (reg.LIVE, reg.CANARY) else 0)
