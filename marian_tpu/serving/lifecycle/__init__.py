"""Zero-downtime model lifecycle (ISSUE 5): the deployment control plane
between the trainer's committed checkpoint bundles (training/bundle.py)
and the continuous-batching scheduler (serving/scheduler.py).

    train ──commit──► bundle ──watch──► warmup ──swap──► serve
                        ▲                (off-path)  │
                        └────────── rollback ◄───────┘

- ``registry``   — ModelRegistry: per-version state machine
  (staged → warming → canary → live → retired, + rejected/failed)
- ``watcher``    — BundleWatcher: seq+mtime polling thread, no inotify
- ``warmup``     — compat refusal, executor load, golden-set smoke
- ``controller`` — SwapController: atomic between-batch re-pointing,
  --canary-fraction routing, failure-rate/p99 auto-rollback, admin verbs
- ``compile_cache`` — persisted XLA compilation cache as a bundle
  member (ISSUE 20): pack on commit, key-verify + adopt before warmup
  so a swap (or fleet cold start) is load+verify instead of full jit

Operator runbook: docs/DEPLOYMENT.md.
"""

from .controller import SwapController
from .registry import (CANARY, FAILED, LIVE, REJECTED, RETIRED, STAGED,
                       WARMING, BundleInfo, LifecycleError, ModelRegistry,
                       ModelVersion, scan_bundles)
from .warmup import (DEFAULT_GOLDEN, CompatMismatch, WarmupError,
                     load_golden)
from .watcher import BundleWatcher

__all__ = [
    "SwapController", "BundleWatcher",
    "ModelRegistry", "ModelVersion", "BundleInfo", "LifecycleError",
    "scan_bundles",
    "STAGED", "WARMING", "CANARY", "LIVE", "RETIRED", "FAILED", "REJECTED",
    "CompatMismatch", "WarmupError", "DEFAULT_GOLDEN", "load_golden",
]
