"""Brownout ladder — explicit, signal-driven degradation under
sustained overload (ISSUE 11 tentpole, the "degrade gracefully instead
of falling off a cliff" half).

Without it, the only overload behaviors are the admission queue bound
(explicit shed at a hard edge) and fail-loud engine rounds: every
priority lane's latency diverges together until something sheds. The
ladder converts sustained overload into ORDERED, observable degradation
levels, each one an explicit trade a fleet operator can reason about:

- **level 0 (normal)** — nothing.
- **level 1 (tighten)** — new decode rows claim a scaled-down decode
  cap (``--brownout-cap-factor``): each sentence costs fewer KV pages
  and fewer steps, so throughput rises at the price of possible
  truncation of the longest outputs.
- **level 2 (evict)** — when queued work outranks a decoding row, the
  lowest-priority active row (tie-break: longest remaining decode) is
  evicted with a retriable ``!!SERVER-RETRY``, one per round — capacity
  flows to the high lanes gradually and predictably.
- **level 3 (shed)** — admission sheds requests below
  ``--brownout-min-priority`` with an explicit !!SERVER-OVERLOADED; the
  high lanes keep a bounded queue and a bounded p99 while the low lanes
  fail fast instead of timing out slowly.

Signals (both already maintained by the observability plane — the
ladder adds no accounting of its own):

- ``marian_capacity_headroom_ratio`` (obs/perf.py): headroom at or
  below ``--brownout-headroom`` means the replica is saturated;
- the SLO engine's fast-window burn rate (obs/slo.py): burn at or
  above the fast-burn factor means the error budget is being consumed
  at incident speed.

Either signal sustained for ``--brownout-hold`` seconds escalates one
level; both healthy for ``--brownout-cool`` seconds de-escalates one
level. Every transition is a timeline event (``brownout.level``), a
gauge move (``marian_brownout_level``), a counter
(``marian_brownout_transitions_total{direction}``), and — on
escalation — a flight-recorder dump, so the incident is captured while
it unfolds (docs/ROBUSTNESS.md "The brownout ladder").

The evaluator runs on its own daemon thread (like the SLO engine);
nothing here touches the batch path — effects are applied through
``apply_fn`` (ServingApp wires the scheduler's and admission
controller's level setters).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from .. import obs
from ..common import lockdep
from ..common import logging as log

LEVEL_NAMES = ("normal", "tighten", "evict", "shed")

DEFAULT_HEADROOM_FLOOR = 0.1
DEFAULT_BURN_THRESHOLD = 14.4       # the SLO engine's fast-burn factor
DEFAULT_HOLD_S = 5.0
DEFAULT_COOL_S = 15.0
DEFAULT_INTERVAL_S = 1.0


class BrownoutController:
    def __init__(self,
                 apply_fn: Callable[[int], None],
                 headroom_fn: Optional[Callable[[], float]] = None,
                 burn_fn: Optional[Callable[[], float]] = None,
                 registry=None,
                 headroom_floor: float = DEFAULT_HEADROOM_FLOOR,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD,
                 hold_s: float = DEFAULT_HOLD_S,
                 cool_s: float = DEFAULT_COOL_S,
                 interval: float = DEFAULT_INTERVAL_S,
                 max_level: int = 3,
                 clock: Callable[[], float] = time.monotonic):
        from . import metrics as msm      # lazy: no import cycle
        self.apply_fn = apply_fn
        self.headroom_fn = headroom_fn
        self.burn_fn = burn_fn
        self.headroom_floor = float(headroom_floor)
        self.burn_threshold = float(burn_threshold)
        self.hold_s = max(0.0, float(hold_s))
        self.cool_s = max(0.0, float(cool_s))
        self.interval = max(0.05, float(interval))
        self.max_level = max(1, min(3, int(max_level)))
        self.clock = clock
        self._lock = lockdep.make_lock("BrownoutController._lock")
        self._level = 0                         # guarded-by: _lock
        self._pressure_since: Optional[float] = None   # guarded-by: _lock
        self._healthy_since: Optional[float] = None    # guarded-by: _lock
        self._last_signals: Dict = {}           # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

        r = registry if registry is not None else msm.REGISTRY
        self.m_level = r.gauge(
            "marian_brownout_level",
            "Current brownout degradation level (0 normal, 1 tighten "
            "decode caps, 2 evict low-priority rows, 3 shed low-"
            "priority admissions)")
        self.m_level.set(0)
        self.m_transitions = r.counter(
            "marian_brownout_transitions_total",
            "Brownout ladder level transitions", labels=("direction",))

    # -- signals ------------------------------------------------------------
    def _read_signals(self):
        headroom = 1.0
        burn = 0.0
        if self.headroom_fn is not None:
            try:
                headroom = float(self.headroom_fn())
            except Exception:  # noqa: BLE001 — a broken gauge must not
                headroom = 1.0                    # wedge the evaluator
        if self.burn_fn is not None:
            try:
                burn = float(self.burn_fn())
            except Exception:  # noqa: BLE001
                burn = 0.0
        return headroom, burn

    # -- evaluation ---------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> int:
        """One evaluation: read signals, maybe move one level, apply +
        announce the transition. Returns the (possibly new) level.
        Called by the evaluator thread — and directly by tests with a
        fake clock."""
        if now is None:
            now = self.clock()
        headroom, burn = self._read_signals()
        overloaded = headroom <= self.headroom_floor \
            or (self.burn_threshold > 0 and burn >= self.burn_threshold)
        new_level: Optional[int] = None
        with self._lock:
            level = self._level
            if overloaded:
                self._healthy_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                if level < self.max_level \
                        and now - self._pressure_since >= self.hold_s:
                    new_level = level + 1
                    self._pressure_since = now   # next rung needs its
                    #                              own sustained hold
            else:
                self._pressure_since = None
                if self._healthy_since is None:
                    self._healthy_since = now
                if level > 0 \
                        and now - self._healthy_since >= self.cool_s:
                    new_level = level - 1
                    self._healthy_since = now
            if new_level is not None:
                self._level = new_level
            self._last_signals = {
                "headroom": round(headroom, 4), "burn": round(burn, 3),
                "overloaded": overloaded, "ts": now}
        if new_level is None:
            return level
        # effects + announcements OUTSIDE the lock (apply_fn reaches
        # into the scheduler/admission; dump IO must never run under a
        # control-plane lock)
        up = new_level > level
        try:
            self.apply_fn(new_level)
        except Exception as e:  # noqa: BLE001 — a failed effect keeps
            log.error("brownout apply({}) failed: {}", new_level, e)
        self.m_level.set(new_level)
        self.m_transitions.labels("up" if up else "down").inc()
        obs.event("brownout.level", level=new_level,
                  level_name=LEVEL_NAMES[new_level],
                  direction="up" if up else "down",
                  headroom=round(headroom, 4), burn=round(burn, 3))
        logf = log.error if up else log.info
        logf("BROWNOUT: level {} -> {} ({}) — headroom {:.3f} (floor "
             "{:.2f}), fast burn {:.1f} (threshold {:.1f})", level,
             new_level, LEVEL_NAMES[new_level], headroom,
             self.headroom_floor, burn, self.burn_threshold)
        if up:
            # escalations are incidents: capture the span ring + state
            # while the overload is unfolding, not after
            obs.FLIGHT.trip_async(
                "brownout",
                detail=f"escalated to level {new_level} "
                       f"({LEVEL_NAMES[new_level]}): headroom "
                       f"{headroom:.3f}, burn {burn:.1f}")
        return new_level

    def level(self) -> int:
        with self._lock:
            return self._level

    def state(self) -> Dict:
        """JSON-ready state (flight dumps, /sloz)."""
        with self._lock:
            return {
                "enabled": True,
                "level": self._level,
                "name": LEVEL_NAMES[self._level],
                "headroom_floor": self.headroom_floor,
                "burn_threshold": self.burn_threshold,
                "hold_s": self.hold_s,
                "cool_s": self.cool_s,
                "signals": dict(self._last_signals),
            }

    # -- evaluator thread ---------------------------------------------------
    def start(self) -> "BrownoutController":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="brownout-eval")
            self._thread.start()
            log.info("brownout ladder armed: headroom floor {:g}, burn "
                     "threshold {:g}, hold {:g}s, cool {:g}s",
                     self.headroom_floor, self.burn_threshold,
                     self.hold_s, self.cool_s)
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the evaluator must
                log.warn("brownout tick failed: {}", e)      # never die

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
        # leaving a degradation level armed after the controller is gone
        # would brown the replica out forever
        reset = False
        with self._lock:
            if self._level != 0:
                self._level = 0
                reset = True
        if reset:
            try:
                self.apply_fn(0)
            except Exception:  # noqa: BLE001
                pass
            self.m_level.set(0)
