"""Rescorer: teacher-forced CE scoring of parallel corpora / n-best lists
(reference: src/rescorer/rescorer.h :: Rescore<Rescorer>::run, used for
R2L reranking and --summary perplexity).

Outputs one score per line (sum of target log-probs, negated CE), or a
summary (cross-entropy / ce-mean-words / perplexity) over the corpus.
"""

from __future__ import annotations

import sys
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .common import logging as log
from .common import io as mio
from .data import BatchGenerator, Corpus, create_vocab
from .models.encoder_decoder import batch_to_arrays, create_model
from .ops.ops import cross_entropy


class Rescorer:
    def __init__(self, options):
        self.options = options
        log.create_loggers(options)
        model_path = (list(options.get("models", [])) or [options.get("model")])[0]
        params, cfg_yaml = mio.load_model(model_path)
        from .ops.quantization import wrap_quantized
        self.params = wrap_quantized(
            {k: jnp.asarray(v) for k, v in params.items()})
        from .models.encoder_decoder import apply_embedded_config
        options = self.options = apply_embedded_config(options, cfg_yaml)
        vocab_paths = list(options.get("vocabs", []))
        self.vocabs = [create_vocab(p, options, i)
                       for i, p in enumerate(vocab_paths)]
        # every vocab but the last is a source stream (multi-source parity
        # with training; see train.py)
        src_side = self.vocabs[:-1] if len(self.vocabs) > 2 else self.vocabs[0]
        self.model = create_model(options, src_side,
                                  self.vocabs[-1], inference=True)

        def per_sentence_ce(params, batch):
            from .models import transformer as T
            cparams = T.cast_params(params, self.model.cfg.compute_dtype)
            src_ids, src_mask = self.model._batch_sources(batch)
            enc = self.model._mod.encode(self.model.cfg, cparams,
                                         src_ids, src_mask,
                                         False, None)
            logits = self.model._mod.decode_train(
                self.model.cfg, cparams, enc, src_mask,
                batch["trg_ids"], batch["trg_mask"], train=False)
            ce = cross_entropy(logits, batch["trg_ids"], 0.0)
            ce = ce * batch["trg_mask"]
            return ce.sum(axis=-1), batch["trg_mask"].sum(axis=-1)

        self._score_fn = jax.jit(per_sentence_ce)

    def run(self, stream=None) -> List[float]:
        opts = self.options
        stream = stream or sys.stdout
        sets = list(opts.get("train-sets", []))
        corpus = Corpus(sets, self.vocabs,
                        opts.with_(**{"shuffle": "none",
                                      "max-length": opts.get("max-length", 1000),
                                      "max-length-crop": True}),
                        inference=False)
        bg = BatchGenerator(corpus, None,
                            mini_batch=int(opts.get("mini-batch", 64) or 64),
                            maxi_batch=10, maxi_batch_sort="src",
                            shuffle_batches=False, prefetch=True)
        scores: dict = {}
        total_ce = 0.0
        total_words = 0.0
        # depth-1 pipeline (common/pipeline.py): host per-row bookkeeping
        # of batch i hides behind batch i+1's device scoring
        from .common.pipeline import pipelined

        def _finalize(pbatch, handle):
            nonlocal total_ce, total_words
            ce, words = np.asarray(handle[0]), np.asarray(handle[1])
            for row in range(pbatch.size):
                sid = int(pbatch.sentence_ids[row])
                scores[sid] = -float(ce[row])  # Marian prints logP
                total_ce += float(ce[row])
                total_words += float(words[row])

        pipelined(bg,
                  lambda b: self._score_fn(self.params, batch_to_arrays(b)),
                  _finalize)
        ordered = [scores[i] for i in sorted(scores)]
        summary = opts.get("summary", None)
        if summary:
            if summary in (True, "cross-entropy"):
                value = total_ce
            elif summary == "ce-mean-words":
                value = total_ce / max(total_words, 1.0)
            elif summary == "perplexity":
                import math
                value = math.exp(min(total_ce / max(total_words, 1.0), 700))
            else:
                value = total_ce
            stream.write(f"{value:.6f}\n")
        else:
            for s in ordered:
                stream.write(f"{s:.6f}\n")
        stream.flush()
        return ordered


def rescore_main(options) -> None:
    Rescorer(options).run()
