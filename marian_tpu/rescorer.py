"""Rescorer: teacher-forced CE scoring of parallel corpora / n-best lists
(reference: src/rescorer/rescorer.h :: Rescore<Rescorer>::run, used for
R2L reranking and --summary perplexity).

Outputs one score per line (sum of target log-probs, negated CE), or a
summary (cross-entropy / ce-mean-words / perplexity) over the corpus.
"""

from __future__ import annotations

import sys
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .common import logging as log
from .common import io as mio
from .data import BatchGenerator, Corpus, create_vocab
from .models.encoder_decoder import batch_to_arrays, create_model
from .ops.ops import cross_entropy


class Rescorer:
    def __init__(self, options):
        self.options = options
        log.create_loggers(options)
        model_path = (list(options.get("models", [])) or [options.get("model")])[0]
        params, cfg_yaml = mio.load_model(model_path)
        from .ops.quantization import wrap_quantized
        self.params = wrap_quantized(
            {k: jnp.asarray(v) for k, v in params.items()})
        from .models.encoder_decoder import apply_embedded_config
        options = self.options = apply_embedded_config(options, cfg_yaml)
        vocab_paths = list(options.get("vocabs", []))
        self.vocabs = [create_vocab(p, options, i)
                       for i, p in enumerate(vocab_paths)]
        # every vocab but the last is a source stream (multi-source parity
        # with training; see train.py)
        src_side = self.vocabs[:-1] if len(self.vocabs) > 2 else self.vocabs[0]
        self.model = create_model(options, src_side,
                                  self.vocabs[-1], inference=True)

        # hoisted: the traced fn must not read self.model through its
        # closure — a rebind would silently retrace (MT-JIT-CLOSURE-VARYING)
        model = self.model

        def per_sentence_ce(params, batch):
            from .models import transformer as T
            cparams = T.cast_params(params, model.cfg.compute_dtype)
            src_ids, src_mask = model._batch_sources(batch)
            enc = model._mod.encode(model.cfg, cparams,
                                    src_ids, src_mask,
                                    False, None)
            logits = model._mod.decode_train(
                model.cfg, cparams, enc, src_mask,
                batch["trg_ids"], batch["trg_mask"], train=False)
            ce = cross_entropy(logits, batch["trg_ids"], 0.0)
            ce = ce * batch["trg_mask"]
            return ce.sum(axis=-1), batch["trg_mask"].sum(axis=-1)

        self._score_fn = jax.jit(per_sentence_ce)

    def _score_corpus(self, corpus):
        """Teacher-force score every sentence tuple: returns
        ({sid: logP}, total_ce, total_words). Shared by the parallel-
        corpus and n-best paths so score semantics can't drift."""
        opts = self.options
        bg = BatchGenerator(corpus, None,
                            mini_batch=int(opts.get("mini-batch", 64) or 64),
                            maxi_batch=10, maxi_batch_sort="src",
                            shuffle_batches=False, prefetch=True)
        scores: dict = {}
        total_ce = 0.0
        total_words = 0.0
        # depth-1 pipeline (common/pipeline.py): host per-row bookkeeping
        # of batch i hides behind batch i+1's device scoring
        from .common.pipeline import pipelined

        def _finalize(pbatch, handle):
            nonlocal total_ce, total_words
            ce, words = np.asarray(handle[0]), np.asarray(handle[1])
            for row in range(pbatch.size):
                sid = int(pbatch.sentence_ids[row])
                scores[sid] = -float(ce[row])  # Marian prints logP
                total_ce += float(ce[row])
                total_words += float(words[row])

        pipelined(bg,
                  lambda b: self._score_fn(self.params, batch_to_arrays(b)),
                  _finalize)
        return scores, total_ce, total_words

    def run(self, stream=None) -> List[float]:
        opts = self.options
        stream = stream or sys.stdout
        sets = list(opts.get("train-sets", []))
        if opts.get("n-best", False):
            return self._run_nbest(sets, stream)
        corpus = Corpus(sets, self.vocabs,
                        opts.with_(**{"shuffle": "none",
                                      "max-length": opts.get("max-length", 1000),
                                      "max-length-crop": True}),
                        inference=False)
        scores, total_ce, total_words = self._score_corpus(corpus)
        ordered = [scores[i] for i in sorted(scores)]
        summary = opts.get("summary", None)
        if summary:
            if summary in (True, "cross-entropy"):
                value = total_ce
            elif summary == "ce-mean-words":
                value = total_ce / max(total_words, 1.0)
            elif summary == "perplexity":
                import math
                value = math.exp(min(total_ce / max(total_words, 1.0), 700))
            else:
                value = total_ce
            stream.write(f"{value:.6f}\n")
        else:
            for s in ordered:
                stream.write(f"{s:.6f}\n")
        stream.flush()
        return ordered


    def _run_nbest(self, sets, stream) -> List[float]:
        """--n-best: the LAST train-set is an n-best list
        (`sid ||| hyp ||| features ||| score`), preceded by one file per
        source stream; every hypothesis is teacher-force scored against
        its sentence's source(s) and the list is re-emitted with the new
        feature appended to the features column (reference: rescorer.h
        n-best rescoring, the marian-scorer half of R2L reranking — an
        R2L model's hypotheses are reversed before scoring exactly as
        the training corpus reverses targets)."""
        opts = self.options
        n_src = max(len(self.vocabs) - 1, 1)
        if len(sets) != n_src + 1:
            raise ValueError(
                f"--n-best rescoring expects --train-sets with {n_src} "
                f"source file(s) + the n-best list (got {len(sets)})")
        src_streams = []
        for p in sets[:-1]:
            with open(p, "r", encoding="utf-8") as fh:
                src_streams.append([l.rstrip("\n") for l in fh])
        entries = []                      # (sid, hyp, parts)
        with open(sets[-1], "r", encoding="utf-8") as fh:
            for line in fh:
                parts = line.rstrip("\n").split(" ||| ")
                if len(parts) < 2:
                    raise ValueError(f"malformed n-best line: {line!r}")
                sid = int(parts[0])
                if not 0 <= sid < len(src_streams[0]):
                    raise ValueError(
                        f"n-best sentence id {sid} out of range for "
                        f"{len(src_streams[0])}-line source")
                entries.append((sid, parts[1], parts))
        from .data.corpus import TextInput
        streams = [[s[sid] for sid, _, _ in entries] for s in src_streams]
        streams.append([hyp for _, hyp, _ in entries])
        corpus = TextInput(streams, self.vocabs, opts,
                           reverse_target=bool(
                               opts.get("right-left", False)))
        scores, _, _ = self._score_corpus(corpus)
        feature = opts.get("n-best-feature", "Score")
        ordered = []
        for i, (_sid, _hyp, parts) in enumerate(entries):
            s = scores[i]
            ordered.append(s)
            seg = f"{feature}= {s:.6f}"
            if len(parts) >= 3:
                parts = list(parts)
                parts[2] = (parts[2] + " " + seg).strip()
            else:
                parts = list(parts) + [seg]
            stream.write(" ||| ".join(parts) + "\n")
        stream.flush()
        return ordered


def rescore_main(options) -> None:
    Rescorer(options).run()
