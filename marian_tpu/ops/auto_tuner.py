"""Auto-tuner: time alternative implementations and bind the fastest
(reference: src/graph/auto_tuner.h :: AutoTuner — Marian times e.g. int16
vs fp32 GEMM per shape-hash and calls the winner thereafter).

On TPU the choice that actually matters is made OUTSIDE jit, because the
implementation choice changes the compiled program: which attention kernel
(XLA-fused dense einsum vs the Pallas flash kernel) to compile for a given
sequence-length bucket. ``calibrate_flash_attention`` measures the crossover
once per process and rebinds the threshold that ``ops.attention.attention``
consults for its "auto" mode (opt-in via --auto-tune; the static default is
the v5e-measured ~1k crossover)."""

from __future__ import annotations

import sys
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


class AutoTuner:
    """Generic per-key implementation chooser (reference: AutoTuner::run /
    ::start/stop timing protocol, collapsed to explicit measurement)."""

    def __init__(self, warmup: int = 1, iters: int = 3):
        self.warmup = warmup
        self.iters = iters
        self._choice: Dict[Any, str] = {}
        self._timings: Dict[Any, Dict[str, float]] = {}

    def measure(self, fn: Callable, *args) -> float:
        """Median wall time of fn(*args) with device sync (block_until_ready
        replaces the reference's cudaStreamSynchronize timing fences)."""
        for _ in range(self.warmup):
            jax.block_until_ready(fn(*args))
        times = []
        for _ in range(self.iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            times.append(time.perf_counter() - t0)
        times.sort()
        return times[len(times) // 2]

    def pick(self, key: Any,
             candidates: Dict[str, Tuple[Callable, tuple]]) -> str:
        """Return the name of the fastest candidate for `key`, timing each
        once and caching the winner (per-shape-hash binding)."""
        if key in self._choice:
            return self._choice[key]
        timings = {name: self.measure(fn, *args)
                   for name, (fn, args) in candidates.items()}
        winner = min(timings, key=timings.get)
        self._choice[key] = winner
        self._timings[key] = timings
        return winner

    def run(self, key: Any,
            candidates: Dict[str, Tuple[Callable, tuple]]):
        """pick + call the winner (the reference AutoTuner::run shape)."""
        name = self.pick(key, candidates)
        fn, args = candidates[name]
        return fn(*args)


# ---------------------------------------------------------------------------
# flash-attention crossover calibration
# ---------------------------------------------------------------------------

_calibrated_threshold: Optional[int] = None


def flash_threshold(default: int = 1024) -> int:
    """Sequence length above which 'auto' picks the Pallas flash kernel."""
    return _calibrated_threshold if _calibrated_threshold is not None \
        else default


def calibrate_flash_attention(heads: int = 8, dim_head: int = 64,
                              batch: int = 4,
                              lengths=(256, 512, 1024, 2048),
                              causal: bool = True) -> int:
    """Time dense vs flash attention per length bucket on the current
    backend; bind the smallest length where flash wins (--auto-tune)."""
    global _calibrated_threshold
    from .attention import dense_attention
    from .pallas.flash_attention import flash_attention

    tuner = AutoTuner()
    crossover = None
    for t in lengths:
        q = jnp.ones((batch, heads, t, dim_head), jnp.bfloat16)
        mask = (jnp.tril(jnp.ones((t, t), jnp.bfloat16))[None, None]
                if causal else None)
        dense_j = jax.jit(lambda a, m: dense_attention(a, a, a, m))
        flash_j = jax.jit(lambda a: flash_attention(a, a, a, causal=causal))
        name = tuner.pick(("attn", t), {
            "dense": (dense_j, (q, mask)),
            "flash": (flash_j, (q,)),
        })
        if name == "flash" and crossover is None:
            crossover = t
    # No crossover measured → flash lost at every tested length; disable it
    # for 'auto' outright rather than extrapolating a win past the sweep.
    _calibrated_threshold = crossover if crossover is not None \
        else sys.maxsize
    return _calibrated_threshold
