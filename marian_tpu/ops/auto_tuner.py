"""Auto-tuner: time alternative implementations and bind the fastest
(reference: src/graph/auto_tuner.h :: AutoTuner — Marian times e.g. int16
vs fp32 GEMM per shape-hash and calls the winner thereafter).

On TPU the choice that actually matters is made OUTSIDE jit, because the
implementation choice changes the compiled program: which attention kernel
(XLA-fused dense einsum vs the Pallas flash kernel) to compile for a given
sequence-length bucket. ``calibrate_flash_attention`` measures the crossover
once per process and rebinds the threshold that ``ops.attention.attention``
consults for its "auto" mode (opt-in via --auto-tune; the static default is
the v5e-measured ~1k crossover)."""

from __future__ import annotations

import sys
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..common import logging as log


class AutoTuner:
    """Generic per-key implementation chooser (reference: AutoTuner::run /
    ::start/stop timing protocol, collapsed to explicit measurement)."""

    def __init__(self, warmup: int = 1, iters: int = 3):
        self.warmup = warmup
        self.iters = iters
        self._choice: Dict[Any, str] = {}
        self._timings: Dict[Any, Dict[str, float]] = {}

    def measure(self, fn: Callable, *args) -> float:
        """Median wall time of fn(*args) with device sync (block_until_ready
        replaces the reference's cudaStreamSynchronize timing fences)."""
        for _ in range(self.warmup):
            jax.block_until_ready(fn(*args))
        times = []
        for _ in range(self.iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            times.append(time.perf_counter() - t0)
        times.sort()
        return times[len(times) // 2]

    def pick(self, key: Any,
             candidates: Dict[str, Tuple[Callable, tuple]]) -> str:
        """Return the name of the fastest candidate for `key`, timing each
        once and caching the winner (per-shape-hash binding)."""
        if key in self._choice:
            return self._choice[key]
        timings = {name: self.measure(fn, *args)
                   for name, (fn, args) in candidates.items()}
        winner = min(timings, key=timings.get)
        self._choice[key] = winner
        self._timings[key] = timings
        return winner

    def run(self, key: Any,
            candidates: Dict[str, Tuple[Callable, tuple]]):
        """pick + call the winner (the reference AutoTuner::run shape)."""
        name = self.pick(key, candidates)
        fn, args = candidates[name]
        return fn(*args)


# ---------------------------------------------------------------------------
# Pallas kernel block/capacity registry (r6). One table, one convention:
# entries are validated at dh=64 (the NMT head width every silicon number
# was taken at) and HALVE for wider heads — per-cell VMEM scales with
# dh x the sequence-side block, so oversized heads degrade to smaller
# blocks (or the callers' fallback paths) instead of a Mosaic VMEM OOM.
# Same rule the r5 flash_attention dh>64 block_k halving established.
# ---------------------------------------------------------------------------

# per-kernel base entries at dh<=64: the sequence-side capacity each
# kernel holds per grid cell (packed: full padded Tq=Tk per (b, head-
# group) cell; decode: the whole [L, dh] cache row per (row, head) cell)
KERNEL_BLOCKS = {
    # packed fwd cell peak ~ g*T x g*T f32 scores + operands; T=256 at
    # g=2/dh=64 is ~2.5 MB — comfortably under the ~16 MB VMEM budget,
    # and the target regime (T 48-64) is far below the cap anyway
    "packed_attention": {"max_t": 256},
    # decode cell holds 2 x [L, dh] cache blocks + the [1, L] score row;
    # L=2048 at dh=64 f32 is ~1 MB/cache block
    "decode_attention": {"max_len": 2048},
    # paged-pool cell accumulates 2 x [max_pages*page_len, dh] VMEM
    # scratch rows (ops/pallas/kv_pool.py) — same per-row footprint as
    # the dense decode cell, so the same 2048-token cap applies; the
    # page-table granularity only changes WHICH HBM lines feed it
    "kv_pool": {"max_tokens": 2048},
}


def _dh_scaled(base: int, dh: int) -> int:
    """Halve a sequence-side capacity for every doubling of head width
    past the validated dh=64 (floor: one 64-wide block)."""
    v = base
    width = 64
    while width < dh:
        v //= 2
        width *= 2
    return max(v, 64)


# ---------------------------------------------------------------------------
# offline sweep overlay (ISSUE 20). The static KERNEL_BLOCKS table above
# holds hand-validated v5e numbers; scripts/kernel_sweep.py measures the
# same capacities ON a chip and records them WITH provenance (chip kind,
# device count, jax version, timestamp, per-candidate timings). Pointing
# MARIAN_KERNEL_SWEEP at that JSON overlays the table — but only when
# the recorded chip matches the running one: blocks tuned for different
# silicon are refused loudly (the provenance is the point — arxiv
# 1802.04799's autotuning loop records where numbers came from; a
# hand-edited table can't).
# ---------------------------------------------------------------------------

SWEEP_ENV = "MARIAN_KERNEL_SWEEP"
# provenance of the applied sweep (None = static table); kept for
# introspection/tests
SWEEP_PROVENANCE: Optional[Dict] = None
_sweep_checked = False


def load_kernel_sweep(path: str, chip: Optional[str] = None) -> bool:
    """Overlay ``KERNEL_BLOCKS`` from a kernel_sweep.py recording.
    Returns True when applied. Refuses (False, with a loud warning)
    when the recorded chip differs from the running one, when the file
    is malformed, or when it names unknown kernels/keys — a sweep that
    cannot be attributed must never silently change block sizes."""
    global SWEEP_PROVENANCE
    import json
    import os
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        log.warn("kernel sweep: cannot read {}: {} — keeping the "
                 "static KERNEL_BLOCKS table", path, e)
        return False
    if chip is None:
        try:
            chip = str(getattr(jax.devices()[0], "device_kind", "unknown"))
        except Exception:  # noqa: BLE001 — no backend: nothing to tune
            chip = "unknown"
    recorded = str(doc.get("chip", ""))
    if not recorded or recorded != chip:
        log.warn("kernel sweep: {} was recorded on chip '{}' but this "
                 "process runs on '{}' — REFUSING the overlay (re-run "
                 "scripts/kernel_sweep.py on this chip)",
                 path, recorded or "?", chip)
        return False
    blocks = doc.get("blocks", {})
    staged = {}
    for kernel, entries in blocks.items():
        if kernel not in KERNEL_BLOCKS:
            log.warn("kernel sweep: unknown kernel {!r} in {} — "
                     "refusing the whole overlay", kernel, path)
            return False
        for key, val in entries.items():
            if key not in KERNEL_BLOCKS[kernel] or int(val) < 64:
                log.warn("kernel sweep: bad entry {}.{}={!r} in {} — "
                         "refusing the whole overlay",
                         kernel, key, val, path)
                return False
            staged[(kernel, key)] = int(val)
    for (kernel, key), val in staged.items():
        KERNEL_BLOCKS[kernel][key] = val
    SWEEP_PROVENANCE = {k: doc.get(k) for k in
                        ("chip", "n_devices", "jax", "recorded_at",
                         "timings") if k in doc}
    SWEEP_PROVENANCE["path"] = os.path.abspath(path)
    log.info("kernel sweep: applied {} block override(s) from {} "
             "(chip '{}')", len(staged), path, recorded)
    return True


def _maybe_load_sweep_env() -> None:
    """One-shot lazy overlay from $MARIAN_KERNEL_SWEEP (checked at the
    first registry lookup, not import time — jax.devices() must not run
    on import)."""
    global _sweep_checked
    if _sweep_checked:
        return
    _sweep_checked = True
    import os
    path = os.environ.get(SWEEP_ENV, "")
    if path:
        load_kernel_sweep(path)


def kernel_block(kernel: str, key: str, dh: int) -> int:
    """Registry lookup with the dh-scaled VMEM convention applied."""
    _maybe_load_sweep_env()
    return _dh_scaled(KERNEL_BLOCKS[kernel][key], dh)


def packed_attention_max_t(dh: int) -> int:
    """Longest (padded) sequence the packed kernel takes per cell; past
    it the dispatcher leaves the shape to dense/flash.

    Two VMEM axes bound it: wide heads grow the [T, dh] operand blocks
    (the halving rule above), and NARROW heads grow the pack group g =
    128//dh, whose backward kernel materializes [g*T, g*T] f32 blocks —
    quadratic in g·T. So the cap bounds g*T at the validated point
    (dh=64: g=2 × T=256 = 512), not T alone: dh=32 → 128, dh=16 → 64.
    The target regime (T 48-64) stays inside the cap at every dh."""
    base = kernel_block("packed_attention", "max_t", dh)
    g = max(1, 128 // max(dh, 1))
    return max(64, min(base, 512 // g))


def decode_attention_max_len(dh: int) -> int:
    """Longest decode cache the fused kernel holds per cell; past it
    decode_attention degrades to its unfused jnp reference path."""
    return kernel_block("decode_attention", "max_len", dh)


def kv_pool_max_tokens(dh: int) -> int:
    """Longest per-row paged span (max_pages x page_len) the paged
    decode kernel assembles in VMEM scratch; past it
    paged_decode_attention degrades to its jnp gather reference."""
    return kernel_block("kv_pool", "max_tokens", dh)


# ---------------------------------------------------------------------------
# flash-attention crossover calibration
# ---------------------------------------------------------------------------

_calibrated_threshold: Optional[int] = None


def flash_threshold(default: int = 1024) -> int:
    """Sequence length above which 'auto' picks the Pallas flash kernel."""
    return _calibrated_threshold if _calibrated_threshold is not None \
        else default


def calibrate_flash_attention(heads: int = 8, dim_head: int = 64,
                              batch: int = 4,
                              lengths=(256, 512, 1024, 2048),
                              causal: bool = True) -> int:
    """Time dense vs flash attention per length bucket on the current
    backend; bind the smallest length where flash wins (--auto-tune)."""
    global _calibrated_threshold
    from .attention import dense_attention
    from .pallas.flash_attention import flash_attention

    tuner = AutoTuner()
    crossover = None
    for t in lengths:
        q = jnp.ones((batch, heads, t, dim_head), jnp.bfloat16)
        mask = (jnp.tril(jnp.ones((t, t), jnp.bfloat16))[None, None]
                if causal else None)
        dense_j = jax.jit(lambda a, m: dense_attention(a, a, a, m))
        flash_j = jax.jit(lambda a: flash_attention(a, a, a, causal=causal))
        name = tuner.pick(("attn", t), {
            "dense": (dense_j, (q, mask)),
            "flash": (flash_j, (q,)),
        })
        if name == "flash" and crossover is None:
            crossover = t
    # No crossover measured → flash lost at every tested length; disable it
    # for 'auto' outright rather than extrapolating a win past the sweep.
    _calibrated_threshold = crossover if crossover is not None \
        else sys.maxsize
    return _calibrated_threshold
