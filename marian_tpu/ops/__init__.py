from .ops import (layer_norm, rms_norm, dropout, activation, affine,
                  masked_softmax, masked_log_softmax, cross_entropy,
                  global_norm, clip_by_global_norm, NEG_INF)
from .attention import (dense_attention, dense_attention_with_weights,
                        causal_mask, combine_masks)
