"""Int8 quantized inference — the TPU-native answer to the reference's CPU
intgemm/FBGEMM path (src/tensors/cpu/integer_common.h, cpu/fbgemm/;
SURVEY.md §2.4 "intgemm/FBGEMM int8 path → native TPU int8 matmuls").

Weights are quantized OFFLINE by marian-conv (symmetric per-channel int8:
q = round(w / s), s = amax|w| / 127); activations are quantized ON THE FLY
per token row (dynamic symmetric), and the matmul runs as an int8×int8 →
int32 ``lax.dot_general`` on the MXU, rescaled by (act_scale ⊗ weight_scale).
This is the AQT recipe (PAPERS.md) — int8 halves HBM weight traffic, which
is what bounds autoregressive decode.

A quantized parameter is a QTensor pytree leaf-pair (int8 values + f32
per-channel scales), so jitted model functions take quantized and float
checkpoints through the same code path.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """Symmetric per-channel int8 tensor: dequant = values * scale along
    `axis` (0 = per-row scales, e.g. vocab-indexed embeddings; 1 = per-column
    scales, e.g. [in, out] matmul weights)."""
    values: jax.Array          # int8
    scale: jax.Array           # f32, shape [values.shape[axis]]
    axis: int = 1

    @property
    def shape(self):
        return self.values.shape

    def tree_flatten(self):
        return (self.values, self.scale), self.axis

    @classmethod
    def tree_unflatten(cls, axis, children):
        return cls(children[0], children[1], axis)

    def dequantize(self, dtype=jnp.float32) -> jax.Array:
        s = self.scale.astype(dtype)
        if self.axis == 0:
            return self.values.astype(dtype) * s[:, None]
        return self.values.astype(dtype) * s[None, :]


def quantize(w, axis: int = 1) -> QTensor:
    """Symmetric per-channel int8 quantization (reference: intgemm's
    PrepareA/PrepareB quantization; marian-conv --gemm-type intgemm8)."""
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=1 - axis)
    scale = np.maximum(amax, 1e-8) / 127.0
    s = scale[:, None] if axis == 0 else scale[None, :]
    q = np.clip(np.rint(w / s), -127, 127).astype(np.int8)
    return QTensor(jnp.asarray(q), jnp.asarray(scale, jnp.float32), axis)


def _quantize_acts(x: jax.Array):
    """Dynamic per-row symmetric int8 activation quantization (the runtime
    half of the AQT recipe; reference: intgemm PrepareA at each GEMM)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127
                  ).astype(jnp.int8)
    return xq, s


def int8_affine(x: jax.Array, q: QTensor,
                b: Optional[jax.Array] = None) -> jax.Array:
    """x @ dequant(q) + b computed as int8×int8→int32 on the MXU.
    q is an [in, out] weight with per-out-channel scales (axis=1)."""
    assert q.axis == 1, "int8_affine expects per-output-channel scales"
    xq, xs = _quantize_acts(x)
    y = jax.lax.dot_general(xq, q.values, (((xq.ndim - 1,), (0,)), ((), ())),
                            preferred_element_type=jnp.int32)
    y = y.astype(jnp.float32) * xs * q.scale[None, :]
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(x.dtype)


def int8_logits(x: jax.Array, q: QTensor,
                shortlist: Optional[jax.Array] = None) -> jax.Array:
    """x @ dequant(q).T for a vocab-major table ([V, d], per-row scales) —
    the tied-embedding output projection with optional shortlist row slice
    (reference: mlp::Output with intgemm8 + Shortlist::indices)."""
    assert q.axis == 0, "int8_logits expects per-row (vocab) scales"
    vals, scale = q.values, q.scale
    if shortlist is not None:
        vals = vals[shortlist]
        scale = scale[shortlist]
    xq, xs = _quantize_acts(x)
    y = jax.lax.dot_general(xq, vals, (((xq.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * xs * scale[None, :]


def int8_gather(q: QTensor, ids: jax.Array, dtype) -> jax.Array:
    """Embedding lookup from a per-row-quantized [V, d] table."""
    assert q.axis == 0
    return (q.values[ids].astype(dtype)
            * q.scale[ids][..., None].astype(dtype))


# ---------------------------------------------------------------------------
# checkpoint plumbing (marian-conv output format)
# ---------------------------------------------------------------------------

QSCALE_SUFFIX = ":qscale"

# Param-name suffixes excluded from quantization: biases, layer norms,
# positional tables (tiny and precision-critical).
_SKIP_SUFFIXES = ("_ln_scale", "_ln_bias")


def quantizable(name: str, arr) -> bool:
    if getattr(arr, "ndim", 0) != 2 or arr.shape[0] < 2:
        return False
    if name.endswith(_SKIP_SUFFIXES) or name == "Wpos":
        return False
    # biases ([1, d]) were already rejected by the ndim/shape check above
    return np.issubdtype(np.asarray(arr).dtype, np.floating)


def quantize_params(params: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Quantize a float checkpoint's matmul weights for saving — embeddings
    ([V, d], also the tied output layer) per row, [in, out] weights per
    column (reference: marian-conv's intgemm8 model preparation)."""
    out: Dict[str, np.ndarray] = {}
    for name, arr in params.items():
        if not quantizable(name, arr):
            out[name] = np.asarray(arr)
            continue
        axis = 0 if name.endswith("Wemb") else 1
        q = quantize(arr, axis=axis)
        out[name] = np.asarray(q.values)
        out[name + QSCALE_SUFFIX] = np.asarray(q.scale)
    return out


def wrap_quantized(params: Dict[str, jax.Array]) -> Dict:
    """Pair `X` (int8) + `X:qscale` items loaded from a converted checkpoint
    back into QTensor leaves; float params pass through unchanged."""
    out: Dict = {}
    for name, arr in params.items():
        if name.endswith(QSCALE_SUFFIX):
            continue
        skey = name + QSCALE_SUFFIX
        if skey in params:
            # axis mirrors quantize_params: embeddings per-row, else per-col
            axis = 0 if name.endswith("Wemb") else 1
            out[name] = QTensor(jnp.asarray(arr, jnp.int8),
                                jnp.asarray(params[skey], jnp.float32), axis)
        else:
            out[name] = arr
    return out


def is_quantized(params: Dict) -> bool:
    return any(k.endswith(QSCALE_SUFFIX) for k in params)
